"""Accelerator behavior tests — behavioral port of the reference's DDP suite
(reference: ray_lightning/tests/test_ddp.py — actor count :29-42, sampler
:45-79, train :82-89, load :91-98, predict :100-116, early stop :118-134)."""

import jax
import numpy as np
import pytest

from ray_lightning_accelerators_tpu import (EarlyStopping,
                                            HorovodRayAccelerator,
                                            RayAccelerator, RayTPUAccelerator)
from ray_lightning_accelerators_tpu.parallel import mesh as mesh_lib

from .utils import (BlobsDataModule, BoringModel, LinearClassifier,
                    boring_loaders, get_trainer, load_test, predict_test,
                    train_test)


@pytest.mark.parametrize("num_workers", [1, 2])
def test_mesh_device_count(num_workers):
    """Analog of the live-actor-count assertion (reference test_ddp.py:29-42):
    the accelerator must engage exactly num_workers devices."""
    acc = RayTPUAccelerator(num_workers=num_workers)
    mesh = acc.build_mesh()
    assert mesh.devices.size == num_workers
    assert acc.world_size == num_workers


def test_horovod_topology():
    acc = HorovodRayAccelerator(num_hosts=2, num_slots=4)
    assert acc.world_size == 8
    assert acc.build_mesh().devices.size == 8


def test_too_many_workers_raises():
    with pytest.raises(ValueError):
        RayTPUAccelerator(num_workers=64).build_mesh()


@pytest.mark.parametrize("num_workers", [1, 2, 8])
def test_train(tmpdir, num_workers):
    train_test(get_trainer(tmpdir, RayTPUAccelerator(num_workers)),
               BoringModel())


def test_train_parity_alias(tmpdir):
    """RayAccelerator keeps its reference signature
    (reference: ray_ddp.py:79-90)."""
    acc = RayAccelerator(num_workers=2, num_cpus_per_worker=1, use_gpu=False)
    train_test(get_trainer(tmpdir, acc), BoringModel())


def test_train_horovod_shape(tmpdir):
    acc = HorovodRayAccelerator(num_hosts=2, num_slots=2)
    train_test(get_trainer(tmpdir, acc), BoringModel())


@pytest.mark.parametrize("num_workers", [1, 2])
def test_load(tmpdir, num_workers):
    load_test(get_trainer(tmpdir, RayTPUAccelerator(num_workers)),
              BoringModel())


@pytest.mark.parametrize("num_workers", [1, 2])
def test_predict(tmpdir, num_workers):
    dm = BlobsDataModule(batch_size=16)
    trainer = get_trainer(tmpdir, RayTPUAccelerator(num_workers),
                          max_epochs=10, limit_train_batches=None,
                          limit_val_batches=None)
    predict_test(trainer, LinearClassifier(), dm)


def test_early_stop(tmpdir):
    """Constant val_loss must stop after patience validations
    (reference: test_ddp.py:118-134)."""
    patience = 2
    model = BoringModel()
    trainer = get_trainer(
        tmpdir, RayTPUAccelerator(2), max_epochs=500,
        callbacks=[EarlyStopping(monitor="val_loss", patience=patience)])
    train, val = boring_loaders()
    trainer.fit(model, train, val)
    assert trainer.should_stop
    assert trainer.current_epoch < 500
    # one improvement round + `patience` non-improving rounds
    assert model.val_epoch == patience + 1


def test_sampler_injection(tmpdir):
    """Sampler config parity (reference: test_ddp.py:45-79): shuffle on for
    train / off for val, replicas == process count, rank == process index."""
    trainer = get_trainer(tmpdir, RayTPUAccelerator(2))
    train, val = boring_loaders()
    trainer.fit(BoringModel(), train, val)
    assert train.sampler.shuffle is True
    assert val.sampler.shuffle is False
    for s in (train.sampler, val.sampler):
        assert s.num_replicas == jax.process_count()
        assert s.rank == jax.process_index()


def test_batch_divisibility_check(tmpdir):
    trainer = get_trainer(tmpdir, RayTPUAccelerator(8))
    train, val = boring_loaders(batch_size=6)  # 6 % 8 != 0
    with pytest.raises(ValueError, match="not divisible"):
        trainer.fit(BoringModel(), train, val)


def test_fsdp_state_is_sharded(tmpdir):
    """use_fsdp must actually shard large params over the fsdp axis."""
    class WideModel(BoringModel):
        def init_params(self, rng):
            return {"layer": {
                "kernel": jax.random.normal(rng, (256, 256)) * 0.05,
                "bias": jax.numpy.zeros((256,))}}

        def forward(self, params, x):
            pad = jax.numpy.zeros((x.shape[0], 224))
            x = jax.numpy.concatenate([x, pad], -1)
            return x @ params["layer"]["kernel"] + params["layer"]["bias"]

        def training_step(self, params, batch, rng):
            out = self.forward(params, batch)
            return jax.numpy.mean((out - 1.0) ** 2)

        def validation_step(self, params, batch):
            return {"val_loss": jax.numpy.asarray(1.0)}

    acc = RayTPUAccelerator(8, use_fsdp=True)
    trainer = get_trainer(tmpdir, acc)
    train, val = boring_loaders(batch_size=8)
    trainer.fit(WideModel(), train, val)
    kernel = trainer._state.params["layer"]["kernel"]
    assert not kernel.sharding.is_fully_replicated
    assert len(kernel.sharding.device_set) == 8


def test_fit_twice_and_test(tmpdir):
    """fit/test callable repeatedly from one script — the notebook-safety
    capability the reference advertises (reference: README.md:34-36)."""
    model = BoringModel()
    trainer = get_trainer(tmpdir, RayTPUAccelerator(2))
    train, val = boring_loaders()
    trainer.fit(model, train, val)
    first = dict(trainer.callback_metrics)
    results = trainer.test(model, val)
    assert "y" in results[0]
    trainer2 = get_trainer(tmpdir, RayTPUAccelerator(2), max_epochs=2)
    trainer2.fit(model, train, val)
    assert trainer2.current_epoch == 2
    assert first  # first run's metrics were materialized


def test_mesh_config_inference():
    cfg = mesh_lib.MeshConfig(data=-1, tensor=2)
    sizes = cfg.axis_sizes(8)
    assert sizes[mesh_lib.DATA_AXIS] == 4
    with pytest.raises(ValueError):
        mesh_lib.MeshConfig(data=3, tensor=2).axis_sizes(8)


def test_fsdp_with_grad_accum_shards_moments(tmpdir):
    """optax.MultiSteps must not silently break optimizer-state sharding
    (tree_map_params path through the wrapper)."""
    from ray_lightning_accelerators_tpu import Trainer

    class WideModel(BoringModel):
        def init_params(self, rng):
            return {"k": jax.random.normal(rng, (256, 256)) * 0.05}

        def forward(self, params, x):
            pad = jax.numpy.zeros((x.shape[0], 224))
            return jax.numpy.concatenate([x, pad], -1) @ params["k"]

        def training_step(self, params, batch, rng):
            return jax.numpy.mean((self.forward(params, batch) - 1.0) ** 2)

        def validation_step(self, params, batch):
            return {"val_loss": jax.numpy.asarray(1.0)}

    trainer = Trainer(default_root_dir=str(tmpdir), max_epochs=1,
                      accelerator=RayTPUAccelerator(8, use_fsdp=True),
                      accumulate_grad_batches=2, precision="f32", seed=0,
                      enable_checkpointing=False)
    train, val = boring_loaders(batch_size=8)
    trainer.fit(WideModel(), train, val)
    moments = [l for l in jax.tree.leaves(trainer._state.opt_state)
               if hasattr(l, "shape") and l.shape == (256, 256)]
    assert moments, "no param-shaped optimizer moments found"
    assert all(not m.sharding.is_fully_replicated for m in moments), \
        "optimizer moments replicated -- FSDP memory savings lost"
