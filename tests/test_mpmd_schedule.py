"""Unit tests for the MPMD pipeline tick programs (parallel/mpmd/schedule.py):
pure schedule math, no processes, no jax arrays."""

import pytest

from ray_lightning_accelerators_tpu.parallel.mpmd import schedule as sched
from ray_lightning_accelerators_tpu.parallel.mpmd.schedule import (
    OP_BWD, OP_FWD, OP_OPT, OP_RECV_ACT, OP_RECV_GRAD, OP_SEND_ACT,
    OP_SEND_GRAD, PipelineScheduleError, Slot, analytic_bubble_fraction,
    audit_programs, build_programs, program_fingerprint, stage_program)


def _ops(program):
    return [s.op for s in program]


def _compute_slots(program, op):
    return [s.microbatch for s in program if s.op == op]


class TestStageProgram:
    def test_first_stage_1f1b_two_stages(self):
        prog = stage_program("1f1b", 0, 2, 4)
        # warmup of S-1-stage=1 fwd, then steady 1F1B, drain, opt
        assert _compute_slots(prog, OP_FWD) == [0, 1, 2, 3]
        assert _compute_slots(prog, OP_BWD) == [0, 1, 2, 3]
        assert prog[-1] == Slot(OP_OPT, -1)
        # stage 0 sends every activation and receives every gradient
        assert _compute_slots(prog, OP_SEND_ACT) == [0, 1, 2, 3]
        assert _compute_slots(prog, OP_RECV_GRAD) == [0, 1, 2, 3]
        assert OP_RECV_ACT not in _ops(prog)
        assert OP_SEND_GRAD not in _ops(prog)

    def test_last_stage_interleaves_immediately(self):
        prog = stage_program("1f1b", 1, 2, 4)
        # last stage has zero warmup: fwd0 then bwd0 right away
        compute = [s for s in prog if s.op in (OP_FWD, OP_BWD)]
        assert [(s.op, s.microbatch) for s in compute[:4]] == [
            (OP_FWD, 0), (OP_BWD, 0), (OP_FWD, 1), (OP_BWD, 1)]
        assert OP_SEND_ACT not in _ops(prog)
        assert OP_RECV_GRAD not in _ops(prog)

    def test_gpipe_runs_all_forwards_first(self):
        prog = stage_program("gpipe", 0, 2, 4)
        ops = [s.op for s in prog if s.op in (OP_FWD, OP_BWD)]
        assert ops == [OP_FWD] * 4 + [OP_BWD] * 4

    def test_1f1b_warmup_depth_scales_with_distance_to_last(self):
        # stage 0 of 4 stages: warmup = S-1-stage = 3 forwards
        prog = stage_program("1f1b", 0, 4, 8)
        ops = [s.op for s in prog if s.op in (OP_FWD, OP_BWD)]
        # 3 warmup forwards, then strict one-forward-one-backward pairs
        assert ops[:3] == [OP_FWD] * 3
        assert ops[3:7] == [OP_FWD, OP_BWD, OP_FWD, OP_BWD]

    def test_every_stage_ends_with_opt(self):
        for sch in sched.SCHEDULES:
            for stage in range(3):
                prog = stage_program(sch, stage, 3, 6)
                assert prog[-1] == Slot(OP_OPT, -1)

    def test_unknown_schedule_refused(self):
        with pytest.raises(PipelineScheduleError, match="schedule"):
            stage_program("interleaved", 0, 2, 4)

    def test_bad_shape_refused(self):
        with pytest.raises(PipelineScheduleError):
            stage_program("1f1b", 2, 2, 4)  # stage out of range
        with pytest.raises(PipelineScheduleError):
            stage_program("1f1b", 0, 2, 0)  # no microbatches


class TestAuditAndFingerprint:
    def test_build_programs_audits_clean(self):
        for sch in sched.SCHEDULES:
            progs = build_programs(sch, 4, 8)
            assert audit_programs(progs) is None

    def test_audit_flags_deadlock(self):
        progs = list(build_programs("1f1b", 2, 4))
        # corrupt stage 1: its first recv waits for a microbatch no one
        # ever sends -> stage 1 blocks at slot 0, stage 0 starves on grads
        bad = [Slot(OP_RECV_ACT, 7) if s == Slot(OP_RECV_ACT, 0) else s
               for s in progs[1]]
        progs[1] = bad
        diag = audit_programs(progs)
        assert diag is not None
        assert diag["deadlocked_stages"] == [0, 1]
        blocked = diag["per_stage"]["1"]
        assert blocked["op"] == OP_RECV_ACT
        assert blocked["waiting_for"] == ("act", 0, 7)

    def test_fingerprint_deterministic_and_distinct(self):
        a = program_fingerprint(stage_program("1f1b", 0, 2, 4))
        b = program_fingerprint(stage_program("1f1b", 0, 2, 4))
        c = program_fingerprint(stage_program("gpipe", 0, 2, 4))
        assert a == b
        assert a != c


class TestBubbleMath:
    def test_analytic_fraction(self):
        assert analytic_bubble_fraction(1, 4) == 0.0
        assert analytic_bubble_fraction(2, 4) == pytest.approx(1 / 5)
        assert analytic_bubble_fraction(4, 8) == pytest.approx(3 / 11)

    def test_more_microbatches_shrink_the_bubble(self):
        fracs = [analytic_bubble_fraction(4, m) for m in (4, 8, 16, 64)]
        assert fracs == sorted(fracs, reverse=True)
