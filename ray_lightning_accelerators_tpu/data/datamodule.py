"""DataModule: bundles train/val/test loaders (LightningDataModule analog,
as consumed by the reference examples via plain DataLoaders,
reference: examples/ray_ddp_example.py:44-59)."""

from __future__ import annotations

from typing import Optional

from .loader import DataLoader


class DataModule:
    def setup(self, stage: str) -> None:
        pass

    def train_dataloader(self) -> Optional[DataLoader]:
        return None

    def val_dataloader(self) -> Optional[DataLoader]:
        return None

    def test_dataloader(self) -> Optional[DataLoader]:
        return None

    def predict_dataloader(self) -> Optional[DataLoader]:
        return self.test_dataloader()
