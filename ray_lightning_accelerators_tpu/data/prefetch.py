"""Async input pipeline: background host prefetch + double-buffered
device placement.

The device-resident dataset cache (core/trainer.py:_build_device_cache)
answers SURVEY.md §7.4 only for array-backed datasets under the HBM
budget with the default collate.  Everything else — StreamingLMDataset,
big vision sets, custom collates, all of eval/predict — runs a fully
synchronous hot loop: collate on host, blocking device placement, then
dispatch, so the accelerator idles through every host/H2D phase.  veScale
(PAPERS.md) makes the same point for eager-style SPMD: the device queue
must never drain.

Two composable stages fix that without changing a single batch:

- :class:`PrefetchIterator` — pulls the wrapped iterator (dataset
  iteration + collate, i.e. the host-latency part) on ONE background
  thread into a bounded depth-N queue.  A single producer and a FIFO
  queue keep the order exactly the source's order; shutdown is explicit
  (``close()`` stops and joins the thread — no leaked threads, enforced
  suite-wide by a conftest guard) and a producer-side exception is
  re-raised on the consumer with its original type and traceback, at
  the position in the stream where it occurred.
- :class:`DevicePrefetcher` — keeps up to N *device-placed* batches in
  flight ahead of the consumer.  Placement runs on the CONSUMER thread
  in stream order (``jax.device_put`` / ``make_array_from_
  process_local_data`` are async dispatches: they return immediately
  while the transfer proceeds), which multi-process placement requires —
  every process must issue the same placements in the same sequence.
  Step k's dispatch therefore never waits on batch k's H2D transfer:
  that transfer was issued while step k-1 (or earlier) computed.

``prefetch_pipeline`` composes the two; the Trainer wires it through
fit/eval/predict behind ``Trainer(prefetch_batches=N)``.

Profiler accounting (utils/profiler.py): per-step ``h2d_wait`` span
(time the consumer waited for its next placed batch), a
``prefetch_depth`` queue-depth gauge, and a ``prefetch_starved_steps``
counter — steps that found the pipeline empty.  A starved run is
input-bound: deeper prefetch or cheaper collate, not a faster model,
is the lever.
"""

from __future__ import annotations

import collections
import queue
import threading
import time
from typing import Any, Callable, Iterable, Iterator, Optional

from ..telemetry import recorder as telemetry
from ..utils.logging import log

# producer stop-check cadence while blocked on a full queue: close()
# latency is bounded by ~2 polls
_PUT_POLL_S = 0.05
# consumer poll while blocked on an empty queue: each timeout re-checks
# that the producer thread is still alive (a silently-dead producer must
# not hang the consumer forever)
_GET_POLL_S = 0.5

# queue records: ("item", payload) | ("raise", exc) | ("end", None)
_ITEM, _RAISE, _END = "item", "raise", "end"


class PrefetchClosed(RuntimeError):
    """Iteration attempted on a pipeline after ``close()``."""


class PrefetchIterator:
    """Iterate ``source`` on a background thread into a bounded queue.

    Deterministic: one producer thread + one FIFO queue reproduce the
    source's order exactly.  ``depth`` bounds host memory (at most
    ``depth`` batches buffered) and bounds how far a stateful source
    (e.g. a round-robin-sharded stream) runs ahead of consumption.

    Exceptions raised by the source surface on the consumer at the
    failing element's position in the stream, with their original type
    and traceback.  ``close()`` (idempotent, also the context-manager
    exit) stops and joins the thread; iteration past ``close()`` raises
    :class:`PrefetchClosed`.
    """

    def __init__(self, source: Iterable[Any], depth: int,
                 profiler=None, fetch_metric: str = "data_fetch",
                 name: str = "rla-prefetch"):
        if depth < 1:
            raise ValueError(f"prefetch depth must be >= 1, got {depth}")
        self._source = iter(source)
        self._profiler = profiler
        self._fetch_metric = fetch_metric
        self._queue: "queue.Queue" = queue.Queue(maxsize=depth)
        self._stop = threading.Event()
        self._closed = False
        self._finished = False
        # NON-daemon on purpose: a leaked producer is a bug (the conftest
        # guard fails the test); every exit path must close() this
        self._thread = threading.Thread(target=self._produce, name=name,
                                        daemon=False)
        self._thread.start()

    # -- producer ------------------------------------------------------ #
    def _put(self, record) -> bool:
        """Stop-aware blocking put; False when close() interrupted it."""
        while not self._stop.is_set():
            try:
                self._queue.put(record, timeout=_PUT_POLL_S)
                return True
            except queue.Full:
                continue
        return False

    def _produce(self) -> None:
        try:
            while not self._stop.is_set():
                t0 = time.perf_counter()
                try:
                    item = next(self._source)
                except StopIteration:
                    break
                if self._profiler is not None:
                    self._profiler.observe(self._fetch_metric,
                                           time.perf_counter() - t0)
                if not self._put((_ITEM, item)):
                    return
            if not self._stop.is_set():
                self._put((_END, None))
        except BaseException as e:  # noqa: BLE001 - carried to consumer
            self._put((_RAISE, e))

    # -- consumer ------------------------------------------------------ #
    def __iter__(self) -> Iterator[Any]:
        return self

    def __next__(self) -> Any:
        if self._closed:
            raise PrefetchClosed("prefetch iterator used after close()")
        if self._finished:
            raise StopIteration
        while True:
            try:
                kind, payload = self._queue.get(timeout=_GET_POLL_S)
                break
            except queue.Empty:
                if not self._thread.is_alive():
                    # one last non-blocking drain: the producer may have
                    # put its final record between the timeout and the
                    # liveness check
                    try:
                        kind, payload = self._queue.get_nowait()
                        break
                    except queue.Empty:
                        self._finished = True
                        raise RuntimeError(
                            "prefetch producer thread died without a "
                            "final record") from None
        if kind == _ITEM:
            return payload
        self._finished = True
        self._thread.join()
        if kind == _END:
            raise StopIteration
        raise payload  # original exception object: type + traceback kept

    def qsize(self) -> int:
        """Batches currently buffered (ready without blocking)."""
        return self._queue.qsize()

    # -- lifecycle ----------------------------------------------------- #
    def close(self, timeout: float = 5.0) -> None:
        """Stop and join the producer.  Idempotent; safe mid-iteration
        (the early-exit paths — limit_train_batches, max_steps,
        max_time, exceptions — all land here via ``finally``)."""
        if self._closed:
            return
        self._closed = True
        self._stop.set()
        deadline = time.monotonic() + timeout
        while self._thread.is_alive() and time.monotonic() < deadline:
            try:  # unblock a producer stuck in put() on a full queue
                self._queue.get_nowait()
            except queue.Empty:
                pass
            self._thread.join(timeout=_PUT_POLL_S)
        if self._thread.is_alive():  # pragma: no cover - defensive
            log.warning("prefetch producer %s did not stop within %.1fs",
                        self._thread.name, timeout)

    def __enter__(self) -> "PrefetchIterator":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class DevicePrefetcher:
    """Keep up to ``depth`` device-placed batches in flight ahead of the
    consumer (the double-buffer generalized to depth N).

    Each ``__next__`` (1) blocks — timed as ``h2d_wait`` — only if no
    placed batch is ready, (2) tops the ring back up by placing every
    batch the host stage already has waiting (placement is an async
    dispatch; the transfers overlap the consumer's compute), and
    (3) returns the oldest placed batch.  Errors from the source or from
    ``place_fn`` are stashed and re-raised exactly at their position in
    the stream, so batches before a failure are still consumed and the
    trainer's ``global_step`` stays consistent.
    """

    def __init__(self, inner, depth: int,
                 place_fn: Optional[Callable[[Any], Any]] = None,
                 profiler=None,
                 wait_metric: str = "h2d_wait",
                 depth_gauge: str = "prefetch_depth",
                 starve_counter: str = "prefetch_starved_steps"):
        if depth < 1:
            raise ValueError(f"prefetch depth must be >= 1, got {depth}")
        self._inner = inner
        self._iter = iter(inner)
        self._depth = depth
        self._place = place_fn
        self._profiler = profiler
        self._wait_metric = wait_metric
        self._depth_gauge = depth_gauge
        self._starve_counter = starve_counter
        self._ring: collections.deque = collections.deque()
        self._exhausted = False
        self._pending_exc: Optional[BaseException] = None
        self._started = False

    def _advance(self) -> bool:
        """Pull + place ONE batch into the ring.  Termination and errors
        are stashed (not raised) so they surface in stream order."""
        if self._exhausted or self._pending_exc is not None:
            return False
        try:
            item = next(self._iter)
            self._ring.append(item if self._place is None
                              else self._place(item))
            return True
        except StopIteration:
            self._exhausted = True
        except BaseException as e:  # noqa: BLE001 - surfaced in order
            self._pending_exc = e
        return False

    def _ready(self) -> bool:
        """Does the host stage have a batch waiting (no blocking)?"""
        qsize = getattr(self._inner, "qsize", None)
        return qsize is not None and qsize() > 0

    def placed_bytes(self) -> int:
        """Logical bytes of the device-placed batches currently in
        flight — the perf observatory's ``prefetch`` HBM pool reader
        (shape metadata only, never a sync; non-array ring leaves count
        zero)."""
        from ..telemetry.perf import tree_nbytes
        return tree_nbytes(list(self._ring))

    def __iter__(self) -> Iterator[Any]:
        return self

    def __next__(self) -> Any:
        prof = self._profiler
        t0 = time.perf_counter()
        # the first batch of a stream inevitably waits (nothing was in
        # flight yet) — that's warmup, not starvation
        starved = self._started and not self._ring
        if not self._ring:
            self._advance()  # blocking pull
        wait = time.perf_counter() - t0
        # top up: issue placements for everything already collated, up to
        # depth — these H2D transfers run while the consumer computes
        while len(self._ring) < self._depth and self._ready():
            if not self._advance():
                break
        if self._ring:
            if prof is not None:
                prof.observe(self._wait_metric, wait)
                if starved:
                    prof.incr(self._starve_counter)
                # buffer remaining AFTER this batch is taken: 0 here means
                # the next step is at risk of starving too
                prof.gauge(self._depth_gauge,
                           len(self._ring) - 1 + (self._inner.qsize()
                                                  if hasattr(self._inner,
                                                             "qsize")
                                                  else 0))
            if starved:
                # flight-recorder breadcrumb: the counter says HOW OFTEN
                # the run starved, the event says WHEN in the timeline
                telemetry.emit("prefetch_starved",
                               wait_ms=round(wait * 1e3, 3))
            self._started = True
            return self._ring.popleft()
        if self._pending_exc is not None:
            exc, self._pending_exc = self._pending_exc, None
            raise exc
        raise StopIteration

    def close(self, timeout: float = 5.0) -> None:
        if isinstance(self._inner, PrefetchIterator):
            self._inner.close(timeout=timeout)
        else:
            # plain iterators (generators) take no timeout; a bare
            # iterable may have no close() at all
            close = getattr(self._inner, "close", None)
            if close is not None:
                close()
        self._ring.clear()

    def __enter__(self) -> "DevicePrefetcher":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def prefetch_pipeline(source: Iterable[Any], depth: int,
                      place_fn: Optional[Callable[[Any], Any]] = None,
                      profiler=None,
                      name: str = "rla-prefetch") -> DevicePrefetcher:
    """The full async input pipeline: host iteration + collate on a
    background thread (:class:`PrefetchIterator`), device placement
    double-buffered ``depth`` ahead (:class:`DevicePrefetcher`).
    ``close()`` on the returned object stops and joins the thread."""
    host = PrefetchIterator(source, depth, profiler=profiler, name=name)
    return DevicePrefetcher(host, depth, place_fn, profiler=profiler)
