"""Real vision-dataset ingestion: MNIST IDX and CIFAR-10 binary parsers.

The reference trains and gates on actual MNIST downloaded by torchvision
(reference: examples/ray_ddp_example.py:37-42 -- ``MNISTDataModule`` with a
FileLock'd download; ray_lightning/tests/utils.py:137-152 -- accuracy >= 0.5
on the real test split).  This environment has no dataset egress, so the
framework parses the standard on-disk formats DIRECTLY when files are
present locally and falls back to shape-identical synthetic data otherwise
(models/mnist.py, models/resnet.py).  No torchvision, no downloads -- a
user mounts the files and every datamodule picks them up.

Formats:

- **MNIST IDX** (yann.lecun.com layout): big-endian magic 0x00000803
  (images, [n, 28, 28] u8) / 0x00000801 (labels, [n] u8), optionally
  ``.gz``-compressed.  Standard names: ``train-images-idx3-ubyte``,
  ``train-labels-idx1-ubyte``, ``t10k-images-idx3-ubyte``,
  ``t10k-labels-idx1-ubyte`` (also the ``.idx3-ubyte`` dotted variants).
- **CIFAR-10 binary** (cs.toronto.edu layout): ``data_batch_{1..5}.bin`` +
  ``test_batch.bin``, 3073-byte records (1 label byte + 3072 RGB bytes,
  channel-major 32x32), possibly under a ``cifar-10-batches-bin/`` subdir.

Both loaders return float32 images scaled to [0, 1] (NHWC for CIFAR) and
int32 labels -- the exact dtypes the models' forward paths expect.
"""

from __future__ import annotations

import gzip
import os
import struct
from typing import Optional, Tuple

import numpy as np

Arrays = Tuple[np.ndarray, np.ndarray]

_IDX_IMAGES_MAGIC = 0x00000803
_IDX_LABELS_MAGIC = 0x00000801


def _open_maybe_gz(path: str):
    if path.endswith(".gz"):
        return gzip.open(path, "rb")
    return open(path, "rb")


def _find(data_dir: str, stem: str) -> Optional[str]:
    """Locate ``stem`` under data_dir, tolerating the dotted IDX naming and
    gzip: train-images-idx3-ubyte / train-images.idx3-ubyte / +.gz."""
    candidates = [stem, stem.replace("-idx", ".idx")]
    candidates += [c + ".gz" for c in candidates]
    for sub in ("", "MNIST/raw"):
        for c in candidates:
            p = os.path.join(data_dir, sub, c)
            if os.path.exists(p):
                return p
    return None


def read_idx_images(path: str) -> np.ndarray:
    """Parse an IDX3 image file -> float32 [n, rows, cols] in [0, 1]."""
    with _open_maybe_gz(path) as f:
        magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
        if magic != _IDX_IMAGES_MAGIC:
            raise ValueError(
                f"{path}: bad IDX image magic 0x{magic:08x} "
                f"(want 0x{_IDX_IMAGES_MAGIC:08x})")
        buf = f.read(n * rows * cols)
    if len(buf) != n * rows * cols:
        raise ValueError(f"{path}: truncated ({len(buf)} bytes for "
                         f"{n}x{rows}x{cols})")
    x = np.frombuffer(buf, dtype=np.uint8).reshape(n, rows, cols)
    return x.astype(np.float32) / 255.0


def read_idx_labels(path: str) -> np.ndarray:
    """Parse an IDX1 label file -> int32 [n]."""
    with _open_maybe_gz(path) as f:
        magic, n = struct.unpack(">II", f.read(8))
        if magic != _IDX_LABELS_MAGIC:
            raise ValueError(
                f"{path}: bad IDX label magic 0x{magic:08x} "
                f"(want 0x{_IDX_LABELS_MAGIC:08x})")
        buf = f.read(n)
    if len(buf) != n:
        raise ValueError(f"{path}: truncated ({len(buf)} bytes for {n})")
    return np.frombuffer(buf, dtype=np.uint8).astype(np.int32)


def load_mnist(data_dir: str, split: str = "train") -> Optional[Arrays]:
    """(images [n,28,28] f32, labels [n] i32) or None when files absent.
    ``split``: "train" or "test" (the t10k files)."""
    stem = "train" if split == "train" else "t10k"
    xp = _find(data_dir, f"{stem}-images-idx3-ubyte")
    yp = _find(data_dir, f"{stem}-labels-idx1-ubyte")
    if xp is None or yp is None:
        return None
    x, y = read_idx_images(xp), read_idx_labels(yp)
    if len(x) != len(y):
        raise ValueError(f"MNIST {split}: {len(x)} images vs {len(y)} labels")
    return x, y


# --------------------------------------------------------------------- #
# CIFAR-10 binary                                                        #
# --------------------------------------------------------------------- #
_CIFAR_RECORD = 1 + 32 * 32 * 3


def read_cifar_batch(path: str) -> Arrays:
    """One CIFAR-10 .bin batch -> (f32 NHWC [n,32,32,3] in [0,1], i32 [n])."""
    raw = np.fromfile(path, dtype=np.uint8)
    if raw.size % _CIFAR_RECORD:
        raise ValueError(f"{path}: size {raw.size} is not a multiple of the "
                         f"{_CIFAR_RECORD}-byte CIFAR-10 record")
    rec = raw.reshape(-1, _CIFAR_RECORD)
    y = rec[:, 0].astype(np.int32)
    # stored channel-major [3, 32, 32]; the models are NHWC end-to-end
    x = rec[:, 1:].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
    return np.ascontiguousarray(x).astype(np.float32) / 255.0, y


def _cifar_dir(data_dir: str) -> Optional[str]:
    for sub in ("", "cifar-10-batches-bin"):
        d = os.path.join(data_dir, sub)
        if os.path.exists(os.path.join(d, "data_batch_1.bin")):
            return d
    return None


def load_cifar10(data_dir: str, split: str = "train") -> Optional[Arrays]:
    """(images NHWC f32, labels i32) or None when the binaries are absent."""
    d = _cifar_dir(data_dir)
    if d is None:
        return None
    if split == "train":
        parts = [read_cifar_batch(os.path.join(d, f"data_batch_{i}.bin"))
                 for i in range(1, 6)
                 if os.path.exists(os.path.join(d, f"data_batch_{i}.bin"))]
        if not parts:
            return None
        xs, ys = zip(*parts)
        return np.concatenate(xs), np.concatenate(ys)
    test = os.path.join(d, "test_batch.bin")
    if not os.path.exists(test):
        return None
    return read_cifar_batch(test)
