"""Language-model data pipeline: tokenization + sequence packing.

No reference analog (the reference's data story stops at MNIST tensors,
reference: examples/ray_ddp_example.py:40-59); an LM flagship needs the
text path.  TPU-first constraints drive the design: the train step is
compiled for ONE static [batch, seq_len] shape, so variable-length
documents must be **packed** into fixed-length rows host-side — padding
minimized up front rather than masked per step — and the packed corpus is
a single int32 array that drops straight into ``ArrayDataset`` (and thus
the device-resident cache fast path, core/trainer.py).
"""

from __future__ import annotations

from typing import Callable, Dict, Iterable, Iterator, List, Optional, \
    Sequence

import numpy as np

from .loader import ArrayDataset, IterableDataset


class CharTokenizer:
    """Character-level tokenizer with a corpus-derived vocabulary.

    Deterministic (sorted vocab), dependency-free, reversible.  Reserves
    id 0 for padding and id 1 for end-of-text.
    """

    PAD_ID = 0
    EOS_ID = 1

    def __init__(self, corpus: str):
        chars = sorted(set(corpus))
        self._to_id: Dict[str, int] = {c: i + 2 for i, c in enumerate(chars)}
        self._to_char: Dict[int, str] = {i: c for c, i in self._to_id.items()}

    @property
    def vocab_size(self) -> int:
        return len(self._to_id) + 2

    def encode(self, text: str) -> List[int]:
        try:
            return [self._to_id[c] for c in text]
        except KeyError as e:
            raise ValueError(f"character {e.args[0]!r} not in vocabulary")

    def decode(self, ids: Sequence[int]) -> str:
        return "".join(self._to_char.get(int(i), "") for i in ids)


class BPETokenizer:
    """Byte-level byte-pair encoding, trained on a corpus; no external
    dependencies.

    Ids 0/1 reserved for pad/eos (shared convention with CharTokenizer);
    base ids 2..257 are the 256 byte values; merges extend upward.  Any
    input text round-trips exactly (byte fallback), unlike CharTokenizer
    which rejects unseen characters.
    """

    PAD_ID = 0
    EOS_ID = 1
    _BASE = 2

    def __init__(self, corpus: str, vocab_size: int = 512):
        if vocab_size < self._BASE + 256:
            raise ValueError(f"vocab_size must be >= {self._BASE + 256}")
        self.merges: Dict[tuple, int] = {}  # (id, id) -> merged id
        data = list(corpus.encode("utf-8"))
        ids = [b + self._BASE for b in data]
        next_id = self._BASE + 256
        while next_id < vocab_size:
            counts: Dict[tuple, int] = {}
            for a, b in zip(ids, ids[1:]):
                counts[(a, b)] = counts.get((a, b), 0) + 1
            if not counts:
                break
            pair = max(counts, key=counts.get)
            if counts[pair] < 2:
                break  # nothing left worth merging
            self.merges[pair] = next_id
            ids = self._merge(ids, pair, next_id)
            next_id += 1
        self.vocab_size = vocab_size
        # decode table: id -> bytes
        self._bytes: Dict[int, bytes] = {
            b + self._BASE: bytes([b]) for b in range(256)}
        for (a, b), m in self.merges.items():
            self._bytes[m] = self._bytes[a] + self._bytes[b]

    @staticmethod
    def _merge(ids: List[int], pair: tuple, new_id: int) -> List[int]:
        out, i = [], 0
        while i < len(ids):
            if i + 1 < len(ids) and (ids[i], ids[i + 1]) == pair:
                out.append(new_id)
                i += 2
            else:
                out.append(ids[i])
                i += 1
        return out

    def encode(self, text: str) -> List[int]:
        ids = [b + self._BASE for b in text.encode("utf-8")]
        # apply merges in training order (ranks): repeatedly merge the
        # lowest-rank pair present
        while len(ids) >= 2:
            ranked = [(self.merges[p], p) for p in zip(ids, ids[1:])
                      if p in self.merges]
            if not ranked:
                break
            _, pair = min(ranked)
            ids = self._merge(ids, pair, self.merges[pair])
        return ids

    def decode(self, ids: Sequence[int]) -> str:
        chunks = [self._bytes.get(int(i), b"") for i in ids]
        return b"".join(chunks).decode("utf-8", errors="replace")


def pack_sequences(docs: Sequence[Sequence[int]], seq_len: int,
                   eos_id: Optional[int] = CharTokenizer.EOS_ID,
                   drop_remainder: bool = True,
                   pad_id: int = CharTokenizer.PAD_ID) -> np.ndarray:
    """Concatenate token documents (with an ``eos_id`` separator after each
    unless None) and slice into fixed [N, seq_len] rows.

    ``drop_remainder=False`` pads the final partial row with ``pad_id``
    (mask pad targets downstream; ops/losses.py treats negative targets as
    masked, so shift-pad accordingly).  Packing wastes no tokens on
    per-document padding — the standard LM pretraining layout and the only
    one that keeps every MXU row busy.
    """
    stream: List[int] = []
    for d in docs:
        stream.extend(int(t) for t in d)
        if eos_id is not None:
            stream.append(eos_id)
    n_full = len(stream) // seq_len
    if drop_remainder or len(stream) % seq_len == 0:
        arr = np.asarray(stream[:n_full * seq_len], np.int32)
        return arr.reshape(n_full, seq_len)
    pad = (n_full + 1) * seq_len - len(stream)
    arr = np.asarray(stream + [pad_id] * pad, np.int32)
    return arr.reshape(n_full + 1, seq_len)


def lm_dataset(text: str, seq_len: int,
               tokenizer: Optional[CharTokenizer] = None):
    """(ArrayDataset of packed [N, seq_len] rows, tokenizer) for a corpus.

    Documents are split on blank lines; each gets an EOS separator.
    """
    tokenizer = tokenizer or CharTokenizer(text)
    docs = [tokenizer.encode(d) for d in text.split("\n\n") if d]
    packed = pack_sequences(docs, seq_len)
    if not len(packed):
        raise ValueError(
            f"corpus too small for even one row of seq_len={seq_len}")
    return ArrayDataset(packed), tokenizer


def pack_stream(docs: Iterable[Sequence[int]], seq_len: int,
                eos_id: Optional[int] = CharTokenizer.EOS_ID
                ) -> Iterator[np.ndarray]:
    """Streaming packer: yields [seq_len] int32 rows as tokens arrive,
    holding at most one partial row — O(seq_len) memory even when a single
    document is itself huge (tokens drain into rows chunk by chunk rather
    than absorbing the whole document first).  The trailing remainder is
    dropped, as in pack_sequences(drop_remainder=True)."""
    buf: List[int] = []

    def drain(tokens) -> Iterator[np.ndarray]:
        for t in tokens:
            buf.append(int(t))
            if len(buf) == seq_len:
                yield np.asarray(buf, np.int32)
                buf.clear()

    for d in docs:
        yield from drain(d)
        if eos_id is not None:
            yield from drain((eos_id,))


class StreamingLMDataset(IterableDataset):
    """Pack an unbounded document stream into fixed rows on the fly.

    ``doc_factory`` is called once per epoch (with the epoch number) and
    must return an iterable of token sequences — e.g. a generator reading
    shards off disk.  Memory stays O(seq_len) regardless of corpus size;
    multi-process sharding happens row-wise in the DataLoader.
    """

    def __init__(self, doc_factory: Callable[[int], Iterable[Sequence[int]]],
                 seq_len: int,
                 eos_id: Optional[int] = CharTokenizer.EOS_ID):
        self.doc_factory = doc_factory
        self.seq_len = seq_len
        self.eos_id = eos_id
        self._epoch = 0

    def set_epoch(self, epoch: int) -> None:
        self._epoch = epoch

    def __iter__(self) -> Iterator[np.ndarray]:
        return pack_stream(self.doc_factory(self._epoch), self.seq_len,
                           self.eos_id)


def synthetic_corpus(n_sentences: int = 200, seed: int = 0) -> str:
    """Tiny grammar-driven corpus with learnable structure (for examples,
    tests, and benches — no downloads in this environment)."""
    rng = np.random.default_rng(seed)
    subjects = ["the pod", "a chip", "the mesh", "an actor", "the trainer",
                "a worker"]
    verbs = ["shards", "compiles", "reduces", "gathers", "schedules",
             "restores"]
    objects = ["the batch", "every gradient", "a checkpoint", "the ring",
               "its state", "the queue"]
    sents = []
    for _ in range(n_sentences):
        s = (f"{subjects[rng.integers(len(subjects))]} "
             f"{verbs[rng.integers(len(verbs))]} "
             f"{objects[rng.integers(len(objects))]}.")
        sents.append(s)
    # paragraphs of ~5 sentences = documents for the packer
    paras = [" ".join(sents[i:i + 5]) for i in range(0, len(sents), 5)]
    return "\n\n".join(paras)
