"""Data pipeline: datasets, sharded sampling, batching.

Capability analog of the reference's DistributedSampler auto-injection
(reference: ray_lightning/ray_ddp.py:280-295, asserted at
ray_lightning/tests/test_ddp.py:52-72).  TPU-native split of responsibilities:

- **SPMD (single controller)**: the host builds one *global* batch and
  ``jax.device_put``s it with a batch sharding -- XLA scatters shards over the
  mesh.  The sampler then has ``num_replicas == num_processes`` (1), not
  num_devices; devices are fed by sharding, not by per-replica loaders.
- **Multi-process (one process per TPU host)**: each process samples its own
  disjoint slice via ShardedSampler(num_replicas=P, rank=p) exactly like the
  reference's per-worker DistributedSampler.

Batches are numpy pytrees (dict/tuple of arrays with a common leading batch
dim); the trainer owns device placement.
"""

from __future__ import annotations

import math
from typing import Any, Callable, Iterator, Optional, Sequence

import numpy as np


class Dataset:
    def __len__(self) -> int:
        raise NotImplementedError

    def __getitem__(self, idx: int) -> Any:
        raise NotImplementedError


class IterableDataset:
    """Stream-style dataset: yields examples, no len/random access.

    For corpora that don't fit in memory (the LM pretraining case —
    data/lm.py's StreamingLMDataset packs a document stream on the fly).
    Sharding under multi-process is element-wise round-robin: process p of
    P keeps elements where ``index % P == p`` — every process sees a
    disjoint, interleaved slice of one deterministic stream, the streaming
    analog of ShardedSampler's disjoint index shards.

    Optional hook: ``set_epoch(epoch)`` for epoch-varying streams.
    """

    def __iter__(self) -> Iterator[Any]:
        raise NotImplementedError


class RandomDataset(Dataset):
    """Fixed random-tensor dataset (fixture parity with the reference's
    RandomDataset, reference: ray_lightning/tests/utils.py:12-21)."""

    def __init__(self, size: int, length: int, seed: int = 0):
        self.length = length
        self.data = np.random.default_rng(seed).standard_normal(
            (length, size), dtype=np.float32)

    def __len__(self) -> int:
        return self.length

    def __getitem__(self, idx: int):
        return self.data[idx]

    def _native_arrays(self):
        return (self.data,)


class ArrayDataset(Dataset):
    """Zips equal-length arrays into (a[i], b[i], ...) examples."""

    def __init__(self, *arrays: np.ndarray):
        assert arrays and all(len(a) == len(arrays[0]) for a in arrays)
        self.arrays = tuple(np.asarray(a) for a in arrays)

    def __len__(self) -> int:
        return len(self.arrays[0])

    def __getitem__(self, idx: int):
        items = tuple(a[idx] for a in self.arrays)
        return items if len(items) > 1 else items[0]

    def _native_arrays(self):
        return self.arrays


class ShardedSampler:
    """Deterministic disjoint index shards per replica.

    Field-for-field parity with what the reference's sampler test asserts
    (shuffle flag, num_replicas == world size, rank == global rank,
    reference: ray_lightning/tests/test_ddp.py:52-72), plus ``set_epoch``
    for epoch-varying shuffles.
    """

    def __init__(self, dataset_len: int, num_replicas: int = 1, rank: int = 0,
                 shuffle: bool = True, drop_last: bool = True, seed: int = 0):
        if rank >= num_replicas:
            raise ValueError(f"rank {rank} >= num_replicas {num_replicas}")
        self.dataset_len = dataset_len
        self.num_replicas = num_replicas
        self.rank = rank
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.seed = seed
        self.epoch = 0
        if drop_last:
            self.num_samples = dataset_len // num_replicas
        else:
            self.num_samples = math.ceil(dataset_len / num_replicas)

    def set_epoch(self, epoch: int) -> None:
        self.epoch = epoch

    def __len__(self) -> int:
        return self.num_samples

    def __iter__(self) -> Iterator[int]:
        if self.shuffle:
            order = np.random.default_rng(
                (self.seed, self.epoch)).permutation(self.dataset_len)
        else:
            order = np.arange(self.dataset_len)
        total = self.num_samples * self.num_replicas
        if total > len(order):  # pad by wrapping, like torch's sampler
            order = np.concatenate([order, order[:total - len(order)]])
        return iter(order[self.rank:total:self.num_replicas].tolist())


def default_collate(samples: Sequence[Any]) -> Any:
    """Stack a list of example pytrees into one batch pytree of arrays."""
    first = samples[0]
    if isinstance(first, dict):
        return {k: default_collate([s[k] for s in samples]) for k in first}
    if isinstance(first, (tuple, list)):
        return type(first)(default_collate(col) for col in zip(*samples))
    return np.stack([np.asarray(s) for s in samples])


class DataLoader:
    """Minimal numpy dataloader with sampler injection support.

    The trainer calls ``_inject_sampler`` on loaders the user passed without
    an explicit sampler -- the analog of PTL's auto
    ``replace_sampler_ddp`` that the reference enables via
    ``require_distributed_sampler`` (reference: ray_lightning/ray_ddp.py:280-287).
    """

    def __init__(self, dataset: Dataset, batch_size: int = 32,
                 shuffle: bool = False, sampler: Optional[ShardedSampler] = None,
                 drop_last: bool = True,
                 collate_fn: Callable[[Sequence[Any]], Any] = default_collate,
                 seed: int = 0, use_native: Optional[bool] = None):
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.collate_fn = collate_fn
        self.seed = seed
        self.use_native = use_native
        self._iterable = isinstance(dataset, IterableDataset)
        self._user_set_sampler = sampler is not None
        if self._iterable:
            if shuffle:
                raise ValueError(
                    "shuffle=True is undefined for an IterableDataset; "
                    "shuffle in the stream itself")
            if sampler is not None:
                raise ValueError("IterableDataset takes no sampler")
            self.sampler = None
            self._shard = (1, 0)  # (num_replicas, rank) round-robin
        else:
            self.sampler = sampler or ShardedSampler(
                len(dataset), 1, 0, shuffle=shuffle, drop_last=drop_last,
                seed=seed)
        self._engine = None  # lazily-built native.DataEngine
        self._engine_key = None
        self._engine_busy = False

    def _inject_sampler(self, num_replicas: int, rank: int,
                        shuffle: bool) -> None:
        if self._iterable:
            self._shard = (num_replicas, rank)
            return
        if self._user_set_sampler:
            return
        self.sampler = ShardedSampler(
            len(self.dataset), num_replicas, rank, shuffle=shuffle,
            drop_last=self.drop_last, seed=self.seed)

    def set_epoch(self, epoch: int) -> None:
        if self._iterable:
            if hasattr(self.dataset, "set_epoch"):
                self.dataset.set_epoch(epoch)
            return
        self.sampler.set_epoch(epoch)

    def __len__(self) -> int:
        if self._iterable:
            raise TypeError("an IterableDataset loader has no length")
        n = len(self.sampler)
        return n // self.batch_size if self.drop_last else math.ceil(
            n / self.batch_size)

    def _iter_stream(self) -> Iterator[Any]:
        """Batch boundaries align to global blocks of replicas*batch_size
        elements, so EVERY rank yields exactly one batch per complete
        block — per-rank batch counts are equal by construction.  (Naive
        per-rank batching lets counts diverge on ragged streams, and a
        rank with one extra step hangs the others' collectives.)  The
        ragged tail block is dropped under multi-replica sharding for the
        same reason."""
        replicas, rank = self._shard
        block = replicas * self.batch_size
        buf = []
        for i, example in enumerate(self.dataset):
            if i % replicas == rank:
                buf.append(example)
            if (i + 1) % block == 0:
                yield self.collate_fn(buf)
                buf = []
        if buf and not self.drop_last and replicas == 1:
            yield self.collate_fn(buf)

    def __iter__(self) -> Iterator[Any]:
        if self._iterable:
            yield from self._iter_stream()
            return
        engine = self._native_engine()
        if engine is not None:
            # single-consumer engine: while this generator is live, further
            # iterators (zip(loader, loader), nested passes) take the Python
            # path instead of resetting this one's stream
            self._engine_busy = True
            try:
                indices = np.fromiter(self.sampler, np.int64)
                yield from engine.iter_indices(indices)
                return
            finally:
                self._engine_busy = False
        buf = []
        for idx in self.sampler:
            buf.append(self.dataset[idx])
            if len(buf) == self.batch_size:
                yield self.collate_fn(buf)
                buf = []
        if buf and not self.drop_last:
            yield self.collate_fn(buf)

    # ------------------------------------------------------------------ #
    # native fast path                                                   #
    # ------------------------------------------------------------------ #
    def _native_engine(self):
        """C++ batch engine when the dataset is array-backed; None otherwise
        (Python path).  Batches are bit-identical either way: the engine
        consumes THIS loader's sampler index order and only parallelizes the
        gather/collate off the GIL, prefetching ahead of consumption to
        overlap input with async XLA dispatch (SURVEY.md §7.4 flags the
        input pipeline as the TPU bottleneck)."""
        def ineligible(reason: str):
            if self.use_native:
                raise RuntimeError(f"use_native=True but {reason}")
            return None

        if self.use_native is False:
            return None
        if getattr(self, "_engine_busy", False):
            return None  # re-entrant iteration: concurrent pass uses Python
        if self.collate_fn is not default_collate:
            return ineligible("a custom collate_fn is set")
        arrays = getattr(self.dataset, "_native_arrays", lambda: None)()
        from .. import native
        if not arrays or not native.engine_compatible_arrays(arrays):
            return ineligible(
                "the dataset does not expose numeric _native_arrays()")
        if not native.available():
            return ineligible(str(native.build_error()))
        key = (self.batch_size, self.drop_last)
        if self._engine is None or self._engine_key != key:
            if self._engine is not None:
                self._engine.close()
            self._engine = native.DataEngine(
                arrays, self.batch_size, drop_last=self.drop_last)
            self._engine_key = key
        return self._engine

    def __getstate__(self):
        # the native engine holds ctypes handles + threads; rebuild on the
        # far side (loaders ship to workers through cloudpickle, the analog
        # of the reference's ray.put'd Trainer, ray_ddp.py:169)
        state = self.__dict__.copy()
        state["_engine"] = None
        state["_engine_key"] = None
        state["_engine_busy"] = False
        return state
