"""Checkpoint IO: atomic single-file checkpoints of full trainer state.

Capability analog of the reference's two checkpoint paths: per-worker PTL
``ModelCheckpoint`` files whose rank-0 path is shipped home (reference:
ray_lightning/ray_ddp.py:269-278) and the Tune bridge's
``dump_checkpoint`` + ``atomic_save`` (reference: ray_lightning/tune.py:128-142).

TPU-native notes: every array is pulled to host (``jax.device_get``) before
serialization -- device arrays may be sharded across a mesh and must be
materialized; this is the XLA analog of the reference's implicit
``state_dict()`` CPU copy.  Writes are atomic (tmp + rename) so a crashed
writer never leaves a torn checkpoint.
"""

from __future__ import annotations

import os
import pickle
import tempfile
from typing import Any, Dict

import flax.serialization
import jax


def _to_host_state_dict(tree: Any) -> Any:
    return flax.serialization.to_state_dict(jax.device_get(tree))


def atomic_save(payload: Dict[str, Any], filepath: str) -> None:
    """Pickle `payload` to `filepath` atomically."""
    directory = os.path.dirname(os.path.abspath(filepath))
    os.makedirs(directory, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=directory, suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            pickle.dump(payload, f, protocol=pickle.HIGHEST_PROTOCOL)
        os.replace(tmp, filepath)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise


def build_checkpoint(state, epoch: int, global_step: int,
                     hparams: Dict[str, Any] | None = None,
                     callbacks: Dict[str, Any] | None = None,
                     extra: Dict[str, Any] | None = None) -> Dict[str, Any]:
    payload = {
        "format_version": 1,
        "epoch": int(epoch),
        "global_step": int(global_step),
        "hparams": dict(hparams or {}),
        "callbacks": dict(callbacks or {}),
    }
    if state is not None:  # None = arrays stored separately (sharded path)
        payload["state"] = _to_host_state_dict(state)
    if extra:
        payload.update(extra)
    return payload


def read_checkpoint(filepath: str) -> Dict[str, Any]:
    with open(filepath, "rb") as f:
        return pickle.load(f)


def restore_state(payload: Dict[str, Any], template_state):
    """Restore a TrainState pytree from a checkpoint payload.

    Field-set drift is reconciled against the template: state fields the
    checkpoint predates (e.g. ``residual``/``grad_accum`` from before
    gradient compression existed) fall back to the template's fresh
    values, and saved fields the template no longer carries (compression
    turned off on resume) are dropped -- error-feedback residuals are
    advisory state, safe to reset, unlike params/opt_state."""
    state = payload["state"]
    if isinstance(state, dict):
        tmpl = flax.serialization.to_state_dict(template_state)
        if isinstance(tmpl, dict):
            state = {k: (tmpl[k] if tmpl[k] is None or state.get(k) is None
                         else state[k])
                     for k in tmpl}
    return flax.serialization.from_state_dict(template_state, state)


def restore_params(payload: Dict[str, Any], template_params):
    return flax.serialization.from_state_dict(template_params,
                                              payload["state"]["params"])


def latest_checkpoint(directory: str, pattern: str = "*.ckpt") -> str | None:
    """Newest checkpoint file under `directory` (recursive), or None.

    The resume anchor for crash recovery (Trainer.fit(ckpt_path="last"),
    runtime/elastic.py) — capability the reference lacks (SURVEY.md §5.4:
    'No mid-run resume of a crashed job')."""
    import glob

    # escape the user directory: hyperparameter-stamped run dirs often carry
    # glob metachars ('runs/sweep[lr=0.1]') that would silently match nothing
    candidates = glob.glob(os.path.join(glob.escape(directory), "**", pattern),
                           recursive=True)
    candidates = [c for c in candidates if os.path.isfile(c)]
    # sharded checkpoints are directories marked complete by their meta.json
    from . import sharded_checkpoint as sharded_lib
    candidates += [os.path.dirname(m) for m in glob.glob(
        os.path.join(glob.escape(directory), "**", sharded_lib.META_FILE),
        recursive=True)]
    if not candidates:
        return None
    return max(candidates, key=os.path.getmtime)
