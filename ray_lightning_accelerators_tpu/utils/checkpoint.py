"""Checkpoint IO: atomic single-file checkpoints of full trainer state.

Capability analog of the reference's two checkpoint paths: per-worker PTL
``ModelCheckpoint`` files whose rank-0 path is shipped home (reference:
ray_lightning/ray_ddp.py:269-278) and the Tune bridge's
``dump_checkpoint`` + ``atomic_save`` (reference: ray_lightning/tune.py:128-142).

TPU-native notes: every array is pulled to host (``jax.device_get``) before
serialization -- device arrays may be sharded across a mesh and must be
materialized; this is the XLA analog of the reference's implicit
``state_dict()`` CPU copy.  Writes are atomic (tmp + rename) so a crashed
writer never leaves a torn checkpoint.
"""

from __future__ import annotations

import os
import pickle
import tempfile
from typing import Any, Dict

import flax.serialization
import jax


def _to_host_state_dict(tree: Any) -> Any:
    return flax.serialization.to_state_dict(jax.device_get(tree))


def atomic_save(payload: Dict[str, Any], filepath: str) -> None:
    """Pickle `payload` to `filepath` atomically."""
    directory = os.path.dirname(os.path.abspath(filepath))
    os.makedirs(directory, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=directory, suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            pickle.dump(payload, f, protocol=pickle.HIGHEST_PROTOCOL)
        os.replace(tmp, filepath)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise


def build_checkpoint(state, epoch: int, global_step: int,
                     hparams: Dict[str, Any] | None = None,
                     callbacks: Dict[str, Any] | None = None,
                     extra: Dict[str, Any] | None = None) -> Dict[str, Any]:
    payload = {
        "format_version": 1,
        "epoch": int(epoch),
        "global_step": int(global_step),
        "hparams": dict(hparams or {}),
        "callbacks": dict(callbacks or {}),
    }
    if state is not None:  # None = arrays stored separately (sharded path)
        payload["state"] = _to_host_state_dict(state)
    if extra:
        payload.update(extra)
    return payload


def read_checkpoint(filepath: str) -> Dict[str, Any]:
    with open(filepath, "rb") as f:
        return pickle.load(f)


def restore_state(payload: Dict[str, Any], template_state):
    """Restore a TrainState pytree from a checkpoint payload.

    Field-set drift is reconciled against the template: state fields the
    checkpoint predates (e.g. ``residual``/``grad_accum`` from before
    gradient compression existed) fall back to the template's fresh
    values, and saved fields the template no longer carries (compression
    turned off on resume) are dropped -- error-feedback residuals are
    advisory state, safe to reset, unlike params/opt_state."""
    state = payload["state"]
    if isinstance(state, dict):
        tmpl = flax.serialization.to_state_dict(template_state)
        if isinstance(tmpl, dict):
            state = {k: (tmpl[k] if tmpl[k] is None or state.get(k) is None
                         else state[k])
                     for k in tmpl}
    return flax.serialization.from_state_dict(template_state, state)


def restore_params(payload: Dict[str, Any], template_params):
    return flax.serialization.from_state_dict(template_params,
                                              payload["state"]["params"])


# memoized pickle verdicts (abspath -> (mtime, size, ok, reason)):
# full-unpickle verification of a multi-GB file must not repeat on every
# retention-GC pass while the file is unchanged; consulted only with
# use_cache (restore-time checks keep the full load)
_pickle_verify_cache: dict = {}


def verify_checkpoint(filepath: str,
                      use_cache: bool = False) -> tuple[bool, str]:
    """Integrity check over either checkpoint format: sharded dirs run
    the digest pass (utils/sharded_checkpoint.verify_checkpoint; with
    ``use_cache`` a save-primed verdict is accepted for unmodified
    trees); pickle files must unpickle end-to-end (a truncated pickle
    raises mid-load; with ``use_cache`` the verdict is memoized per
    mtime+size).  Returns ``(ok, reason)`` — never raises."""
    from . import sharded_checkpoint as sharded_lib
    if os.path.isdir(filepath):
        return sharded_lib.verify_checkpoint(filepath, use_cache=use_cache)
    if not os.path.isfile(filepath):
        return False, "missing"
    key = os.path.abspath(filepath)
    try:
        st = os.stat(filepath)
        stamp = (st.st_mtime, st.st_size)
    except OSError:
        stamp = None
    if use_cache and stamp is not None:
        cached = _pickle_verify_cache.get(key)
        if cached is not None and cached[:2] == stamp:
            return cached[2], cached[3]
    try:
        read_checkpoint(filepath)
        verdict = (True, "ok")
    except Exception as e:  # torn write, disk corruption, wrong file
        verdict = (False, f"unreadable pickle: {type(e).__name__}: {e}")
    if stamp is not None:
        _pickle_verify_cache[key] = stamp + verdict
    return verdict


def list_checkpoints(directory: str,
                     pattern: str = "*.ckpt") -> list[str]:
    """Every checkpoint under ``directory`` (recursive; pickle files plus
    sharded dirs marked complete by their meta.json), newest first."""
    import glob

    # escape the user directory: hyperparameter-stamped run dirs often carry
    # glob metachars ('runs/sweep[lr=0.1]') that would silently match nothing
    candidates = glob.glob(os.path.join(glob.escape(directory), "**", pattern),
                           recursive=True)
    candidates = [c for c in candidates if os.path.isfile(c)]
    # sharded checkpoints are directories marked complete by their meta.json
    from . import sharded_checkpoint as sharded_lib
    candidates += [os.path.dirname(m) for m in glob.glob(
        os.path.join(glob.escape(directory), "**", sharded_lib.META_FILE),
        recursive=True)]
    return sorted(candidates, key=os.path.getmtime, reverse=True)


def latest_checkpoint(directory: str, pattern: str = "*.ckpt",
                      verify: bool = True) -> str | None:
    """Newest VERIFIED checkpoint under `directory` (recursive), or None.

    The resume anchor for crash recovery (Trainer.fit(ckpt_path="last"),
    runtime/elastic.py) — capability the reference lacks (SURVEY.md §5.4:
    'No mid-run resume of a crashed job').  Candidates are walked newest
    first and each is integrity-checked (``verify_checkpoint``): a torn
    or corrupt newest checkpoint — the one a crash/preemption most likely
    damaged — is skipped with a warning and the resume falls back to the
    previous verified one instead of handing the trainer garbage.
    ``verify=False`` restores the raw newest-by-mtime pick."""
    from .logging import log

    for cand in list_checkpoints(directory, pattern):
        if not verify:
            return cand
        ok, why = verify_checkpoint(cand)
        if ok:
            return cand
        log.warning("skipping unverified checkpoint %s: %s", cand, why)
    return None


def prune_checkpoints(directory: str, keep_last_k: int,
                      protect: tuple | list = (),
                      pattern: str = "*.ckpt") -> list[str]:
    """Retention GC: keep the newest ``keep_last_k`` checkpoints under
    ``directory`` and delete the rest — EXCEPT that the newest *verified*
    checkpoint is always kept, even when it is older than the retention
    window (if every kept checkpoint is torn, deleting the last good one
    would destroy the only resume anchor).  ``protect`` paths (e.g. a
    tracked best_model_path) are never deleted.  Returns removed paths."""
    from . import sharded_checkpoint as sharded_lib
    from .logging import log

    if keep_last_k is None or keep_last_k < 1:
        return []
    candidates = list_checkpoints(directory, pattern)
    protected = {os.path.abspath(p) for p in protect if p}
    if not [p for p in candidates[keep_last_k:]
            if os.path.abspath(p) not in protected]:
        # nothing would be deleted: skip the digest pass entirely (this
        # runs every validation end -- re-hashing multi-GB checkpoints
        # to confirm an anchor nobody is about to delete is waste)
        return []
    keep = set(candidates[:keep_last_k])
    # use_cache: a checkpoint this process just saved (and digested) is
    # accepted without a re-hash; only checkpoints of unknown provenance
    # pay the full pass
    if not any(verify_checkpoint(p, use_cache=True)[0] for p in keep):
        for p in candidates[keep_last_k:]:
            if verify_checkpoint(p, use_cache=True)[0]:
                keep.add(p)
                log.warning(
                    "checkpoint retention: every checkpoint in the "
                    "keep_last_k=%d window failed verification; keeping "
                    "older verified %s as the resume anchor",
                    keep_last_k, p)
                break
    removed = []
    for p in candidates[keep_last_k:]:
        if p in keep or os.path.abspath(p) in protected:
            continue
        sharded_lib.remove_checkpoint(p)
        removed.append(p)
    return removed
