"""Shared exponential backoff with half-jitter.

One backoff implementation for every retry layer in the package —
``ElasticRunner``'s between-attempt restarts (runtime/elastic.py) and
the serve tier's request-retry / replica-revival schedules
(serve/controller.py) — so their math can never drift apart.  The
sequence is pinned by test: ``min(cap, base * 2**(attempt-1))`` scaled
by a uniform factor in ``[0.5, 1.0)``.

Half-jitter rather than full jitter: the delay never drops below half
the deterministic schedule, so a retry loop keeps its exponential
spacing guarantee while a fleet of retriers restarting off one sick
shared host still decorrelates instead of hot-looping it in lockstep.

Dependency leaf (stdlib only): runtime and serve both import it, never
the reverse.
"""

from __future__ import annotations

import random
from typing import Callable

DEFAULT_BACKOFF_CAP_S = 60.0


def backoff_delay_s(attempt: int, base_s: float,
                    cap_s: float = DEFAULT_BACKOFF_CAP_S,
                    rng: Callable[[], float] = random.random) -> float:
    """Exponential backoff with half-jitter: ``min(cap, base * 2**(a-1))``
    scaled by a uniform factor in [0.5, 1.0).  ``attempt`` is 1-based (the
    first RETRY).  Jitter keeps a fleet of runners restarting off a sick
    shared host from hot-looping it in lockstep."""
    if base_s <= 0 or attempt < 1:
        return 0.0
    d = min(cap_s, base_s * (2.0 ** (attempt - 1)))
    return d * (0.5 + 0.5 * rng())
