"""Exponential moving average of parameters, as an optax transform.

No reference analog (optimization there is the user's torch code).  The
TPU-honest design constraint: EMA must update **inside the jitted train
step** — a callback copying params at epoch boundaries would miss the
per-step averaging that gives EMA its value, and doing it host-side would
sync every step.  So the tracker is a ``GradientTransformation`` chained
AFTER the optimizer: it passes updates through unchanged and shadows the
post-update parameters in its own state, which lives in the donated
``TrainState.opt_state`` on device like any optimizer moment (and is
checkpointed/sharded with it for free).
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import optax


class EmaState(NamedTuple):
    ema: Any          # pytree shadowing params (initialized to params)
    count: jax.Array  # steps taken


def ema_tracker(decay: float = 0.999) -> optax.GradientTransformation:
    """Chain after an optimizer: ``optax.chain(tx, ema_tracker(0.999))``.

    Updates flow through untouched; the state tracks
    ``ema = decay * ema + (1-decay) * new_params`` each step.  Initializing
    the shadow to the initial params (rather than zeros + bias correction)
    keeps extraction a plain state read.
    """

    def init_fn(params):
        # a REAL copy: jnp.asarray would alias the param buffers, and the
        # trainer donates the whole TrainState — donating the same buffer
        # via params and via this shadow is an XLA error
        return EmaState(ema=jax.tree.map(jnp.copy, params),
                        count=jnp.zeros((), jnp.int32))

    def update_fn(updates, state, params=None):
        if params is None:
            raise ValueError(
                "ema_tracker needs params; call tx.update(grads, state, "
                "params) with the params argument")
        new_params = optax.apply_updates(params, updates)
        d = jnp.asarray(decay, jnp.float32)
        new_ema = jax.tree.map(
            lambda e, p: (d * e.astype(jnp.float32)
                          + (1.0 - d) * p.astype(jnp.float32)).astype(e.dtype),
            state.ema, new_params)
        return updates, EmaState(ema=new_ema, count=state.count + 1)

    return optax.GradientTransformation(init_fn, update_fn)


def _find_ema_states(opt_state) -> list:
    """Locate EmaState nodes anywhere in a (possibly nested/wrapped)
    optimizer state tree — chain tuples, MultiSteps wrappers, etc."""
    found = []

    def walk(node):
        if isinstance(node, EmaState):
            found.append(node)
            return
        if isinstance(node, (tuple, list)) or hasattr(node, "_fields"):
            for child in node:
                walk(child)
        elif hasattr(node, "inner_opt_state"):
            walk(node.inner_opt_state)

    walk(opt_state)
    return found


def ema_params(opt_state):
    """Extract the EMA parameter pytree from an optimizer state containing
    an ``ema_tracker``; None when no tracker is present."""
    states = _find_ema_states(opt_state)
    return states[0].ema if states else None
