"""Loggers: metric sinks for the trainer.

The reference delegated logging to PTL and only bridged
``trainer.callback_metrics`` to Tune (reference: ray_lightning/tune.py:82-95).
Here loggers are first-class: CSV on disk by default, an in-memory logger for
tests.  All values arriving here are host floats -- the trainer is responsible
for materializing device arrays at log boundaries only (never per step),
keeping the XLA pipeline async.
"""

from __future__ import annotations

import csv
import json as _json
import logging
import os
from typing import Dict, List, Optional

from ..analysis import knobs


class _RankFormatter(logging.Formatter):
    """Rank/pid-stamped formatter.  The rank comes from the flight
    recorder's process identity (telemetry/recorder.py, set by the
    worker boot path) — in a fanned-out run every line says which rank
    said it, which is the difference between a log and a timeline.
    ``json_mode`` (``RLA_TPU_LOG_JSON``) renders one JSON object per
    line (ts/level/logger/rank/pid/msg) for log shippers."""

    def __init__(self, json_mode: bool = False):
        super().__init__()
        self.json_mode = json_mode

    @staticmethod
    def _rank() -> str:
        try:
            # lazy: telemetry.recorder imports knobs, never this module,
            # so the late import cannot cycle
            from ..telemetry.recorder import current_rank
            rank = current_rank()
        except Exception:
            rank = None
        return "driver" if rank is None else str(rank)

    def format(self, record: logging.LogRecord) -> str:
        rank = self._rank()
        if self.json_mode:
            out = {"ts": round(record.created, 3),
                   "level": record.levelname,
                   "logger": record.name,
                   "rank": rank,
                   "pid": record.process,
                   "msg": record.getMessage()}
            if record.exc_info:
                out["exc"] = self.formatException(record.exc_info)
            if record.stack_info:
                out["stack"] = self.formatStack(record.stack_info)
            return _json.dumps(out)
        msg = (f"[{record.levelname} rla-tpu {rank}:{record.process}] "
               f"{record.getMessage()}")
        if record.exc_info:
            msg = f"{msg}\n{self.formatException(record.exc_info)}"
        if record.stack_info:
            msg = f"{msg}\n{self.formatStack(record.stack_info)}"
        return msg


log = logging.getLogger("ray_lightning_accelerators_tpu")


def configure_logging(json_mode: Optional[bool] = None) -> None:
    """(Re)install the package handler/formatter.  ``json_mode`` None
    reads the ``RLA_TPU_LOG_JSON`` knob; runs once at import and again
    whenever a caller (or test) flips the knob."""
    if json_mode is None:
        json_mode = knobs.get_bool("RLA_TPU_LOG_JSON", False)
    handler = next((h for h in log.handlers
                    if isinstance(h, logging.StreamHandler)), None)
    if handler is None:
        handler = logging.StreamHandler()
        log.addHandler(handler)
    handler.setFormatter(_RankFormatter(json_mode=json_mode))
    level = knobs.get_str("RLA_TPU_LOG_LEVEL", "WARNING").upper()
    if not isinstance(logging.getLevelName(level), int):
        # a typo'd level must not crash at import/boot time
        log.setLevel("WARNING")
        log.warning("bad RLA_TPU_LOG_LEVEL=%r; using WARNING", level)
    else:
        log.setLevel(level)


if not log.handlers:
    configure_logging()


class Logger:
    def log_metrics(self, metrics: Dict[str, float], step: int) -> None:
        raise NotImplementedError

    def finalize(self) -> None:
        pass


class InMemoryLogger(Logger):
    def __init__(self):
        self.history: List[Dict[str, float]] = []

    def log_metrics(self, metrics: Dict[str, float], step: int) -> None:
        row = dict(metrics)
        row["step"] = step
        self.history.append(row)


class CSVLogger(Logger):
    """Append-only metrics.csv under `save_dir` (schema grows as keys appear)."""

    def __init__(self, save_dir: str, name: str = "metrics.csv"):
        self.save_dir = save_dir
        self.path = os.path.join(save_dir, name)
        self._rows: List[Dict[str, float]] = []
        self._keys: List[str] = ["step"]

    def log_metrics(self, metrics: Dict[str, float], step: int) -> None:
        row = {"step": step, **metrics}
        for k in row:
            if k not in self._keys:
                self._keys.append(k)
        self._rows.append(row)

    def finalize(self) -> None:
        if not self._rows:
            return
        os.makedirs(self.save_dir, exist_ok=True)
        with open(self.path, "w", newline="") as f:
            writer = csv.DictWriter(f, fieldnames=self._keys)
            writer.writeheader()
            writer.writerows(self._rows)
