"""Loggers: metric sinks for the trainer.

The reference delegated logging to PTL and only bridged
``trainer.callback_metrics`` to Tune (reference: ray_lightning/tune.py:82-95).
Here loggers are first-class: CSV on disk by default, an in-memory logger for
tests.  All values arriving here are host floats -- the trainer is responsible
for materializing device arrays at log boundaries only (never per step),
keeping the XLA pipeline async.
"""

from __future__ import annotations

import csv
import logging
import os
from typing import Dict, List, Optional

from ..analysis import knobs

log = logging.getLogger("ray_lightning_accelerators_tpu")
if not log.handlers:
    _h = logging.StreamHandler()
    _h.setFormatter(logging.Formatter("[%(levelname)s rla-tpu] %(message)s"))
    log.addHandler(_h)
    _level = knobs.get_str("RLA_TPU_LOG_LEVEL", "WARNING").upper()
    if not isinstance(logging.getLevelName(_level), int):
        # a typo'd level must not crash at import time (setLevel raises)
        log.setLevel("WARNING")
        log.warning("bad RLA_TPU_LOG_LEVEL=%r; using WARNING", _level)
    else:
        log.setLevel(_level)


class Logger:
    def log_metrics(self, metrics: Dict[str, float], step: int) -> None:
        raise NotImplementedError

    def finalize(self) -> None:
        pass


class InMemoryLogger(Logger):
    def __init__(self):
        self.history: List[Dict[str, float]] = []

    def log_metrics(self, metrics: Dict[str, float], step: int) -> None:
        row = dict(metrics)
        row["step"] = step
        self.history.append(row)


class CSVLogger(Logger):
    """Append-only metrics.csv under `save_dir` (schema grows as keys appear)."""

    def __init__(self, save_dir: str, name: str = "metrics.csv"):
        self.save_dir = save_dir
        self.path = os.path.join(save_dir, name)
        self._rows: List[Dict[str, float]] = []
        self._keys: List[str] = ["step"]

    def log_metrics(self, metrics: Dict[str, float], step: int) -> None:
        row = {"step": step, **metrics}
        for k in row:
            if k not in self._keys:
                self._keys.append(k)
        self._rows.append(row)

    def finalize(self) -> None:
        if not self._rows:
            return
        os.makedirs(self.save_dir, exist_ok=True)
        with open(self.path, "w", newline="") as f:
            writer = csv.DictWriter(f, fieldnames=self._keys)
            writer.writeheader()
            writer.writerows(self._rows)
