"""Sharded + async checkpointing (orbax/tensorstore backend).

The pickle path (utils/checkpoint.py) gathers the FULL train state onto
process 0's host memory and writes one file — the direct analog of the
reference's rank-0 ``dump_checkpoint`` shipping (reference:
ray_lightning/tune.py:128-142), and exactly what does not scale once params
are sharded over a pod: the gather re-materializes every FSDP shard on one
host and serializes the write behind a single NIC.

This module is the TPU-native path:

- **save**: every process writes its own array shards in parallel (orbax /
  tensorstore OCDBT); no cross-host gather, IO bandwidth scales with hosts.
- **restore**: pass abstract arrays carrying target shardings and each
  process reads only the bytes its devices need — a pod restores a
  checkpoint without any host ever holding the full state.
- **async**: ``sharded-async`` hands the device arrays to a background
  committer so training continues while bytes hit disk
  (``wait_until_finished`` fences).

Layout: ``<path>/state/`` (orbax tree) + ``<path>/meta.json`` (epoch, step,
hparams, callback states — the non-array half of the payload).
"""

from __future__ import annotations

import json
import os
import shutil
from typing import Any, Dict, Optional

import jax

STATE_DIR = "state"
META_FILE = "meta.json"

_sync_ckptr = None
_async_ckptr = None
_finalize_threads: list = []


def _checkpointer(async_save: bool):
    global _sync_ckptr, _async_ckptr
    import orbax.checkpoint as ocp
    if async_save:
        if _async_ckptr is None:
            _async_ckptr = ocp.AsyncCheckpointer(
                ocp.StandardCheckpointHandler())
        return _async_ckptr
    if _sync_ckptr is None:
        _sync_ckptr = ocp.StandardCheckpointer()
    return _sync_ckptr


def wait_until_finished() -> None:
    """Fence any in-flight async save: the orbax commit AND the meta.json
    finalize rename (no-op when none in flight)."""
    if _async_ckptr is not None:
        _async_ckptr.wait_until_finished()
    while _finalize_threads:
        _finalize_threads.pop().join()


def is_sharded_checkpoint(path: str) -> bool:
    return os.path.isdir(path) and os.path.exists(
        os.path.join(path, META_FILE))


def save_sharded(path: str, state: Any, metadata: Dict[str, Any],
                 async_save: bool = False) -> None:
    """Write ``state`` (a pytree of [possibly sharded] jax arrays) under
    ``path`` with every process writing its own shards.  ``metadata`` must
    be JSON-serializable; it is written by process 0 only, LAST, so a
    completed ``meta.json`` marks a complete checkpoint (torn writes are
    invisible to ``is_sharded_checkpoint``/``latest_checkpoint``)."""
    import orbax.checkpoint as ocp
    path = os.path.abspath(path)
    ckptr = _checkpointer(async_save)
    if async_save:
        ckptr.save(os.path.join(path, STATE_DIR),
                   args=ocp.args.StandardSave(state), force=True)
    else:
        ckptr.save(os.path.join(path, STATE_DIR), state, force=True)
    if jax.process_index() == 0:
        # the dir can transiently vanish between the array commit and this
        # write (observed rarely when a prior async save's eviction race
        # leaves cleanup work in flight in the same process); recreate
        # rather than crash the save
        os.makedirs(path, exist_ok=True)
        tmp = os.path.join(path, META_FILE + ".tmp")
        with open(tmp, "w") as f:
            json.dump(metadata, f)
        if async_save:
            # rename only once the array commit completes, from a tracked
            # (joinable) thread: wait_until_finished() joins it, so a fenced
            # checkpoint is guaranteed to carry its completion marker
            import threading

            def _finalize():
                _async_ckptr.wait_until_finished()
                try:
                    # only mark complete if the state tree survived (an
                    # eviction race can sweep it and leave the recreated
                    # dir empty -- meta.json alone would make a state-less
                    # dir look like a restorable checkpoint)
                    if os.path.isdir(os.path.join(path, STATE_DIR)):
                        os.replace(tmp, os.path.join(path, META_FILE))
                except OSError:
                    pass  # checkpoint dir evicted while committing

            t = threading.Thread(target=_finalize, daemon=True)
            _finalize_threads.append(t)
            t.start()
        else:
            if not os.path.isdir(os.path.join(path, STATE_DIR)):
                # the committed state tree was swept away with the dir;
                # meta.json must never mark a state-less checkpoint
                # complete.  Single-host: redo the array save (cheap,
                # heals the race).  Multi-host: orbax save is a
                # collective -- process 0 cannot redo it alone, so fail
                # this save loudly instead of deadlocking the pod.
                if jax.process_count() > 1:
                    raise RuntimeError(
                        f"checkpoint state tree vanished during save: "
                        f"{path}")
                ckptr.save(os.path.join(path, STATE_DIR), state,
                           force=True)
            os.replace(tmp, os.path.join(path, META_FILE))


def read_metadata(path: str) -> Dict[str, Any]:
    with open(os.path.join(path, META_FILE)) as f:
        return json.load(f)


def restore_sharded(path: str, template: Optional[Any] = None,
                    shardings: Optional[Any] = None) -> Any:
    """Restore the state tree saved under ``path``.

    - ``template`` (a pytree matching the saved structure) makes restore
      structure-checked; with ``shardings`` (a matching pytree of
      ``NamedSharding``) each leaf comes back already device-put with that
      sharding and each process reads only its shards.
    - with neither, the tree comes back in saved structure on default
      devices (single-host convenience path).
    """
    wait_until_finished()
    ckptr = _checkpointer(False)
    state_path = os.path.join(os.path.abspath(path), STATE_DIR)
    if template is None:
        return ckptr.restore(state_path)
    if shardings is not None:
        abstract = jax.tree.map(
            lambda x, s: jax.ShapeDtypeStruct(
                jax.numpy.shape(x), x.dtype, sharding=s),
            template, shardings)
    else:
        abstract = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(jax.numpy.shape(x), x.dtype),
            template)
    return ckptr.restore(state_path, abstract)


def remove_checkpoint(path: str) -> None:
    """Delete a checkpoint, whether a pickle file or a sharded directory."""
    if os.path.isdir(path):
        shutil.rmtree(path, ignore_errors=True)
        if os.path.isdir(path):
            # an async finalize rename can land meta.json mid-traversal,
            # leaving a dir that is_sharded_checkpoint would mistake for a
            # complete checkpoint -- sweep again
            shutil.rmtree(path, ignore_errors=True)
    elif os.path.exists(path):
        os.unlink(path)
