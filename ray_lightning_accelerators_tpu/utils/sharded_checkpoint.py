"""Sharded + async checkpointing (orbax/tensorstore backend).

The pickle path (utils/checkpoint.py) gathers the FULL train state onto
process 0's host memory and writes one file — the direct analog of the
reference's rank-0 ``dump_checkpoint`` shipping (reference:
ray_lightning/tune.py:128-142), and exactly what does not scale once params
are sharded over a pod: the gather re-materializes every FSDP shard on one
host and serializes the write behind a single NIC.

This module is the TPU-native path:

- **save**: every process writes its own array shards in parallel (orbax /
  tensorstore OCDBT); no cross-host gather, IO bandwidth scales with hosts.
- **restore**: pass abstract arrays carrying target shardings and each
  process reads only the bytes its devices need — a pod restores a
  checkpoint without any host ever holding the full state.  Because the
  abstract arrays carry GLOBAL shapes, the same checkpoint restores onto a
  *different* device count: the target shardings redistribute the saved
  shards (the portable-redistribution primitive elastic resume needs).
- **async**: ``sharded-async`` hands the device arrays to a background
  committer so training continues while bytes hit disk
  (``wait_until_finished`` fences; an atexit hook fences at interpreter
  exit so the last save's completion marker is never lost).
- **integrity**: ``meta.json`` embeds per-file SHA-256 digests of the
  committed state tree, written AFTER the array commit —
  ``verify_checkpoint`` recomputes them, so a torn or bit-rotted
  checkpoint is detected before a restore walks into it.

Layout: ``<path>/state/`` (orbax tree) + ``<path>/meta.json`` (epoch, step,
hparams, callback states, integrity record — the non-array half of the
payload).
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
from typing import Any, Dict, Optional, Tuple

import jax

STATE_DIR = "state"
META_FILE = "meta.json"
INTEGRITY_KEY = "integrity"

_sync_ckptr = None
_async_ckptr = None
_finalize_threads: list = []
_atexit_registered = False
# verification results primed by the save path (abspath -> (meta.json
# mtime, state-tree total bytes, ok, reason)): a save that just digested
# its own tree should not be re-hashed moments later by retention GC.
# Keyed on meta mtime (a rewrite invalidates) AND total tree size (a
# truncated/vanished shard invalidates via a cheap stat walk, no
# hashing); opt-in per call (use_cache) because a cached verdict still
# cannot see same-size bit rot after the save.
_verify_cache: Dict[str, tuple] = {}


def _register_exit_fence() -> None:
    """Fence async saves at interpreter exit: the daemon ``_finalize``
    thread dies with the interpreter, which would silently drop the last
    async checkpoint's ``meta.json`` completion marker — the checkpoint
    would exist on disk yet never count as complete."""
    global _atexit_registered
    if not _atexit_registered:
        import atexit
        atexit.register(wait_until_finished)
        _atexit_registered = True


def _checkpointer(async_save: bool):
    global _sync_ckptr, _async_ckptr
    import orbax.checkpoint as ocp
    if async_save:
        if _async_ckptr is None:
            _async_ckptr = ocp.AsyncCheckpointer(
                ocp.StandardCheckpointHandler())
            _register_exit_fence()
        return _async_ckptr
    if _sync_ckptr is None:
        _sync_ckptr = ocp.StandardCheckpointer()
    return _sync_ckptr


def wait_until_finished() -> None:
    """Fence any in-flight async save: the orbax commit AND the meta.json
    finalize rename (no-op when none in flight)."""
    if _async_ckptr is not None:
        _async_ckptr.wait_until_finished()
    while _finalize_threads:
        _finalize_threads.pop().join()


def is_sharded_checkpoint(path: str) -> bool:
    return os.path.isdir(path) and os.path.exists(
        os.path.join(path, META_FILE))


def _tree_digests(path: str) -> Dict[str, Dict[str, Any]]:
    """Per-file SHA-256 + size of everything under ``<path>/state/``
    (relative paths).  File-level digests catch exactly what kills a
    restore in practice — truncated shards, partial copies, bit rot —
    without re-reading the arrays through orbax."""
    state_dir = os.path.join(path, STATE_DIR)
    files: Dict[str, Dict[str, Any]] = {}
    for root, _dirs, names in os.walk(state_dir):
        for name in sorted(names):
            fp = os.path.join(root, name)
            rel = os.path.relpath(fp, state_dir)
            h = hashlib.sha256()
            try:
                with open(fp, "rb") as f:
                    for chunk in iter(lambda: f.read(1 << 20), b""):
                        h.update(chunk)
                files[rel] = {"sha256": h.hexdigest(),
                              "bytes": os.path.getsize(fp)}
            except OSError:
                continue  # racing eviction; the dir-survival check rules
    return files


def _write_meta(path: str, metadata: Dict[str, Any]) -> None:
    """meta.json LAST, with the integrity record, via tmp+rename: a
    completed meta.json marks a complete AND digest-verifiable
    checkpoint."""
    meta = dict(metadata)
    meta[INTEGRITY_KEY] = {"algo": "sha256",
                           "files": _tree_digests(path)}
    tmp = os.path.join(path, META_FILE + ".tmp")
    with open(tmp, "w") as f:
        json.dump(meta, f)
    meta_path = os.path.join(path, META_FILE)
    os.replace(tmp, meta_path)
    try:
        # the digests were computed from the tree this instant: prime
        # the cache so retention GC does not immediately re-hash it
        total = sum(r["bytes"] for r in meta[INTEGRITY_KEY]["files"]
                    .values())
        _verify_cache[path] = (os.path.getmtime(meta_path), total,
                               True, "ok")
    except OSError:
        pass


def _tree_total_bytes(path: str) -> int:
    """Stat-walk total of the state tree — the no-hash staleness probe
    for cached verify verdicts."""
    total = 0
    for root, _dirs, names in os.walk(os.path.join(path, STATE_DIR)):
        for name in names:
            try:
                total += os.path.getsize(os.path.join(root, name))
            except OSError:
                continue
    return total


def save_sharded(path: str, state: Any, metadata: Dict[str, Any],
                 async_save: bool = False) -> None:
    """Write ``state`` (a pytree of [possibly sharded] jax arrays) under
    ``path`` with every process writing its own shards.  ``metadata`` must
    be JSON-serializable; it is written by process 0 only, LAST (with the
    per-file integrity digests of the committed tree), so a completed
    ``meta.json`` marks a complete checkpoint (torn writes are invisible
    to ``is_sharded_checkpoint``/``latest_checkpoint``)."""
    import orbax.checkpoint as ocp
    path = os.path.abspath(path)
    ckptr = _checkpointer(async_save)
    if async_save:
        ckptr.save(os.path.join(path, STATE_DIR),
                   args=ocp.args.StandardSave(state), force=True)
    else:
        ckptr.save(os.path.join(path, STATE_DIR), state, force=True)
        # orbax's StandardCheckpointer subclasses AsyncCheckpointer (0.7.x):
        # save() returns with the commit still on a background thread.  The
        # sync contract here is "bytes are durable when save_sharded
        # returns" -- the integrity digests (and any caller immediately
        # reading the tree) depend on it, so fence explicitly.
        wait = getattr(ckptr, "wait_until_finished", None)
        if wait is not None:
            wait()
    # single-writer meta finalize: the array commit above was the
    # collective (every process wrote its shards); only process 0
    # digests + renames meta.json, and the multi-host redo path refuses
    # loudly instead of re-entering the collective save alone
    # graftlint: ok(rank-divergence) — single-writer meta.json finalize
    if jax.process_index() == 0:
        # the dir can transiently vanish between the array commit and this
        # write (observed rarely when a prior async save's eviction race
        # leaves cleanup work in flight in the same process); recreate
        # rather than crash the save
        os.makedirs(path, exist_ok=True)
        if async_save:
            # digest + write meta only once the array commit completes,
            # from a tracked (joinable) thread: wait_until_finished() (and
            # the atexit fence) joins it, so a fenced checkpoint is
            # guaranteed to carry its completion marker
            import threading

            meta_snapshot = dict(metadata)

            def _finalize():
                _async_ckptr.wait_until_finished()
                try:
                    # only mark complete if the state tree survived (an
                    # eviction race can sweep it and leave the recreated
                    # dir empty -- meta.json alone would make a state-less
                    # dir look like a restorable checkpoint)
                    if os.path.isdir(os.path.join(path, STATE_DIR)):
                        _write_meta(path, meta_snapshot)
                except OSError:
                    pass  # checkpoint dir evicted while committing

            t = threading.Thread(target=_finalize, daemon=True)
            _finalize_threads.append(t)
            t.start()
        else:
            if not os.path.isdir(os.path.join(path, STATE_DIR)):
                # the committed state tree was swept away with the dir;
                # meta.json must never mark a state-less checkpoint
                # complete.  Single-host: redo the array save (cheap,
                # heals the race).  Multi-host: orbax save is a
                # collective -- process 0 cannot redo it alone, so fail
                # this save loudly instead of deadlocking the pod.
                if jax.process_count() > 1:
                    raise RuntimeError(
                        f"checkpoint state tree vanished during save: "
                        f"{path}")
                ckptr.save(os.path.join(path, STATE_DIR), state,
                           force=True)
                wait = getattr(ckptr, "wait_until_finished", None)
                if wait is not None:
                    wait()
            _write_meta(path, metadata)


def read_metadata(path: str) -> Dict[str, Any]:
    with open(os.path.join(path, META_FILE)) as f:
        return json.load(f)


def verify_checkpoint(path: str, use_cache: bool = False) -> Tuple[bool, str]:
    """Integrity pass over a sharded checkpoint dir: structure (state
    tree present, meta.json parseable) plus the per-file digest record
    when one exists.  Returns ``(ok, reason)`` — never raises.  A
    checkpoint written before digests existed verifies on structure
    alone (restores of it worked yesterday; refusing them today would
    break every existing run dir).

    ``use_cache=True`` accepts a verdict primed by this process's own
    save of the same (unmodified, by meta.json mtime) checkpoint — for
    hot paths like retention GC that would otherwise re-hash a
    multi-GB tree right after writing it.  Restore-time verification
    should keep the default full pass."""
    path = os.path.abspath(path)
    if not os.path.isdir(path):
        return False, "not a directory"
    meta_path = os.path.join(path, META_FILE)
    if not os.path.exists(meta_path):
        return False, "meta.json missing (torn or in-flight save)"
    if use_cache:
        cached = _verify_cache.get(path)
        try:
            mtime = os.path.getmtime(meta_path)
        except OSError:
            mtime = None
        if cached is not None and mtime is not None \
                and cached[0] == mtime \
                and cached[1] == _tree_total_bytes(path):
            return cached[2], cached[3]
    try:
        with open(meta_path) as f:
            meta = json.load(f)
    except (ValueError, OSError) as e:
        return False, f"meta.json unreadable: {e}"
    state_dir = os.path.join(path, STATE_DIR)
    if not os.path.isdir(state_dir):
        return False, "state tree missing"
    integ = meta.get(INTEGRITY_KEY)
    if not isinstance(integ, dict) or "files" not in integ:
        return True, "ok (no integrity record; pre-digest checkpoint)"
    actual = _tree_digests(path)
    for rel, rec in integ["files"].items():
        got = actual.get(rel)
        if got is None:
            return False, f"shard file missing: {rel}"
        if rec.get("bytes") is not None and got["bytes"] != rec["bytes"]:
            return False, (f"shard file truncated/resized: {rel} "
                           f"({got['bytes']} != {rec['bytes']} bytes)")
        if got["sha256"] != rec.get("sha256"):
            return False, f"shard file digest mismatch: {rel}"
    return True, "ok"


def restore_sharded(path: str, template: Optional[Any] = None,
                    shardings: Optional[Any] = None) -> Any:
    """Restore the state tree saved under ``path``.

    - ``template`` (a pytree matching the saved structure) makes restore
      structure-checked; with ``shardings`` (a matching pytree of
      ``NamedSharding``) each leaf comes back already device-put with that
      sharding and each process reads only its shards.  The template's
      GLOBAL shapes are what must match — the device count/mesh may
      differ from the saving run's (elastic resume onto a shrunk pool):
      the target shardings redistribute the saved bytes.
    - with neither, the tree comes back in saved structure on default
      devices (single-host convenience path).
    """
    wait_until_finished()
    ckptr = _checkpointer(False)
    state_path = os.path.join(os.path.abspath(path), STATE_DIR)
    if template is None:
        return ckptr.restore(state_path)
    if shardings is not None:
        abstract = jax.tree.map(
            lambda x, s: jax.ShapeDtypeStruct(
                jax.numpy.shape(x), x.dtype, sharding=s),
            template, shardings)
    else:
        abstract = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(jax.numpy.shape(x), x.dtype),
            template)
    return ckptr.restore(state_path, abstract)


def remove_checkpoint(path: str) -> None:
    """Delete a checkpoint, whether a pickle file or a sharded directory."""
    if os.path.isdir(path):
        shutil.rmtree(path, ignore_errors=True)
        if os.path.isdir(path):
            # an async finalize rename can land meta.json mid-traversal,
            # leaving a dir that is_sharded_checkpoint would mistake for a
            # complete checkpoint -- sweep again
            shutil.rmtree(path, ignore_errors=True)
    elif os.path.exists(path):
        os.unlink(path)
