"""Learning-rate schedules, jit-traceable end to end.

The reference delegates optimization entirely to the user's torch module
(reference: ray_lightning/tests/utils.py:60-62 configures a bare SGD); this
framework ships the schedule family LM/vision training actually uses.  All
schedules are optax-compatible callables ``step -> lr`` built from jnp ops,
so they can be passed straight to ``optax.adamw(learning_rate=...)`` AND
evaluated inside the jitted train step for metric logging: a module that
sets ``self.lr_schedule = sched`` gets a per-step ``lr`` entry in its
training metrics (core/trainer.py wires this).
"""

from __future__ import annotations

from typing import Callable, Dict

import jax.numpy as jnp
import optax

Schedule = Callable[[jnp.ndarray], jnp.ndarray]


def constant(lr: float) -> Schedule:
    return optax.constant_schedule(lr)


def warmup_cosine(peak_lr: float, total_steps: int, warmup_steps: int = 0,
                  end_lr: float = 0.0) -> Schedule:
    """Linear warmup to ``peak_lr`` then cosine decay to ``end_lr``."""
    return optax.warmup_cosine_decay_schedule(
        init_value=0.0, peak_value=peak_lr, warmup_steps=warmup_steps,
        decay_steps=total_steps, end_value=end_lr)


def warmup_linear(peak_lr: float, total_steps: int, warmup_steps: int = 0,
                  end_lr: float = 0.0) -> Schedule:
    """Linear warmup then linear decay to ``end_lr`` at ``total_steps``."""
    warm = optax.linear_schedule(0.0, peak_lr, max(warmup_steps, 1))
    decay = optax.linear_schedule(peak_lr, end_lr,
                                  max(total_steps - warmup_steps, 1))
    return optax.join_schedules([warm, decay], [warmup_steps])


def step_decay(init_lr: float,
               boundaries_and_scales: Dict[int, float]) -> Schedule:
    """Piecewise-constant: multiply by scale at each step boundary."""
    return optax.piecewise_constant_schedule(init_lr, boundaries_and_scales)


def inverse_sqrt(peak_lr: float, warmup_steps: int) -> Schedule:
    """Noam/transformer schedule: linear warmup then 1/sqrt(step) decay."""
    w = max(warmup_steps, 1)

    def sched(step):
        s = jnp.maximum(step, 1).astype(jnp.float32)
        return peak_lr * jnp.minimum(s / w, jnp.sqrt(w / s))

    return sched


def wsd(peak_lr: float, total_steps: int, warmup_steps: int = 0,
        decay_steps: int = 0, end_lr: float = 0.0) -> Schedule:
    """Warmup–stable–decay: ramp up, hold at ``peak_lr``, linear-decay over
    the final ``decay_steps`` to ``end_lr``.  The plateau makes mid-flight
    checkpoints comparable (no per-step decay drift) — the schedule of
    choice for continuously-trained LMs."""
    w = max(warmup_steps, 0)
    d = max(decay_steps, 0)
    stable_end = max(total_steps - d, w)

    def sched(step):
        s = step.astype(jnp.float32) if hasattr(step, "astype") \
            else jnp.float32(step)
        warm = jnp.where(w > 0, s / jnp.maximum(w, 1), 1.0)
        decay_frac = (s - stable_end) / jnp.maximum(d, 1)
        decay = 1.0 - decay_frac * (1.0 - end_lr / peak_lr)
        factor = jnp.where(s < w, warm,
                           jnp.where(s < stable_end, 1.0,
                                     jnp.clip(decay, end_lr / peak_lr, 1.0)))
        return peak_lr * factor

    return sched
