"""Torch weight interop: import/export between torch state_dicts and
param pytrees.

The migration path for reference users: their models and checkpoints are
torch (the reference is a PTL plugin; its whole world is
``state_dict()``s, reference: ray_lightning/ray_ddp.py:274).  This module
moves weights across, with the two convention mismatches handled
explicitly:

- **Linear layout**: ``torch.nn.Linear.weight`` is [out, in]; the matmul
  convention throughout this framework is [in, out] — transpose on the way
  through.
- **dtypes**: torch bf16 has no numpy dtype; conversions route through
  ``ml_dtypes.bfloat16`` (shipped with jax) without an f32 detour.

The mapping API is explicit (pytree path -> state_dict key + optional
transform): silent name-fuzzy matching is how weight imports go quietly
wrong.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple, Union

import numpy as np

Transform = Callable[[np.ndarray], np.ndarray]
MapEntry = Union[str, Tuple[str, Transform]]


def from_torch(tensor) -> np.ndarray:
    """torch.Tensor -> numpy, preserving bf16 via ml_dtypes."""
    import torch
    t = tensor.detach().cpu()
    if t.dtype == torch.bfloat16:
        import ml_dtypes
        # Tensor.view(dtype) needs contiguity (transposed/sliced state_dict
        # entries are not); the f32 path survives because .numpy() handles
        # strides itself
        return t.contiguous().view(torch.uint16).numpy().view(
            ml_dtypes.bfloat16)
    return t.numpy()


def to_torch(array):
    """numpy/jax array -> torch.Tensor, preserving bf16."""
    import torch
    a = np.asarray(array)
    if a.dtype.name == "bfloat16":
        return torch.from_numpy(
            a.view(np.uint16).copy()).view(torch.bfloat16)
    return torch.from_numpy(a.copy())


def transpose(a: np.ndarray) -> np.ndarray:
    """The Linear-layout transform ([out, in] -> [in, out])."""
    return np.ascontiguousarray(a.T)


def state_dict_to_tree(state_dict) -> Dict[str, np.ndarray]:
    """Whole torch state_dict -> flat {key: numpy} dict."""
    return {k: from_torch(v) for k, v in state_dict.items()}


def _flatten(tree: Any, prefix: str = "") -> Dict[str, Any]:
    out = {}
    if isinstance(tree, dict):
        for k, v in tree.items():
            out.update(_flatten(v, f"{prefix}{k}/"))
    else:
        out[prefix[:-1]] = tree
    return out


def _set_path(tree: Dict, path: str, value) -> None:
    keys = path.split("/")
    node = tree
    for k in keys[:-1]:
        node = node[k]
    node[keys[-1]] = value


def import_state_dict(template_params: Dict, state_dict,
                      mapping: Dict[str, MapEntry],
                      strict: bool = True) -> Dict:
    """Build a params pytree from a torch ``state_dict``.

    ``mapping``: pytree path (``"dense_0/kernel"``) -> state_dict key, or
    ``(key, transform)`` — e.g. ``("net.0.weight", transpose)`` for Linear
    kernels.  Every mapped array is shape-checked against the template;
    with ``strict`` every template leaf must be mapped.
    """
    import copy

    flat = _flatten(template_params)
    missing = sorted(set(flat) - set(mapping))
    if strict and missing:
        raise ValueError(f"unmapped template leaves: {missing}")
    extra = sorted(set(mapping) - set(flat))
    if extra:
        raise ValueError(f"mapping paths not in template: {extra}")

    out = copy.deepcopy({k: v for k, v in template_params.items()})
    for path, entry in mapping.items():
        key, tf = entry if isinstance(entry, tuple) else (entry, None)
        if key not in state_dict:
            raise KeyError(f"{key!r} not in state_dict (for {path!r})")
        arr = from_torch(state_dict[key])
        if tf is not None:
            arr = tf(arr)
        want = np.shape(flat[path])
        if tuple(arr.shape) != tuple(want):
            raise ValueError(
                f"{path!r}: state_dict {key!r} has shape {arr.shape}, "
                f"template wants {want} (missing a transpose?)")
        arr = arr.astype(np.asarray(flat[path]).dtype)
        _set_path(out, path, arr)
    return out


def linear_mapping(tree_path: str, torch_prefix: str) -> Dict[str, MapEntry]:
    """Mapping entries for one torch ``nn.Linear`` -> {kernel, bias} pair."""
    return {
        f"{tree_path}/kernel": (f"{torch_prefix}.weight", transpose),
        f"{tree_path}/bias": f"{torch_prefix}.bias",
    }


def export_state_dict(params: Dict,
                      mapping: Dict[str, MapEntry]) -> Dict[str, Any]:
    """Inverse of import_state_dict: params pytree -> torch state_dict
    (same mapping; transforms are re-applied, so involutions like
    ``transpose`` round-trip)."""
    flat = _flatten(params)
    out = {}
    for path, entry in mapping.items():
        key, tf = entry if isinstance(entry, tuple) else (entry, None)
        arr = np.asarray(flat[path])
        if tf is not None:
            arr = tf(arr)
        out[key] = to_torch(arr)
    return out
