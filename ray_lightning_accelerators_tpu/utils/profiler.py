"""Profiling/tracing subsystem.

The reference has none (SURVEY.md §5.1: no profiler, no timing, no spans
anywhere in its tree) and its build note calls for one as a first-class TPU
subsystem: XLA's async dispatch makes naive timing and printf-debugging
useless — a ``time.time()`` around a jitted call measures *dispatch*, not
compute, and device work only surfaces in XLA traces.

Three layers:

- **Span timing** (`Profiler.span`): nested host-side wall-clock spans with
  a thread-local stack.  Each span also opens a
  ``jax.profiler.TraceAnnotation`` so the same names line up inside
  TensorBoard/XProf device traces.  ``sync=True`` spans block on device work
  (``jax.block_until_ready``) so step spans measure real compute.
- **Device traces** (`start_trace`/`stop_trace`): wraps ``jax.profiler`` to
  dump an XPlane/TensorBoard trace directory.
- **Device memory** (`device_memory_stats`): PjRt per-device HBM counters.

The Trainer takes ``profiler=`` and wraps its hot phases
(data fetch / train step / validation) in spans; see core/trainer.py.
"""

from __future__ import annotations

import math
import random
import threading
import time
from contextlib import contextmanager
from typing import Any, Dict, List, Optional


class _SpanHandle:
    """Mutable holder for a span's device outputs (see Profiler.span)."""

    __slots__ = ("value",)

    def __init__(self):
        self.value = None

    def set(self, value: Any) -> None:
        self.value = value


class _SpanStat:
    __slots__ = ("count", "total", "samples", "maxv", "_rng")

    def __init__(self):
        self.count = 0
        self.total = 0.0
        self.samples: List[float] = []  # uniform reservoir for percentiles
        # exact running max: the worst span must survive even after the
        # reservoir evicts it (tail-latency honesty -- serving is judged
        # on its worst request, not its worst sampled request)
        self.maxv = 0.0
        self._rng = random.Random(0x5EED)

    def add(self, dt: float, cap: int = 4096) -> None:
        self.count += 1
        self.total += dt
        if dt > self.maxv:
            self.maxv = dt
        # reservoir sampling: every span has equal probability of being in
        # the percentile sample, so long runs aren't summarized by their
        # first cap spans (compile/warmup) alone
        if len(self.samples) < cap:
            self.samples.append(dt)
        else:
            j = self._rng.randrange(self.count)
            if j < cap:
                self.samples[j] = dt

    def merge(self, count: int, total: float, samples: List[float],
              maxv: float, cap: int = 4096) -> None:
        """Fold another stat's (count, total, reservoir, max) into this
        one.  Count/total/max are exact.  The merged reservoir is a
        near-uniform sample of the UNION of the two underlying
        populations: when the combined sample fits the cap both sets are
        kept whole; otherwise elements are kept by A-Res weighted
        sampling, each sample weighted by how many real observations it
        represents (``count / len(samples)`` on its side) — a reservoir
        summarizing 10k spans must dominate one summarizing 10, or the
        merged percentiles would skew toward the small rank."""
        if count <= 0:
            return
        mine_n, mine = self.count, self.samples
        self.count += int(count)
        self.total += float(total)
        if maxv > self.maxv:
            self.maxv = float(maxv)
        union = list(mine) + list(samples)
        if len(union) <= cap:
            self.samples = union
            return
        weighted = []
        for src_samples, src_count in ((mine, mine_n), (samples, count)):
            if not src_samples:
                continue
            w = max(1.0, src_count / len(src_samples))
            weighted += [(s, w) for s in src_samples]
        # A-Res: key = u^(1/w); the cap largest keys are a weighted
        # sample without replacement.  Seeded rng: merges are
        # deterministic for a given input order.
        rng = random.Random(0xC0FFEE ^ self.count)
        keyed = sorted(((rng.random() ** (1.0 / w), s)
                        for s, w in weighted), reverse=True)
        self.samples = [s for _k, s in keyed[:cap]]


class Profiler:
    """Named nested wall-clock spans + XLA trace annotations."""

    def __init__(self, sync: bool = False):
        """``sync=True``: spans wrapping device work block until it finishes,
        so durations measure compute rather than async dispatch."""
        self.sync = sync
        self._stats: Dict[str, _SpanStat] = {}
        self._tls = threading.local()
        self._lock = threading.Lock()
        self._trace_dir: Optional[str] = None
        self._comms: Optional[Dict[str, Any]] = None
        self._counters: Dict[str, int] = {}
        # gauge -> [count, sum, min, max, last]
        self._gauges: Dict[str, List[float]] = {}

    def __getstate__(self):
        """Ship-able across processes (the Trainer fan-out pickles its
        profiler): locks/thread-locals/stats stay behind -- a worker
        starts its own clean profile."""
        return {"sync": self.sync}

    def __setstate__(self, state):
        self.__init__(sync=state["sync"])

    # ------------------------------------------------------------------ #
    def _stack(self) -> List[str]:
        if not hasattr(self._tls, "stack"):
            self._tls.stack = []
        return self._tls.stack

    @contextmanager
    def span(self, name: str):
        """Time a block under `name`, nested as parent/child in the report.

        Yields a handle; call ``handle.set(outputs)`` with the block's device
        outputs and a sync-mode profiler will block on them before closing,
        so the span measures compute rather than async dispatch."""
        import jax

        handle = _SpanHandle()
        stack = self._stack()
        full = "/".join(stack + [name])
        stack.append(name)
        t0 = time.perf_counter()
        try:
            with jax.profiler.TraceAnnotation(name):
                yield handle
                if self.sync and handle.value is not None:
                    # graftlint: ok(host-sync) — opt-in sync=True mode:
                    jax.block_until_ready(handle.value)  # measure compute
        finally:
            dt = time.perf_counter() - t0
            stack.pop()
            with self._lock:
                self._stats.setdefault(full, _SpanStat()).add(dt)

    def observe(self, name: str, dt_s: float) -> None:
        """Record an externally timed duration under ``name`` — the same
        statistics as a span without entering one.  Serving metrics time
        request lifecycles (submit -> first token) that are not a single
        with-block on one thread."""
        with self._lock:
            self._stats.setdefault(name, _SpanStat()).add(dt_s)

    # ------------------------------------------------------------------ #
    # Counters & gauges (input-pipeline accounting; data/prefetch.py)     #
    # ------------------------------------------------------------------ #
    def incr(self, name: str, n: int = 1) -> None:
        """Bump a monotonically-increasing counter.  The prefetch
        pipeline counts ``prefetch_starved_steps`` — steps that found no
        batch ready; a nonzero count means the run is input-bound."""
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + n

    def counters(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._counters)

    def gauge(self, name: str, value: float) -> None:
        """Sample an instantaneous level (e.g. ``prefetch_depth``, the
        number of batches ready ahead of the consumer).  Tracks
        count/mean/min/max/last."""
        v = float(value)
        with self._lock:
            g = self._gauges.get(name)
            if g is None:
                self._gauges[name] = [1, v, v, v, v]
            else:
                g[0] += 1
                g[1] += v
                g[2] = min(g[2], v)
                g[3] = max(g[3], v)
                g[4] = v

    def gauges(self) -> Dict[str, Dict[str, float]]:
        """name -> {count, mean, min, max, last}."""
        with self._lock:
            items = {k: list(v) for k, v in self._gauges.items()}
        return {k: {"count": int(c), "mean": s / max(c, 1), "min": lo,
                    "max": hi, "last": last}
                for k, (c, s, lo, hi, last) in items.items()}

    # ------------------------------------------------------------------ #
    # Comms accounting (bytes-on-wire; parallel/collectives.py)           #
    # ------------------------------------------------------------------ #
    def record_comms(self, per_step: Dict[str, Any]) -> None:
        """Attach a per-step bytes-on-wire record for the gradient
        exchange (``collectives.wire_bytes_per_step`` shape: baseline
        fp32 bytes, exchange bytes, compression_ratio, ...).  Analytic,
        not sampled — collective payload sizes are static per compiled
        step, so the honest number is computed once at compile time."""
        with self._lock:
            self._comms = dict(per_step)

    def comms(self) -> Optional[Dict[str, Any]]:
        """The last recorded gradient-exchange wire accounting (None when
        no compression-enabled trainer compiled against this profiler)."""
        with self._lock:
            return dict(self._comms) if self._comms is not None else None

    # ------------------------------------------------------------------ #
    # Cross-process merge (telemetry/registry.py)                         #
    # ------------------------------------------------------------------ #
    def export_state(self) -> Dict[str, Any]:
        """A picklable/JSON-able snapshot of everything this profiler
        accumulated — span stats WITH their raw reservoirs (percentile
        merging needs samples, not quantiles), counters, gauges, and the
        comms record.  The cross-rank telemetry gather ships this shape
        home so the driver can ``merge()`` every rank into one report."""
        with self._lock:
            return {
                "stats": {name: {"count": st.count, "total": st.total,
                                 "samples": list(st.samples),
                                 "max": st.maxv}
                          for name, st in self._stats.items()},
                "counters": dict(self._counters),
                "gauges": {k: list(v) for k, v in self._gauges.items()},
                "comms": (dict(self._comms) if self._comms is not None
                          else None),
            }

    def merge(self, other: Any) -> "Profiler":
        """Fold another profiler (or an ``export_state()`` dict from one)
        into this one.  Span counts/totals/maxes are exact; reservoirs
        merge count-weighted (see ``_SpanStat.merge``); counters sum;
        gauges combine count/sum/min/max with the other side's ``last``
        winning (merge order = recency order by convention); the comms
        record is adopted when this profiler has none (it is analytic
        and identical across SPMD ranks).  Returns self for chaining."""
        state = other.export_state() if isinstance(other, Profiler) \
            else other
        if not isinstance(state, dict):
            raise TypeError(
                f"Profiler.merge takes a Profiler or export_state() "
                f"dict, got {type(other).__name__}")
        with self._lock:
            for name, row in (state.get("stats") or {}).items():
                st = self._stats.setdefault(name, _SpanStat())
                st.merge(int(row.get("count", 0)),
                         float(row.get("total", 0.0)),
                         list(row.get("samples") or ()),
                         float(row.get("max", 0.0)))
            for name, n in (state.get("counters") or {}).items():
                self._counters[name] = self._counters.get(name, 0) + int(n)
            for name, g in (state.get("gauges") or {}).items():
                c, s, lo, hi, last = g
                mine = self._gauges.get(name)
                if mine is None:
                    self._gauges[name] = [int(c), float(s), float(lo),
                                          float(hi), float(last)]
                else:
                    mine[0] += int(c)
                    mine[1] += float(s)
                    mine[2] = min(mine[2], float(lo))
                    mine[3] = max(mine[3], float(hi))
                    if c:
                        mine[4] = float(last)
            if self._comms is None and state.get("comms") is not None:
                self._comms = dict(state["comms"])
        return self

    # ------------------------------------------------------------------ #
    def summary(self) -> Dict[str, Dict[str, float]]:
        """name -> {count, total_s, mean_s, p50_s, p95_s, p99_s, max_s}.

        Percentiles come from the uniform reservoir; ``max_s`` is the
        exact running maximum (tail latency is judged on the worst span,
        which the reservoir may have evicted)."""
        out: Dict[str, Dict[str, float]] = {}
        with self._lock:
            items = [(name, st.count, st.total, sorted(st.samples),
                      st.maxv) for name, st in self._stats.items()]
        for name, count, total, xs, maxv in items:
            pick = (lambda q: xs[min(len(xs) - 1,
                                     int(math.ceil(q * len(xs))) - 1)]
                    if xs else 0.0)
            out[name] = {
                "count": count,
                "total_s": total,
                "mean_s": total / max(count, 1),
                "p50_s": pick(0.50),
                "p95_s": pick(0.95),
                "p99_s": pick(0.99),
                "max_s": maxv,
            }
        return out

    def describe(self) -> str:
        """Human-readable table, longest total first."""
        rows = sorted(self.summary().items(),
                      key=lambda kv: -kv[1]["total_s"])
        lines = [f"{'span':<40} {'count':>7} {'total':>9} {'mean':>9} "
                 f"{'p50':>9} {'p95':>9} {'p99':>9} {'max':>9}"]
        for name, s in rows:
            lines.append(
                f"{name:<40} {s['count']:>7d} {s['total_s']:>8.3f}s "
                f"{s['mean_s'] * 1e3:>7.2f}ms {s['p50_s'] * 1e3:>7.2f}ms "
                f"{s['p95_s'] * 1e3:>7.2f}ms {s['p99_s'] * 1e3:>7.2f}ms "
                f"{s['max_s'] * 1e3:>7.2f}ms")
        for name, n in sorted(self.counters().items()):
            lines.append(f"counter {name:<32} {n:>7d}")
        for name, g in sorted(self.gauges().items()):
            lines.append(
                f"gauge   {name:<32} last={g['last']:g} "
                f"mean={g['mean']:.2f} min={g['min']:g} max={g['max']:g}")
        starved = self.counters().get("prefetch_starved_steps", 0)
        if starved:
            steps = self.summary().get("h2d_wait", {}).get("count", 0)
            lines.append(
                f"input pipeline: {starved}/{steps} steps found the "
                "prefetch queue empty — run is input-bound (raise "
                "prefetch_batches or cheapen the host pipeline)")
        c = self.comms()
        if c is not None:
            lines.append(
                f"grad exchange [{c.get('mode')}]: "
                f"{c.get('exchange_bytes_per_step', 0) / 1e6:.2f} MB/step "
                f"on wire vs {c.get('baseline_fp32_bytes_per_step', 0) / 1e6:.2f}"
                f" MB fp32 ({c.get('compression_ratio')}x overall, "
                f"{c.get('compressed_ratio')}x on compressed leaves)")
        return "\n".join(lines)

    def reset(self) -> None:
        with self._lock:
            self._stats.clear()
            self._comms = None
            self._counters.clear()
            self._gauges.clear()

    # ------------------------------------------------------------------ #
    # Device traces (TensorBoard / XProf)                                #
    # ------------------------------------------------------------------ #
    def start_trace(self, log_dir: str) -> None:
        """Begin an XPlane device trace (view in TensorBoard's profiler)."""
        import jax

        if self._trace_dir is not None:
            raise RuntimeError(f"trace already running -> {self._trace_dir}")
        jax.profiler.start_trace(log_dir)
        self._trace_dir = log_dir

    def stop_trace(self) -> Optional[str]:
        import jax

        if self._trace_dir is None:
            return None
        jax.profiler.stop_trace()
        d, self._trace_dir = self._trace_dir, None
        return d

    @contextmanager
    def trace(self, log_dir: str):
        self.start_trace(log_dir)
        try:
            yield
        finally:
            self.stop_trace()


def trace_events(trace_dir: str) -> List[Dict[str, Any]]:
    """Device-side op events from the newest ``*.trace.json.gz`` under an
    XPlane trace directory (written by ``Profiler.start_trace``/
    ``jax.profiler.start_trace``).

    Each event: ``{name, ts_us, dur_us, end_us, category, bytes, flops}``
    with durations from the DEVICE clock (``device_duration_ps``) -- on a
    tunneled PjRt link these are the honest on-chip times while host
    wall-clock mostly measures dispatch.  Host/python events are
    excluded."""
    import glob
    import gzip
    import json
    import os

    files = sorted(glob.glob(os.path.join(trace_dir, "**",
                                          "*.trace.json.gz"),
                             recursive=True), key=os.path.getmtime)
    if not files:
        raise FileNotFoundError(f"no trace.json.gz under {trace_dir}")
    with gzip.open(files[-1]) as f:
        t = json.load(f)
    out: List[Dict[str, Any]] = []
    for e in t.get("traceEvents", []):
        a = e.get("args") or {}
        if e.get("ph") != "X" or "device_duration_ps" not in a:
            continue
        ts = float(a.get("device_offset_ps", 0)) / 1e6
        dur = float(a["device_duration_ps"]) / 1e6
        out.append({
            "name": e["name"], "ts_us": ts, "dur_us": dur,
            "end_us": ts + dur,
            # timeline identity: events nest only WITHIN one device
            # timeline; concurrent chips must not read as parent/child
            "pid": e.get("pid"), "tid": e.get("tid"),
            "category": a.get("hlo_category", "?"),
            "bytes": int(a.get("raw_bytes_accessed",
                               a.get("bytes_accessed", 0) or 0)),
            "flops": int(a.get("model_flops", 0) or 0),
        })
    out.sort(key=lambda ev: (ev["ts_us"], -ev["dur_us"]))
    return out


def trace_op_summary(trace_dir: str, top: int = 0) -> Dict[str, Any]:
    """Roofline-style aggregation of a device trace: EXCLUSIVE (self)
    time per op and per HLO category, with achieved GB/s / TF/s.

    Nested events (``while`` bodies, fusions inside scans) are resolved
    by interval containment, so a scan's children are not double-counted
    against their parent.  Returns ``{"total_ms", "by_category":
    {cat: {self_ms, gbps, tfs, pct}}, "ops": [top-N rows]}``."""
    evs = trace_events(trace_dir)
    # stack-based nesting, one stack PER DEVICE (pid): concurrent chips
    # overlap in time without any parent/child relation, but within one
    # device the module/step wrapper events genuinely contain the op
    # events even when exported on different trace lines (tids)
    stacks: Dict[Any, List[Dict[str, Any]]] = {}
    for e in evs:
        stack = stacks.setdefault(e["pid"], [])
        while stack and stack[-1]["end_us"] <= e["ts_us"] + 1e-6:
            stack.pop()
        e["_child_dur"] = 0.0
        if stack:
            stack[-1]["_child_dur"] += e["dur_us"]
        stack.append(e)
    agg: Dict[Any, List[float]] = {}
    for e in evs:
        key = (e["category"], e["name"])
        row = agg.setdefault(key, [0.0, 0, 0, 0])
        row[0] += max(0.0, e["dur_us"] - e["_child_dur"])
        row[1] += 1
        row[2] += e["bytes"]
        row[3] += e["flops"]
    total_us = sum(v[0] for v in agg.values())

    def rates(dur_us: float, nbytes: int, nflops: int) -> Dict[str, float]:
        secs = dur_us * 1e-6
        return {"gbps": nbytes / secs / 1e9 if secs else 0.0,
                "tfs": nflops / secs / 1e12 if secs else 0.0}

    cats: Dict[str, List[float]] = {}
    for (cat, _name), (dur, _n, b, fl) in agg.items():
        c = cats.setdefault(cat, [0.0, 0, 0])
        c[0] += dur
        c[1] += b
        c[2] += fl
    by_category = {
        cat: {"self_ms": dur / 1e3,
              "pct": 100.0 * dur / total_us if total_us else 0.0,
              **rates(dur, b, fl)}
        for cat, (dur, b, fl) in cats.items()}
    rows = sorted(agg.items(), key=lambda kv: -kv[1][0])
    if top:
        rows = rows[:top]
    ops = [{"category": cat, "name": name, "self_ms": dur / 1e3,
            "count": n,
            "pct": 100.0 * dur / total_us if total_us else 0.0,
            **rates(dur, b, fl)}
           for (cat, name), (dur, n, b, fl) in rows]
    return {"total_ms": total_us / 1e3, "by_category": by_category,
            "ops": ops}


def device_memory_stats() -> List[Dict[str, Any]]:
    """Per-device PjRt memory counters (bytes_in_use, peak, limit...).

    Empty dicts on backends that don't expose stats (CPU).  The perf
    observatory's HBM ledger (telemetry/perf.py) builds per-pool
    attribution on top: ``device_bytes_in_use()`` below is its ground
    truth where the backend reports real HBM."""
    import jax

    out = []
    for d in jax.local_devices():
        try:
            out.append(dict(d.memory_stats() or {}))
        except Exception:
            out.append({})
    return out


def device_bytes_in_use() -> Optional[int]:
    """Summed PjRt ``bytes_in_use`` across local devices, or None on
    backends that expose no memory stats (CPU) — callers fall back to
    live-array accounting (``telemetry.perf.placed_bytes_total``)."""
    vals = [s.get("bytes_in_use") for s in device_memory_stats()
            if s.get("bytes_in_use")]
    return int(sum(vals)) if vals else None


# --------------------------------------------------------------------- #
# FLOPs / MFU estimation                                                 #
# --------------------------------------------------------------------- #
def flops_estimate(fn, *args, **kwargs) -> Optional[float]:
    """FLOPs for one invocation of (jit-able) ``fn`` on these args, from
    XLA's compiled cost analysis.  None when the backend reports no
    estimate.  Trace-only: nothing executes on device."""
    import jax

    # graftlint: ok(retrace) — trace-only cost estimate, once per bench
    compiled = jax.jit(fn).lower(*args, **kwargs).compile()
    try:
        analyses = compiled.cost_analysis()
    except Exception:
        return None
    if not analyses:
        return None
    a = analyses[0] if isinstance(analyses, (list, tuple)) else analyses
    flops = a.get("flops")
    return float(flops) if flops else None


def mfu(flops_per_step: float, step_time_s: float,
        peak_flops: Optional[float] = None) -> float:
    """Model FLOPs utilization: achieved/peak.  ``peak_flops`` defaults to
    a per-chip bf16 estimate for the current backend (v5e ~197 TFLOP/s;
    0.0 is returned when unknown so callers can gate on it)."""
    import jax

    if peak_flops is None:
        kind = (jax.devices()[0].device_kind or "").lower()
        peaks = {"v5 lite": 197e12, "v5litepod": 197e12, "v5e": 197e12,
                 "v4": 275e12, "v5p": 459e12,
                 "v6 lite": 918e12, "v6e": 918e12}
        peak_flops = next((v for k, v in peaks.items() if k in kind), 0.0)
        if not peak_flops:
            return 0.0
    return flops_per_step / (step_time_s * peak_flops)
