"""Deterministic seeding across driver and workers.

Parity with the reference's ``PL_GLOBAL_SEED`` propagation into every Ray
actor (reference: ray_lightning/ray_ddp.py:154-159).  We honor both that
variable and our own, and return a jax PRNG key -- the TPU-native seed object.
"""

from __future__ import annotations

import os
import random
from typing import Optional

import jax
import numpy as np

from ..analysis import knobs
from .logging import log

SEED_ENV_VARS = ("RLA_TPU_GLOBAL_SEED", "PL_GLOBAL_SEED")


def seed_everything(seed: Optional[int] = None) -> int:
    """Seed python/numpy RNGs, export the seed for child processes."""
    if seed is None:
        # our knob first (typed, warn-and-default on malformed), then
        # the reference-parity PL name (non-RLA: raw read is sanctioned)
        seed = knobs.get_int("RLA_TPU_GLOBAL_SEED", None, malformed=0)
    if seed is None:
        raw = os.environ.get("PL_GLOBAL_SEED")
        if raw:
            try:
                seed = int(raw)
            except ValueError:
                log.warning("bad PL_GLOBAL_SEED=%r; using 0", raw)
                seed = 0
        else:
            seed = 0
    random.seed(seed)
    np.random.seed(seed % (2 ** 32))
    for var in SEED_ENV_VARS:
        os.environ[var] = str(seed)
    return seed


def rng_from_seed(seed: int) -> jax.Array:
    return jax.random.PRNGKey(seed)
