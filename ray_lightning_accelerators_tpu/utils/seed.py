"""Deterministic seeding across driver and workers.

Parity with the reference's ``PL_GLOBAL_SEED`` propagation into every Ray
actor (reference: ray_lightning/ray_ddp.py:154-159).  We honor both that
variable and our own, and return a jax PRNG key -- the TPU-native seed object.
"""

from __future__ import annotations

import os
import random
from typing import Optional

import jax
import numpy as np

SEED_ENV_VARS = ("RLA_TPU_GLOBAL_SEED", "PL_GLOBAL_SEED")


def seed_everything(seed: Optional[int] = None) -> int:
    """Seed python/numpy RNGs, export the seed for child processes."""
    if seed is None:
        for var in SEED_ENV_VARS:
            if os.environ.get(var):
                seed = int(os.environ[var])
                break
        else:
            seed = 0
    random.seed(seed)
    np.random.seed(seed % (2 ** 32))
    for var in SEED_ENV_VARS:
        os.environ[var] = str(seed)
    return seed


def rng_from_seed(seed: int) -> jax.Array:
    return jax.random.PRNGKey(seed)
