"""ray_lightning_accelerators_tpu: a TPU-native distributed training framework
with the capability surface of `ray_lightning` (reference:
ray_lightning/__init__.py:1-4 exports RayAccelerator + HorovodRayAccelerator).

Public API adds the full trainer stack the reference borrowed from PTL, the
`RayTPUAccelerator` north-star class, and the Tune-equivalent subsystem.
"""

from .accelerators.base import Accelerator
from .accelerators.tpu import (HorovodRayAccelerator, RayAccelerator,
                               RayTPUAccelerator)
from .core.callbacks import Callback, EarlyStopping, ModelCheckpoint
from .core.module import TpuModule
from .core.state import TrainState
from .core.trainer import Trainer
from .data.datamodule import DataModule
from .data.loader import (ArrayDataset, DataLoader, Dataset,
                          IterableDataset, RandomDataset, ShardedSampler)
from .data.prefetch import (DevicePrefetcher, PrefetchIterator,
                            prefetch_pipeline)
from .parallel.collectives import TensorShardedParamsError
from .parallel.mesh import MeshConfig, build_mesh
from .parallel.ring_attention import ring_attention, ring_attention_sharded
from .parallel.ulysses import ulysses_attention, ulysses_attention_sharded
from .runtime.elastic import ElasticResizeError, ElasticRunner
from .runtime.preemption import Preempted, PreemptionNotice, get_notice
from .runtime.session import get_actor_rank, init_session, put_queue
from .utils.profiler import Profiler, device_memory_stats
from . import models  # lazy family exports (models/__init__.py PEP 562)
from . import serve
from . import telemetry
from .serve import ServeEngine, ServeReplicas
from .telemetry import (FlightRecorder, MetricsRegistry,
                        PerfObservatory)
from . import tune
from .tune import TuneReportCallback, TuneReportCheckpointCallback
from .utils import schedules

__version__ = "0.1.0"

__all__ = [
    "Accelerator", "RayAccelerator", "RayTPUAccelerator",
    "HorovodRayAccelerator",
    "Trainer", "TpuModule", "TrainState",
    "Callback", "EarlyStopping", "ModelCheckpoint",
    "DataModule", "DataLoader", "Dataset", "IterableDataset", "ArrayDataset",
    "RandomDataset", "ShardedSampler",
    "PrefetchIterator", "DevicePrefetcher", "prefetch_pipeline",
    "MeshConfig", "build_mesh",
    "ulysses_attention", "ulysses_attention_sharded",
    "ring_attention", "ring_attention_sharded",
    "ElasticRunner", "ElasticResizeError", "TensorShardedParamsError",
    "Preempted", "PreemptionNotice", "get_notice",
    "get_actor_rank", "init_session", "put_queue",
    "Profiler", "device_memory_stats",
    "models", "schedules",
    "serve", "ServeEngine", "ServeReplicas",
    "telemetry", "FlightRecorder", "MetricsRegistry",
    "PerfObservatory",
    "tune", "TuneReportCallback", "TuneReportCheckpointCallback",
]
