"""The three public accelerators, name-for-name with the reference's surface.

- ``RayTPUAccelerator`` -- the north-star class (BASELINE.json): SPMD data
  parallelism over `num_workers` TPU devices, optional FSDP, optional model
  axes for tensor/sequence/pipeline parallelism.
- ``RayAccelerator``   -- parity name for the reference's DDP plugin
  (reference: ray_lightning/ray_ddp.py:34-97).  Same kwargs
  (num_workers, num_cpus_per_worker, use_gpu, init_hook); maps to the same
  SPMD path.  ``use_gpu`` has no meaning on TPU and is accepted + ignored.
- ``HorovodRayAccelerator`` -- parity name for the reference's Horovod plugin
  (reference: ray_lightning/ray_horovod.py:40-102) with its hosts x slots
  topology.  The ring-allreduce semantics map onto the same ICI collectives:
  XLA's all-reduce over a (hosts*slots)-way data axis IS a ring (or better,
  torus) reduction on TPU interconnect -- there is no separate protocol to
  implement, which is precisely the TPU-native redesign.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax

from ..parallel import mesh as mesh_lib
from ..utils.logging import log
from .base import Accelerator


class RayTPUAccelerator(Accelerator):
    """SPMD data-parallel (+ optional model-parallel axes) over TPU devices.

    Args:
      num_workers: number of batch shards (device count used for DP).  None =
        all devices not consumed by model axes.
      use_fsdp: shard params/optimizer over the DP axis (ZeRO-3).  The axis is
        relabeled `fsdp` so batch stays sharded over it either way.
      tensor/sequence/pipeline/expert: model-parallel axis sizes.
      init_hook: callable run once at setup on every process (parity with
        reference init_hook, ray_lightning/ray_ddp.py:58-59,106-107).
    """

    def __init__(self, num_workers: Optional[int] = None, *,
                 use_fsdp: bool = False, tensor: int = 1, sequence: int = 1,
                 pipeline: int = 1, expert: int = 1,
                 dcn_data: int = 1, dcn_pipeline: int = 1,
                 init_hook: Optional[Callable[[], None]] = None,
                 devices: Optional[list] = None,
                 num_hosts: int = 1,
                 agents: Optional[list] = None):
        dp = -1 if num_workers is None else num_workers
        if use_fsdp:
            cfg = mesh_lib.MeshConfig(data=1, fsdp=dp, tensor=tensor,
                                      sequence=sequence, pipeline=pipeline,
                                      expert=expert)
        else:
            cfg = mesh_lib.MeshConfig(data=dp, tensor=tensor,
                                      sequence=sequence, pipeline=pipeline,
                                      expert=expert)
        super().__init__(cfg, init_hook=init_hook, use_fsdp=use_fsdp,
                         dcn_data=dcn_data, dcn_pipeline=dcn_pipeline,
                         devices=devices)
        self.num_workers = num_workers
        # multi-host launch plan: with num_hosts > 1 and per-host agents
        # (kwarg or RLA_TPU_AGENTS env, started via `rla-tpu agent`),
        # Trainer.fit fans out one process per host through the actor
        # runtime (the reference's multi-node Ray placement,
        # ray_lightning/ray_ddp.py:92-97)
        self.num_hosts = num_hosts
        self.agents = list(agents) if agents else None

    def launch_spec(self):
        from ..runtime.agent import agents_from_env
        if self.num_hosts <= 1:
            # num_hosts == 1 with EXPLICIT agents still fans out: "run my
            # training on that one (possibly remote, chip-holding) host"
            # is the single-host analog of the reference placing its one
            # actor wherever the resources are (ray_ddp.py:92-97).  Only
            # the kwarg opts in -- an ambient $RLA_TPU_AGENTS left over
            # from a multi-host run must not silently redirect (or break)
            # default in-process training.
            if not self.agents:
                return None
            agents = self.agents
        else:
            agents = self.agents or agents_from_env()
        if agents is None:
            log.warning(
                "%s(num_hosts=%d) has no host agents configured (pass "
                "agents=... or set RLA_TPU_AGENTS, agents started via "
                "`rla-tpu agent`); degrading to single-process training "
                "over local devices", type(self).__name__, self.num_hosts)
            return None
        if len(agents) != self.num_hosts:
            raise ValueError(
                f"num_hosts={self.num_hosts} but {len(agents)} agent "
                f"addresses were configured ({agents}); the contract is "
                f"one process per host -- pass exactly num_hosts agents")
        if self.num_workers is not None and \
                self.num_workers % self.num_hosts != 0:
            raise ValueError(
                f"num_workers={self.num_workers} must be divisible by "
                f"num_hosts={self.num_hosts}")
        per_host = (None if self.num_workers is None
                    else self.num_workers // self.num_hosts)
        return {"num_processes": self.num_hosts, "agents": agents,
                "devices_per_host": per_host}

    def select_devices(self):
        # base handles the fully-specified case (truncation + multi-process
        # guard); decorate its error with the num_workers framing
        try:
            return super().select_devices()
        except ValueError as e:
            if self.num_workers is not None and "are visible" in str(e):
                total_model = (self.mesh_config.tensor *
                               self.mesh_config.sequence *
                               self.mesh_config.pipeline *
                               self.mesh_config.expert)
                raise ValueError(
                    f"requested {self.num_workers * total_model} devices "
                    f"(num_workers={self.num_workers} x model={total_model}) "
                    f"but only {len(jax.devices())} are visible") from e
            raise


class RayAccelerator(RayTPUAccelerator):
    """Parity-named DDP accelerator (reference: ray_lightning/ray_ddp.py:34)."""

    def __init__(self, num_workers: int = 1, num_cpus_per_worker: int = 1,
                 use_gpu: bool = False,
                 init_hook: Optional[Callable[[], None]] = None, **kwargs):
        if use_gpu:
            log.warning("RayAccelerator(use_gpu=True) requested on a TPU "
                        "framework; training runs on the available XLA "
                        "devices instead.")
        self.num_cpus_per_worker = num_cpus_per_worker
        self.use_gpu = use_gpu
        super().__init__(num_workers=num_workers, init_hook=init_hook, **kwargs)


class HorovodRayAccelerator(RayTPUAccelerator):
    """Parity-named hosts x slots accelerator
    (reference: ray_lightning/ray_horovod.py:40, topology at :84-85).

    `num_hosts * num_slots` total batch shards.  `num_hosts` binds to real
    process topology: with per-host agents configured, Trainer.fit places
    one process per host (the reference's hosts x slots actor placement,
    ray_horovod.py:107-114); inside an already-formed multi-process world
    a mismatched num_hosts raises.  Single-process without agents it
    degrades (with a warning) to plain DP over local devices, same as the
    reference on one node.
    """

    def __init__(self, num_hosts: int = 1, num_slots: int = 1,
                 use_gpu: bool = False,
                 init_hook: Optional[Callable[[], None]] = None, **kwargs):
        self.num_slots = num_slots
        self.use_gpu = use_gpu
        super().__init__(num_workers=num_hosts * num_slots,
                         init_hook=init_hook, num_hosts=num_hosts, **kwargs)

    def launch_spec(self):
        spec = super().launch_spec()
        if spec is not None:
            spec["devices_per_host"] = self.num_slots
        return spec
