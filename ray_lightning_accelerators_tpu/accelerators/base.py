"""Accelerator base: the strategy object users hand to the Trainer.

Capability analog of the reference's accelerator plugins
(``RayAccelerator``, reference: ray_lightning/ray_ddp.py:34-97;
``HorovodRayAccelerator``, reference: ray_lightning/ray_horovod.py:40-102):
a constructor-level object that decides the distributed topology while the
user's model and trainer code stay unchanged.

TPU-native redesign: instead of owning processes and process groups, an
Accelerator owns a **device mesh** and the sharding rules over it.  XLA
derives the collectives; no rendezvous, no per-gradient hooks.  Process-level
fan-out (one process per TPU host) is the runtime layer's job
(`runtime/actors.py`) -- the accelerator only describes topology.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

import jax
from jax.sharding import Mesh, NamedSharding

from ..parallel import mesh as mesh_lib
from ..parallel import plan as plan_lib
from ..parallel import sharding as sharding_lib
from ..utils.logging import log


class Accelerator:
    """Describes topology + shardings.  Subclasses set `mesh_config`."""

    def __init__(self, mesh_config: Optional[mesh_lib.MeshConfig] = None,
                 init_hook: Optional[Callable[[], None]] = None,
                 use_fsdp: bool = False,
                 dcn_data: int = 1, dcn_pipeline: int = 1,
                 devices: Optional[list] = None):
        self.mesh_config = mesh_config or mesh_lib.MeshConfig()
        self.init_hook = init_hook
        self.use_fsdp = use_fsdp
        # explicit device subset (e.g. a tune trial's partition,
        # tune.trial_devices()); None = all visible devices
        self.devices = list(devices) if devices is not None else None
        # multi-slice: replicate the per-slice (ICI) mesh across slices on
        # the data / pipeline axes over DCN (parallel/mesh.py
        # build_hybrid_mesh); 1 x 1 = single slice
        self.dcn_data = dcn_data
        self.dcn_pipeline = dcn_pipeline
        # large leaves infer_fsdp_shardings had to warn-and-replicate in
        # the last param_shardings resolution (observability: telemetry
        # event `fsdp_fallback` + trainer-side profiler counter)
        self.last_fsdp_fallbacks: list = []
        self._mesh: Optional[Mesh] = None

    # ---------------------------------------------------------------- #
    # Topology                                                          #
    # ---------------------------------------------------------------- #
    def select_devices(self) -> list:
        devices = (list(self.devices) if self.devices is not None
                   else list(jax.devices()))
        cfg = self.mesh_config
        sizes = (cfg.data, cfg.fsdp, cfg.pipeline, cfg.expert, cfg.sequence,
                 cfg.tensor)
        if -1 not in sizes:  # fully specified mesh
            import math
            need = math.prod(sizes)
            if need > len(devices):
                raise ValueError(f"mesh needs {need} devices but only "
                                 f"{len(devices)} are visible")
            if need < len(devices):
                if jax.process_count() > 1:
                    # truncating jax.devices() across processes would build a
                    # mesh that excludes some hosts' local devices entirely
                    # (their device_put/collectives would then hang or fail)
                    raise ValueError(
                        f"mesh covers {need} of {len(devices)} devices; in "
                        f"multi-process mode the mesh must span every "
                        f"process -- size the mesh to the full device count "
                        f"or pass an explicit device list")
                devices = devices[:need]
        return devices

    def build_mesh(self) -> Mesh:
        if self._mesh is None:
            if self.dcn_data * self.dcn_pipeline > 1:
                # multi-slice spans every visible device; no truncation
                self._mesh = mesh_lib.build_hybrid_mesh(
                    self.mesh_config, self.dcn_data, self.dcn_pipeline)
            else:
                self._mesh = mesh_lib.build_mesh(self.mesh_config,
                                                 self.select_devices())
        return self._mesh

    @property
    def mesh(self) -> Mesh:
        return self.build_mesh()

    @property
    def world_size(self) -> int:
        """Number of batch shards (DDP world-size analog)."""
        return mesh_lib.data_parallel_size(self.build_mesh())

    @property
    def num_processes(self) -> int:
        return jax.process_count()

    # ---------------------------------------------------------------- #
    # Shardings                                                         #
    # ---------------------------------------------------------------- #
    def batch_sharding(self, mesh: Mesh) -> NamedSharding:
        return mesh_lib.batch_sharding(mesh)

    def param_shardings(self, mesh: Mesh, params: Any, module: Any = None,
                        report_fallbacks: bool = True) -> Any:
        """Param half of ``state_shardings`` (factored out so the trainer
        can resolve the compressed-exchange layout BEFORE building
        residual state).

        Priority: a module exposing ``param_logical_axes()`` gets
        rule-based shardings (tp/fsdp/sp-aware); otherwise ``use_fsdp``
        shards large leaves over the fsdp axis; otherwise everything
        replicates (pure DP).  When ``infer_fsdp_shardings`` has to
        warn-and-replicate a large leaf (no fsdp-divisible dim), each
        fallback lands in ``last_fsdp_fallbacks`` and — unless
        ``report_fallbacks=False`` (probe calls) — emits a telemetry
        event (kind ``fsdp_fallback``) so the silent loss of FSDP
        memory savings shows up in the unified MetricsRegistry export."""
        repl = plan_lib.replicated_sharding(mesh)
        if report_fallbacks:
            # every REPORTING resolution re-records its fallbacks, so a
            # later fit on this accelerator never mirrors a previous
            # run's count into the profiler
            self.last_fsdp_fallbacks = []
        if module is not None and hasattr(module, "param_logical_axes"):
            return sharding_lib.tree_logical_to_shardings(
                mesh, module.param_logical_axes())
        if self.use_fsdp:
            fallbacks = []

            def on_fallback(name, leaf):
                fallbacks.append({"param": name,
                                  "shape": list(map(int, leaf.shape))})

            sh = sharding_lib.infer_fsdp_shardings(
                params, mesh, on_fallback=on_fallback)
            if report_fallbacks:
                from ..telemetry import recorder as telemetry
                for fb in fallbacks:
                    log.warning(
                        "use_fsdp: param %s %s has no dim divisible by "
                        "the fsdp axis; it (and its optimizer moments) "
                        "stay REPLICATED — no FSDP memory saving for "
                        "this leaf", fb["param"], tuple(fb["shape"]))
                    telemetry.emit("fsdp_fallback", **fb)
                self.last_fsdp_fallbacks = fallbacks
            return sh
        return jax.tree.map(lambda _: repl, params)

    def state_shardings(self, mesh: Mesh, state: Any, module: Any = None,
                        tx: Any = None,
                        report_fallbacks: bool = True) -> Any:
        """Sharding pytree for the TrainState (see ``param_shardings``
        for the param layout rules).  Optimizer moments inherit each
        param's layout via ``optax.tree_map_params``."""
        import optax as _optax

        repl = plan_lib.replicated_sharding(mesh)
        param_sh = self.param_shardings(mesh, state.params, module=module,
                                        report_fallbacks=report_fallbacks)

        params_sharded = any(
            not s.is_fully_replicated for s in jax.tree.leaves(param_sh))
        if tx is not None:
            try:
                opt_sh = _optax.tree_map_params(
                    tx, lambda _s, p_sh: p_sh, state.opt_state, param_sh,
                    transform_non_params=lambda _s: repl)
            except Exception as e:  # exotic optimizer state shapes
                opt_sh = jax.tree.map(lambda _: repl, state.opt_state)
                if params_sharded:
                    log.warning(
                        "could not map param shardings onto the optimizer "
                        "state (%s: %s); optimizer moments will be fully "
                        "REPLICATED -- expect ~3x param memory per device, "
                        "defeating FSDP savings", type(e).__name__, e)
                    if report_fallbacks:
                        fb = {"param": "<opt_state>",
                              "reason": f"{type(e).__name__}: {e}"}
                        # keep the profiler counter (fed from
                        # last_fsdp_fallbacks) in lockstep with the
                        # event tally
                        self.last_fsdp_fallbacks.append(fb)
                        from ..telemetry import recorder as telemetry
                        telemetry.emit("fsdp_fallback", **fb)
        else:
            opt_sh = jax.tree.map(lambda _: repl, state.opt_state)
            if params_sharded:
                log.warning("state_shardings called without tx; optimizer "
                            "moments will be fully replicated")
        # gradient-compression state (parallel/collectives.py): residual
        # trees are ALWAYS stacked per-replica ([n, ...], dim 0 over the
        # batch axes — both the DP and the shard-local FSDP layouts
        # carry the replica dim, and the exchange's in_specs expect it);
        # grad_accum is stacked under pure DP ([n, *param] — one more
        # dim than its param, so the shape test below cannot collide)
        # but PARAM-shaped (post-exchange, shard-local) under compressed
        # FSDP, where it inherits the param layout; the layouts are
        # authored in parallel/plan.py (the single spec-producing module)
        stacked = plan_lib.stacked_replica_sharding(mesh)

        def accum_sh(tree):
            if tree is None:
                return None
            return jax.tree.map(
                lambda leaf, p, p_sh: (
                    p_sh if tuple(getattr(leaf, "shape", ()))
                    == tuple(getattr(p, "shape", ())) else stacked),
                tree, state.params, param_sh)

        extras = {
            "residual": (None if getattr(state, "residual", None) is None
                         else jax.tree.map(lambda _: stacked,
                                           state.residual)),
            "grad_accum": accum_sh(getattr(state, "grad_accum", None)),
            # guardian vector (runtime/guardian.py): one tiny replicated
            # f32 leaf; None when the guard is off (pre-guardian pytree)
            "guard_ema": (None if getattr(state, "guard_ema", None) is None
                          else repl),
        }
        return state.replace(step=repl, params=param_sh, opt_state=opt_sh,
                             rng=repl, **extras)

    # ---------------------------------------------------------------- #
    # Multi-host launch plan                                            #
    # ---------------------------------------------------------------- #
    def launch_spec(self) -> Optional[Dict[str, Any]]:
        """A multi-machine launch plan for the Trainer's fan-out path, or
        None to train in-process.  Subclasses with ``num_hosts``
        implement it (`accelerators/tpu.py`)."""
        return None

    def validate_process_topology(self) -> None:
        """Inside a formed multi-process world, a host count that doesn't
        match the world is a configuration error, not something to degrade
        silently (reference really placed hosts x slots workers,
        ray_lightning/ray_horovod.py:107-114)."""
        num_hosts = getattr(self, "num_hosts", None)
        if num_hosts and num_hosts > 1 and jax.process_count() > 1 \
                and num_hosts != jax.process_count():
            raise ValueError(
                f"num_hosts={num_hosts} but this distributed world has "
                f"{jax.process_count()} processes; size num_hosts to the "
                f"process count (one process per host)")

    def __getstate__(self) -> Dict[str, Any]:
        """Ship-able state for the multi-machine fan-out: built meshes and
        explicit device lists hold live Device objects that are only
        meaningful in this process (the reference drops live actor handles
        the same way, ray_lightning/ray_ddp.py:123-130)."""
        state = dict(self.__dict__)
        state["_mesh"] = None
        if state.get("devices") is not None:
            log.warning("explicit device list does not transfer across "
                        "processes; remote workers will use all their "
                        "visible devices")
            state["devices"] = None
        return state

    # ---------------------------------------------------------------- #
    # Lifecycle + parity surface                                        #
    # ---------------------------------------------------------------- #
    def setup_environment(self) -> None:
        if self.init_hook is not None:
            self.init_hook()

    def teardown(self) -> None:
        """Release device state so fit/test can run twice from one script
        (parity with reference teardown, ray_lightning/ray_ddp.py:109-121;
        notebook-safety claim, reference README.md:34-36)."""
        self._mesh = None
        jax.clear_caches()

    def distributed_sampler_kwargs(self) -> Dict[str, int]:
        """Per-*process* sampler config (the reference's analog is per-worker,
        reference: ray_lightning/ray_ddp.py:288-295).  Under SPMD one process
        feeds all its devices via sharding, so replicas = processes."""
        return {"num_replicas": jax.process_count(),
                "rank": jax.process_index()}

    @property
    def require_distributed_sampler(self) -> bool:
        return True

    def __repr__(self) -> str:
        cfg = self.mesh_config
        axes = {a: s for a, s in zip(mesh_lib.AXIS_ORDER,
                                     (cfg.data, cfg.fsdp, cfg.pipeline,
                                      cfg.expert, cfg.sequence, cfg.tensor))
                if s != 1}
        return f"{type(self).__name__}({axes})"
