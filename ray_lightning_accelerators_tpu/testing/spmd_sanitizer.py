"""Cross-rank collective sanitizer: record every traced collective,
diff the sequences across ranks, turn the silent SPMD deadlock into a
typed one-look postmortem.

The worst SPMD failure mode is not an exception — it is a *hang*: one
rank's program issues a collective the others never join (a
rank-divergent branch, a mismatched axis, two subsystems disagreeing
about an exchange order) and every healthy rank blocks inside XLA until
the watchdog reaps the world minutes later, with a diagnosis that says
"stopped making progress" and nothing about WHY.  The static rules
(``analysis/rules/spmd_collectives.py`` / ``rank_divergence.py``) close
the statically visible holes; this module is the runtime net under
everything they cannot see.

Design (opt-in via the ``RLA_TPU_SPMD_SANITIZER`` knob + the
``spmd_sanitizer`` conftest fixture):

- **Interception.**  ``install()`` wraps the public ``jax.lax``
  collective entry points (``psum``/``pmean``/``all_gather``/
  ``all_to_all``/``psum_scatter``/``ppermute``/``axis_index`` — exactly
  the ops the repo's exchange/gather builders in
  ``parallel/collectives.py``, the fused loss, ulysses/ring/pipeline
  call).  Collectives execute Python only at TRACE time, so the wrapper
  costs nothing per step: each traced call appends one host-side record
  ``(op, axis names, shape, dtype, call site)`` to a bounded ring
  (``RLA_TPU_SPMD_SEQ_EVENTS``) and mirrors a compact event into the
  PR 7 flight recorder (kind ``spmd_collective``) so the unified
  timeline shows the collective stream in context.

- **Spill.**  Every record re-snapshots ``rank{N}.collectives.json``
  under ``RLA_TPU_TELEMETRY_DIR`` (atomic tmp+rename, same contract as
  the flight recorder's spill): a rank that wedges mid-collective
  leaves its sequence on disk, which is the whole point.  Worker
  processes auto-install at boot (``runtime/actors._worker_main``) when
  the knob is in their env overlay.

- **The checker.**  ``check_collective_sequences(dir)`` gathers every
  rank's spill, aligns on absolute call index and raises a typed,
  wire-registered :class:`CollectiveMismatch` whose diagnosis embeds
  the FIRST divergent entry per rank (op/axes/shape/dtype/site).  The
  driver runs it after fan-out runs (``Trainer._run_in_world``) and
  chaos attempts (``runtime/elastic.ElasticRunner``) — a wedge whose
  real cause is a divergent collective surfaces as
  ``CollectiveMismatch`` naming the divergent call, not as a generic
  ``WorkerWedged``.

Import-light by design: nothing here imports jax until ``install()``
actually patches it, so the testing package stays a zero-cost import.
"""

from __future__ import annotations

import functools
import json
import os
import sys
import threading
from collections import deque
from typing import Any, Dict, List, Mapping, Optional, Tuple

from ..analysis import knobs
from ..telemetry import recorder as telemetry

SANITIZER_ENV = "RLA_TPU_SPMD_SANITIZER"
SEQ_EVENTS_ENV = "RLA_TPU_SPMD_SEQ_EVENTS"
DEFAULT_SEQ_EVENTS = 512

# the jax.lax entry points wrapped while the sanitizer is installed —
# the ops the repo's exchange/gather builders and parallel modules use
COLLECTIVE_OPS: Tuple[str, ...] = (
    "psum", "pmean", "pmax", "pmin", "all_gather", "all_to_all",
    "psum_scatter", "ppermute", "axis_index")

_SPILL_SUFFIX = ".collectives.json"


class CollectiveMismatch(RuntimeError):
    """Ranks traced DIVERGENT collective sequences: the program that
    hangs (or silently corrupts) instead of raising.  The diagnosis
    carries the first divergent entry per rank — op, axis names, shape,
    dtype and call site — so the postmortem names the exact call.

    Wire-registered (``runtime/wire.py``): a worker- or driver-side
    raise crosses the actor pipe and the agent relay typed, with the
    diagnosis embedded in the message (the ``WorkerWedged`` marker
    pattern) and recovered by :meth:`from_message`.
    """

    _MARKER = "| collectives="

    def __init__(self, message: str,
                 diagnosis: Optional[Dict[str, Any]] = None):
        super().__init__(message)
        self.diagnosis = dict(diagnosis or {})

    @classmethod
    def from_divergence(cls, diagnosis: Dict[str, Any]
                        ) -> "CollectiveMismatch":
        diagnosis = dict(diagnosis)
        idx = diagnosis.get("first_divergence")
        per_rank = diagnosis.get("per_rank") or {}
        bits = []
        for rank in sorted(per_rank):
            e = per_rank[rank]
            if e is None:
                bits.append(f"rank {rank}: <no call #{idx}>")
            else:
                bits.append(
                    f"rank {rank}: {e.get('op')}(axes={e.get('axes')}, "
                    f"shape={e.get('shape')}, dtype={e.get('dtype')}) "
                    f"at {e.get('site')}")
        msg = (f"cross-rank collective sequences diverge at call "
               f"#{idx}: " + "; ".join(bits) + " "
               + cls._MARKER
               + json.dumps(diagnosis, sort_keys=True, default=str))
        return cls(msg, diagnosis=diagnosis)

    @classmethod
    def from_message(cls, message: str) -> "CollectiveMismatch":
        """Rebuild from a wire-crossing (name, message, tb) payload,
        recovering the embedded diagnosis."""
        diagnosis: Dict[str, Any] = {}
        i = message.find(cls._MARKER)
        if i >= 0:
            try:
                diagnosis = json.loads(message[i + len(cls._MARKER):])
            except ValueError:
                pass
        return cls(message, diagnosis=diagnosis)


# --------------------------------------------------------------------- #
# Recording                                                              #
# --------------------------------------------------------------------- #
def _norm_axes(axis_name: Any) -> List[str]:
    if axis_name is None:
        return []
    if isinstance(axis_name, (tuple, list)):
        return [str(a) for a in axis_name]
    return [str(axis_name)]


def _shape_dtype(x: Any) -> Tuple[Optional[List[int]], Optional[str]]:
    """Host metadata of the first array-ish leaf of ``x`` (a tracer at
    record time — shape/dtype reads never sync a device)."""
    if x is None:
        return None, None
    leaves = [x]
    if not hasattr(x, "shape"):
        try:
            import jax
            leaves = jax.tree_util.tree_leaves(x)
        except Exception:
            return None, None
    for leaf in leaves:
        shape = getattr(leaf, "shape", None)
        if shape is not None:
            dtype = getattr(leaf, "dtype", None)
            return list(shape), (str(dtype) if dtype is not None else None)
    return None, None


def _call_site(depth: int = 2) -> Optional[str]:
    """``path:lineno`` of the frame that called the wrapped collective,
    trimmed to a package/repo-relative tail for cross-process
    comparability."""
    try:
        frame = sys._getframe(depth)
    except ValueError:
        return None
    path = frame.f_code.co_filename
    parts = path.replace(os.sep, "/").split("/")
    tail = "/".join(parts[-3:]) if len(parts) > 3 else "/".join(parts)
    return f"{tail}:{frame.f_lineno}"


class SpmdSanitizer:
    """One process's bounded collective-call sequence.

    Entries carry a monotonically increasing absolute index ``i`` so
    sequences stay alignable across ranks even after the ring drops old
    heads.  Thread-safe (serve threads and a fitting trainer may trace
    concurrently); every record re-spills — tracing is rare, so the
    extra write is noise, and crash-observability is the contract."""

    def __init__(self, capacity: int = DEFAULT_SEQ_EVENTS,
                 rank: Optional[int] = None,
                 spill_path: Optional[str] = None):
        self.capacity = max(1, int(capacity))
        self.rank = rank
        self.spill_path = spill_path
        self._ring: deque = deque(maxlen=self.capacity)
        self._n = 0
        self._lock = threading.Lock()
        self._spill_warned = False

    def record(self, op: str, axis_name: Any, x: Any = None,
               site: Optional[str] = None) -> None:
        axes = _norm_axes(axis_name)
        shape, dtype = _shape_dtype(x)
        with self._lock:
            entry = {"i": self._n, "op": op, "axes": axes,
                     "shape": shape, "dtype": dtype, "site": site}
            self._ring.append(entry)
            self._n += 1
        # the unified timeline's view (bounded flight-recorder ring);
        # the sanitizer's own spill below stays the diff channel
        telemetry.emit("spmd_collective", op=op, axes=",".join(axes),
                       site=site)
        self.spill()

    def sequence(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [dict(e) for e in self._ring]

    def snapshot(self) -> Dict[str, Any]:
        return {"rank": self.rank, "pid": os.getpid(),
                "n": self._n, "capacity": self.capacity,
                "events": self.sequence()}

    def spill(self) -> Optional[str]:
        """Atomic snapshot to ``spill_path`` — never raises (same
        telemetry-observes-never-gates contract as the recorder).  The
        tmp name is pid+thread-keyed: two threads tracing concurrently
        (serve replica + fitting trainer) each write their OWN tmp and
        the atomic replace publishes whichever complete snapshot lands
        last — never an interleaved torn file."""
        path = self.spill_path
        if path is None:
            return None
        tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
        try:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            with open(tmp, "w") as f:
                json.dump(self.snapshot(), f)
            os.replace(tmp, path)
            return path
        except Exception as e:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            if not self._spill_warned:
                self._spill_warned = True
                telemetry.log.warning(
                    "spmd sanitizer spill to %s failed: %s", path, e)
            return None


# --------------------------------------------------------------------- #
# Installation (jax.lax patching) + process singleton                    #
# --------------------------------------------------------------------- #
_active: Optional[SpmdSanitizer] = None
_originals: Dict[str, Any] = {}
_install_lock = threading.Lock()


def _make_wrapper(op: str, orig, sanitizer: SpmdSanitizer):
    axis_idx = 0 if op == "axis_index" else 1

    @functools.wraps(orig)
    def wrapper(*args, **kwargs):
        axis = kwargs.get("axis_name")
        if axis is None and len(args) > axis_idx:
            axis = args[axis_idx]
        x = None if op == "axis_index" else (args[0] if args else None)
        try:
            sanitizer.record(op, axis, x, site=_call_site())
        except Exception:
            pass  # the sanitizer observes; it must never fail a trace
        return orig(*args, **kwargs)

    wrapper._rla_spmd_wrapped = True
    return wrapper


def enabled(env: Optional[Mapping[str, str]] = None) -> bool:
    return knobs.get_bool(SANITIZER_ENV, False, env=env)


def spill_path_for(rank: Optional[int],
                   env: Optional[Mapping[str, str]] = None
                   ) -> Optional[str]:
    tdir = knobs.get_str(telemetry.DIR_ENV, None, env=env)
    if not tdir:
        return None
    label = "driver" if rank is None else f"rank{int(rank)}"
    return os.path.join(tdir, label + _SPILL_SUFFIX)


def get_sanitizer() -> Optional[SpmdSanitizer]:
    return _active


def install(sanitizer: Optional[SpmdSanitizer] = None,
            rank: Optional[int] = None,
            env: Optional[Mapping[str, str]] = None) -> SpmdSanitizer:
    """Patch the ``jax.lax`` collective entry points with recording
    wrappers.  Idempotent per process (a second install rebinds the
    ring, not the patches)."""
    global _active
    import jax

    with _install_lock:
        if sanitizer is None:
            sanitizer = SpmdSanitizer(
                capacity=knobs.get_int(SEQ_EVENTS_ENV, DEFAULT_SEQ_EVENTS,
                                       env=env),
                rank=rank, spill_path=spill_path_for(rank, env=env))
        # overwrite any STALE spill from a previous process generation of
        # this rank right away (worker restarts between elastic attempts
        # re-run boot install): an attempt must never be diffed against
        # a dead generation's sequence
        sanitizer.spill()
        for op in COLLECTIVE_OPS:
            current = getattr(jax.lax, op, None)
            if current is None:
                continue
            if getattr(current, "_rla_spmd_wrapped", False):
                # already patched: rebuild the wrapper over the saved
                # original so it records into the NEW ring
                current = _originals[op]
            else:
                _originals[op] = current
            setattr(jax.lax, op, _make_wrapper(op, current, sanitizer))
        _active = sanitizer
    return sanitizer


def uninstall() -> None:
    """Restore the original ``jax.lax`` entry points."""
    global _active
    with _install_lock:
        if _originals:
            import jax
            for op, orig in _originals.items():
                setattr(jax.lax, op, orig)
            _originals.clear()
        _active = None


def maybe_install_from_env(rank: Optional[int] = None,
                           env: Optional[Mapping[str, str]] = None
                           ) -> Optional[SpmdSanitizer]:
    """Worker-boot hook (``runtime/actors._worker_main``): install when
    the knob is set in the per-worker overlay / process env."""
    if not enabled(env):
        return None
    return install(rank=rank, env=env)


# --------------------------------------------------------------------- #
# Driver-side checker                                                    #
# --------------------------------------------------------------------- #
def clear_spills(tdir: Optional[str] = None,
                 env: Optional[Mapping[str, str]] = None) -> None:
    """Remove every ``*.collectives.json`` under the telemetry dir — the
    driver calls this at run entry so a smaller world (or a rerun in
    the same dir) is never diffed against stale rank files left by a
    previous run.  Workers re-spill on boot and on every record, so
    anything a live run traces reappears immediately."""
    tdir = tdir or knobs.get_str(telemetry.DIR_ENV, None, env=env)
    if not tdir or not os.path.isdir(tdir):
        return
    for fn in os.listdir(tdir):
        if fn.endswith(_SPILL_SUFFIX):
            try:
                os.unlink(os.path.join(tdir, fn))
            except OSError:
                pass


def gather_sequences(tdir: Optional[str] = None
                     ) -> Dict[str, Dict[str, Any]]:
    """label ('driver' / 'rank0' / ...) -> spilled sequence snapshot for
    every ``*.collectives.json`` under the telemetry dir."""
    tdir = tdir or knobs.get_str(telemetry.DIR_ENV, None)
    out: Dict[str, Dict[str, Any]] = {}
    if not tdir or not os.path.isdir(tdir):
        return out
    for fn in sorted(os.listdir(tdir)):
        if not fn.endswith(_SPILL_SUFFIX):
            continue
        try:
            with open(os.path.join(tdir, fn)) as f:
                snap = json.load(f)
        except (OSError, ValueError):
            continue  # torn mid-crash files are an expected state
        if isinstance(snap, dict):
            out[fn[:-len(_SPILL_SUFFIX)]] = snap
    return out


def _entry_key(e: Dict[str, Any]) -> Tuple:
    return (e.get("op"), tuple(e.get("axes") or ()),
            tuple(e.get("shape") or ()) if e.get("shape") is not None
            else None,
            e.get("dtype"), e.get("site"))


def diff_sequences(snapshots: Mapping[str, Dict[str, Any]]
                   ) -> Optional[Dict[str, Any]]:
    """The divergence diagnosis across >= 2 rank sequences, or None when
    every rank traced the same collective stream.

    Sequences align on the absolute call index ``i`` (rings may have
    dropped old heads on busy ranks); comparison starts at the highest
    retained start index and runs to the longest sequence — a rank
    whose stream ENDS early (it never issued call #k the others did) is
    a divergence too, reported with ``None`` as its entry."""
    ranks = {label: snap for label, snap in snapshots.items()
             if label != "driver"}
    if len(ranks) < 2:
        return None
    by_rank: Dict[str, Dict[int, Dict[str, Any]]] = {}
    starts, ends = [], []
    for label, snap in ranks.items():
        events = snap.get("events") or []
        by_rank[label] = {int(e["i"]): e for e in events}
        starts.append(min(by_rank[label]) if by_rank[label] else 0)
        ends.append(snap.get("n", len(events)))
    lo, hi = max(starts), max(ends)
    for i in range(lo, hi):
        entries = {label: by_rank[label].get(i) for label in by_rank}
        keys = {None if e is None else _entry_key(e)
                for e in entries.values()}
        if len(keys) > 1:
            return {
                "first_divergence": i,
                "per_rank": entries,
                "lengths": {label: snap.get("n")
                            for label, snap in ranks.items()},
                "ring_dropped": lo > 0,
            }
    return None


def check_collective_sequences(tdir: Optional[str] = None,
                               raise_on_mismatch: bool = True
                               ) -> Optional[CollectiveMismatch]:
    """Gather + diff the rank sequences under the telemetry dir; raise
    (or return, with ``raise_on_mismatch=False``) the typed
    :class:`CollectiveMismatch`.  None when the sequences agree."""
    diagnosis = diff_sequences(gather_sequences(tdir))
    if diagnosis is None:
        return None
    exc = CollectiveMismatch.from_divergence(diagnosis)
    if raise_on_mismatch:
        raise exc
    return exc


def check_world_collectives(raise_on_mismatch: bool = True,
                            env: Optional[Mapping[str, str]] = None
                            ) -> Optional[CollectiveMismatch]:
    """The driver seam (trainer fan-out, elastic attempts): a no-op
    unless the sanitizer knob is on AND a telemetry dir is configured —
    unconfigured runs pay nothing, not even a directory listing."""
    if not enabled(env):
        return None
    tdir = knobs.get_str(telemetry.DIR_ENV, None, env=env)
    if not tdir:
        return None
    return check_collective_sequences(
        tdir, raise_on_mismatch=raise_on_mismatch)


def reset_world_collectives(env: Optional[Mapping[str, str]] = None
                            ) -> None:
    """Run-entry counterpart of :func:`check_world_collectives` (same
    gating): clear stale rank spills so this run's diff only ever sees
    sequences its own workers traced."""
    if not enabled(env):
        return
    clear_spills(env=env)
