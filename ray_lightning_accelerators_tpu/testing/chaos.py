"""Deterministic fault injection for the worker runtime.

Hangs, crashes, and stragglers are the failure modes that cost real bench
rounds (VERDICT.md: wedged tunnel, 25-minute silent hang) -- and the ones
hardest to reproduce on demand.  This harness makes them deterministic:
faults are declared in an env var, honored by every ``Worker`` subprocess
inside its dispatch loop (runtime/actors.py ``_worker_main``), and need no
TPU, no timing races, no monkeypatching of runtime internals.

Syntax (comma-separated faults)::

    RLA_TPU_CHAOS=crash@rank1:step3,hang@rank0,slow@all:2.5

Replica-layer faults (serve tier, honored inside
``serve.replicas._replica_serve`` rather than the worker dispatch
loop)::

    RLA_TPU_CHAOS=crash@replica0:chunk2,hang@replica1:chunk3:once,slow@replica0:1.5

``kind@target[:qualifier...]`` where

- kind: ``crash`` (``os._exit`` with exit code 43), ``hang`` (freeze the
  heartbeat, then sleep forever -- simulates a fully frozen process, so
  the watchdog's stale-beat path fires), ``slow`` (delay the dispatch by
  the given seconds -- a straggler that still completes), ``preempt``
  (deliver SIGTERM to the worker itself -- with a
  ``runtime.preemption`` notice handler installed via
  ``RLA_TPU_PREEMPT_GRACE_S`` this simulates a spot/preemption notice
  the dispatched body drains gracefully; without one it is a plain
  SIGTERM death), ``lost`` (``os._exit`` with exit code 44 AND a
  persistent "host gone" marker under ``RLA_TPU_CHAOS_NS``: every
  respawn of that rank dies at boot, so ``pool.restart_dead()`` can
  never bring it back -- the permanently lost host that forces an
  elastic scale-down), ``rejoin`` (the grow counterpart of ``lost``:
  the host comes back on its Nth respawn AFTER going lost --
  ``rejoin@rank1:step3`` counts boot attempts while rank 1's lost
  marker exists and clears it via :func:`clear_lost` on the 3rd, so
  elastic grow (``ActorPool.revive``) is testable deterministically;
  never fires on a dispatch);
- target: ``rankN`` or ``all`` (worker layer), or ``replicaN`` (replica
  layer: the fault fires inside the replica's SERVE CHUNK path, counted
  per chunk via the ``chunkK`` qualifier -- only ``crash``/``hang``/
  ``slow`` make sense there; ``hang`` freezes the worker's heartbeat so
  the pool watchdog sees a frozen process, exactly like the worker-layer
  kind);
- qualifiers: ``stepN`` -- fire on the Nth dispatch of the worker
  process's lifetime (1-based; crash/hang/preempt/lost default to step
  1, slow defaults to every dispatch); a float -- the delay for
  ``slow``; ``once`` -- fire at most once across process RESTARTS
  (claimed through an atomic token file under the ``RLA_TPU_CHAOS_NS``
  directory), so a wedge->restart->resume loop converges
  deterministically.  ``lost`` markers are keyed by the rank the fault
  fired on: after an elastic scale-down drops that rank, surviving
  ranks (which keep their original rank identity) never inherit the
  marker.

Faults fire BEFORE the dispatched fn runs, counting every dispatch
(including runtime-internal ones such as ``initialize_worker``); tests
pick explicit steps when that matters.  Parse errors raise driver-side
(``parse_chaos``) and ship home as a ``RemoteError`` worker-side rather
than silently dropping the fault.

Numeric-layer faults (the anomaly guardian's test surface, honored at
the train-step BUILD seams in ``core/trainer.py`` rather than any
dispatch loop)::

    RLA_TPU_CHAOS=nanloss@rank0:step3,gradspike@rank1:step5
    RLA_TPU_CHAOS=badbatch@step5,bitflip@rank1:step4

- ``nanloss`` poisons the traced loss metric at global step K;
- ``gradspike`` scales the (per-replica, when a stacked local-gradient
  tree exists and ``rankN`` names a replica) gradients by 1e4 at step K;
- ``badbatch`` NaN-poisons the HOST batch feeding global step K (rank-
  less by nature — the same poisoned batch reaches every replica), so
  the guardian's blame cascade lands on ``data``;
- ``bitflip`` flips one exponent bit of one element in the first
  gradient leaf (replica ``rankN``'s row when stacked) — the silent-
  data-corruption emulation whose per-rank divergence the guardian
  names.

Steps are the 1-based GLOBAL optimizer step.  Numeric faults are
once-by-construction: they are claimed at step-BUILD time through the
``RLA_TPU_CHAOS_NS`` token store, so the recompile after a guardian
rewind replays the window CLEAN (without a namespace dir every build
re-arms them — single-fit unit tests need no namespace).
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass
from typing import Callable, List, Optional

from ..analysis import knobs

CHAOS_ENV = "RLA_TPU_CHAOS"
CHAOS_NS_ENV = "RLA_TPU_CHAOS_NS"
CHAOS_EXIT_CODE = 43
LOST_EXIT_CODE = 44
_KINDS = ("crash", "hang", "slow", "preempt", "lost", "rejoin",
          "nanloss", "gradspike", "badbatch", "bitflip")
# faults that make sense at the replica serve-chunk layer: a replica is
# a full process, so preempt/lost stay worker-layer kinds
_REPLICA_KINDS = ("crash", "hang", "slow")
# numeric faults (anomaly-guardian test surface): honored at the
# train-step build seams in core/trainer.py, never by a dispatch loop
_NUMERIC_KINDS = ("nanloss", "gradspike", "badbatch", "bitflip")

LAYER_WORKER = "worker"
LAYER_REPLICA = "replica"
LAYER_NUMERIC = "numeric"


def _lost_markers(rank: int, ns_dir: Optional[str]) -> List[str]:
    """Persistent 'host gone' marker files for ``rank`` under the chaos
    namespace dir (rank-keyed, so one rank's markers never match
    another's)."""
    if not ns_dir or not os.path.isdir(ns_dir):
        return []
    suffix = f"-r{rank}.lost"
    return [os.path.join(ns_dir, name) for name in sorted(os.listdir(ns_dir))
            if name.endswith(suffix)]


def clear_lost(rank: int, ns_dir: Optional[str] = None) -> List[str]:
    """Remove ``rank``'s persistent 'host gone' markers so the next
    respawn of that rank boots instead of dying -- the test-side grow
    primitive (a host coming back).  ``ns_dir`` defaults to
    ``RLA_TPU_CHAOS_NS``.  Returns the removed marker paths (empty when
    the rank was never lost)."""
    ns_dir = ns_dir or knobs.get_raw(CHAOS_NS_ENV) or None
    removed = []
    for path in _lost_markers(rank, ns_dir):
        try:
            os.remove(path)
            removed.append(path)
        except OSError:
            pass
    return removed


@dataclass(frozen=True)
class ChaosFault:
    kind: str
    rank: Optional[int]  # None = all ranks
    step: Optional[int]  # None = every dispatch (slow) / step 1 (crash|hang)
    delay_s: Optional[float] = None  # slow only
    once: bool = False
    # which injection seam honors this fault: "worker" = the dispatch
    # loop in runtime/actors._worker_main (step = dispatch index),
    # "replica" = serve.replicas._replica_serve (step = chunk index)
    layer: str = LAYER_WORKER
    # pipeline stage-group target ('stageN'): the fault applies to every
    # member of that stage group — injectors constructed in a process
    # whose RLA_TPU_PIPELINE_STAGE differs drop it at filter time
    stage: Optional[int] = None

    def matches(self, rank: int, step: int) -> bool:
        if self.rank is not None and self.rank != rank:
            return False
        if self.step is not None:
            return step == self.step
        # crash/hang without an explicit step fire on the first dispatch;
        # slow without one fires on every dispatch
        return True if self.kind == "slow" else step == 1

    def token(self, rank: int) -> str:
        """Stable per-rank claim key for ``once`` semantics (layer-
        prefixed for replica faults so a replica chunk claim can never
        collide with a worker dispatch claim)."""
        prefix = "replica" if self.layer == LAYER_REPLICA else "rank"
        if self.stage is not None:
            tgt = f"stage{self.stage}"
        elif self.rank is None:
            tgt = "all"
        else:
            tgt = f"{prefix}{self.rank}"
        step = "any" if self.step is None else f"step{self.step}"
        tok = f"{self.kind}-{tgt}-{step}-r{rank}"
        return tok if self.layer == LAYER_WORKER else f"{self.layer}-{tok}"


def parse_chaos(spec: str) -> List[ChaosFault]:
    """Parse an ``RLA_TPU_CHAOS`` spec; raises ``ValueError`` with the
    offending token on any malformed fault."""
    faults: List[ChaosFault] = []
    for part in (p.strip() for p in spec.split(",")):
        if not part:
            continue
        kind, at, target_q = part.partition("@")
        if not at or kind not in _KINDS:
            raise ValueError(
                f"chaos fault {part!r}: expected kind@target with kind in "
                f"{_KINDS}")
        bits = target_q.split(":")
        target = bits[0]
        layer = LAYER_NUMERIC if kind in _NUMERIC_KINDS else LAYER_WORKER
        stage: Optional[int] = None
        if kind == "badbatch" and target.startswith("step") \
                and target[4:].isdigit():
            # badbatch@stepK shorthand: the poisoned batch is global by
            # nature (every replica consumes it), so there is no rank
            if bits[1:]:
                raise ValueError(
                    f"chaos fault {part!r}: badbatch@stepK takes no "
                    "qualifiers")
            if int(target[4:]) < 1:
                raise ValueError(
                    f"chaos fault {part!r}: steps are 1-based")
            faults.append(ChaosFault("badbatch", None, int(target[4:]),
                                     layer=LAYER_NUMERIC))
            continue
        if target == "all":
            rank = None
        elif target.startswith("stage") and target[5:].isdigit():
            # pipeline stage-group fault domain: matches every rank of
            # the stage group (parallel/mpmd sets RLA_TPU_PIPELINE_STAGE
            # in each member's env; the injector filters on it)
            rank = None
            stage = int(target[5:])
        elif target.startswith("rank") and target[4:].isdigit():
            rank = int(target[4:])
        elif target.startswith("replica") and target[7:].isdigit():
            rank = int(target[7:])
            layer = LAYER_REPLICA
            if kind not in _REPLICA_KINDS:
                raise ValueError(
                    f"chaos fault {part!r}: replica-layer faults support "
                    f"{_REPLICA_KINDS} only (preempt/lost are whole-"
                    "process kinds — target the worker with 'rankN')")
        else:
            raise ValueError(
                f"chaos fault {part!r}: target must be 'rankN', "
                f"'replicaN', 'stageN' or 'all', got {target!r}")
        step: Optional[int] = None
        delay: Optional[float] = None
        once = False
        for q in bits[1:]:
            if q == "once":
                once = True
            elif q.startswith("step") and q[4:].isdigit():
                if layer == LAYER_REPLICA:
                    raise ValueError(
                        f"chaos fault {part!r}: replica faults count "
                        "serve CHUNKS — use 'chunkN', not 'stepN'")
                step = int(q[4:])
                if step < 1:
                    raise ValueError(
                        f"chaos fault {part!r}: steps are 1-based")
            elif q.startswith("chunk") and q[5:].isdigit():
                if layer != LAYER_REPLICA:
                    raise ValueError(
                        f"chaos fault {part!r}: 'chunkN' only applies to "
                        "replica-layer targets ('replicaN')")
                step = int(q[5:])
                if step < 1:
                    raise ValueError(
                        f"chaos fault {part!r}: chunks are 1-based")
            else:
                try:
                    delay = float(q)
                except ValueError:
                    raise ValueError(
                        f"chaos fault {part!r}: unknown qualifier {q!r} "
                        "(expected 'stepN'/'chunkN', 'once', or a float "
                        "delay)") from None
        if kind == "slow" and delay is None:
            raise ValueError(
                f"chaos fault {part!r}: 'slow' needs a float delay "
                "qualifier (e.g. slow@all:2.5)")
        if kind != "slow" and delay is not None:
            raise ValueError(
                f"chaos fault {part!r}: only 'slow' takes a delay")
        if kind == "badbatch" and rank is not None:
            raise ValueError(
                f"chaos fault {part!r}: badbatch is rank-less (the "
                "poisoned batch reaches every replica) — use "
                "'badbatch@stepK' or 'badbatch@all:stepK'")
        if kind in _NUMERIC_KINDS and stage is not None:
            raise ValueError(
                f"chaos fault {part!r}: numeric faults target 'rankN' "
                "or 'all' (the SPMD step builders), not a pipeline "
                "stage group")
        faults.append(ChaosFault(kind, rank, step, delay, once,
                                 layer=layer, stage=stage))
    return faults


class ChaosInjector:
    """Worker-process side: one per worker, consulted once per dispatch.

    ``freeze_heartbeat``: callable stopping the worker's beat thread
    (``WorkerBeat.freeze``) so a ``hang`` looks like a frozen process to
    the watchdog, not a long dispatch.

    ``layer`` selects which faults of the spec this injector honors:
    the worker dispatch loop builds a ``"worker"`` injector (steps =
    dispatches), the serve replica layer builds a ``"replica"`` one
    (steps = serve chunks) — one spec can carry both kinds and each
    seam only fires its own.
    """

    def __init__(self, faults: List[ChaosFault], rank: int,
                 freeze_heartbeat: Optional[Callable[[], None]] = None,
                 ns_dir: Optional[str] = None,
                 layer: str = LAYER_WORKER,
                 stage: Optional[int] = None):
        self.layer = layer
        # stage-targeted faults only arm inside their own stage group
        # (``stage`` = this process's RLA_TPU_PIPELINE_STAGE, if any)
        self.faults = [f for f in faults if f.layer == layer
                       and (f.stage is None or f.stage == stage)]
        self.rank = rank
        self.freeze_heartbeat = freeze_heartbeat
        self.ns_dir = ns_dir
        self._step = 0
        if any(f.once or f.kind in ("lost", "rejoin")
               for f in self.faults) and not ns_dir:
            raise ValueError(
                f"chaos 'once', 'lost' and 'rejoin' faults need "
                f"{CHAOS_NS_ENV} set to a directory (the cross-restart "
                "claim store)")
        # rejoin: the lost host comes back on its Kth respawn (K =
        # the fault's stepN, default 1) — count boot attempts while this
        # rank's lost marker(s) exist and clear them at the threshold,
        # BEFORE the death loop below reads them
        for f in self.faults:
            if f.kind != "rejoin" or (f.rank is not None
                                      and f.rank != rank):
                continue
            if not _lost_markers(rank, self.ns_dir):
                continue
            boots_path = os.path.join(self.ns_dir,
                                      f.token(rank) + ".boots")
            with open(boots_path, "ab") as fh:
                fh.write(b".")
            if os.path.getsize(boots_path) >= (f.step or 1):
                clear_lost(rank, self.ns_dir)
        # a rank whose 'lost' fault already fired is a gone host: every
        # respawned generation dies at boot, before serving any dispatch
        for f in self.faults:
            if (f.kind == "lost"
                    and (f.rank is None or f.rank == rank)
                    and os.path.exists(self._lost_marker(f))):
                os._exit(LOST_EXIT_CODE)

    @classmethod
    def from_env(cls, rank: int,
                 freeze_heartbeat: Optional[Callable[[], None]] = None,
                 layer: str = LAYER_WORKER) -> Optional["ChaosInjector"]:
        spec = knobs.get_str(CHAOS_ENV, "")
        if not spec:
            return None
        inj = cls(parse_chaos(spec), rank, freeze_heartbeat,
                  knobs.get_raw(CHAOS_NS_ENV) or None, layer=layer,
                  stage=knobs.get_int("RLA_TPU_PIPELINE_STAGE", None))
        return inj if inj.faults else None

    def _lost_marker(self, fault: ChaosFault) -> str:
        """Persistent 'host gone' marker path for a lost fault on THIS
        rank (rank-keyed: an elastic scale-down that drops the rank never
        leaks the marker onto survivors, which keep their own ranks)."""
        return os.path.join(self.ns_dir, fault.token(self.rank) + ".lost")

    def _claim_once(self, fault: ChaosFault) -> bool:
        """Atomically claim a once-fault across processes AND restarts:
        O_CREAT|O_EXCL on a token file -- first claimant fires, every
        later (re-spawned) process skips."""
        os.makedirs(self.ns_dir, exist_ok=True)
        path = os.path.join(self.ns_dir, fault.token(self.rank))
        try:
            os.close(os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY))
            return True
        except FileExistsError:
            return False

    def on_dispatch(self) -> None:
        """Called by the dispatch loop before executing the shipped fn."""
        self._step += 1
        for fault in self.faults:
            if fault.kind == "rejoin":
                # a boot-time kind (handled in __init__); its stepN
                # counts respawns, not dispatches
                continue
            if not fault.matches(self.rank, self._step):
                continue
            if fault.once and not self._claim_once(fault):
                continue
            if fault.kind == "slow":
                time.sleep(fault.delay_s)
            elif fault.kind == "crash":
                os._exit(CHAOS_EXIT_CODE)
            elif fault.kind == "preempt":
                # a spot notice IS a SIGTERM: the runtime.preemption
                # handler (installed when RLA_TPU_PREEMPT_GRACE_S is in
                # the worker env) flips the notice the dispatched body
                # drains; with no handler the default disposition kills
                # the process -- both are the real contract
                import signal
                os.kill(os.getpid(), signal.SIGTERM)
            elif fault.kind == "lost":
                # host gone: persist the marker FIRST so every respawn
                # dies at boot, then die
                os.makedirs(self.ns_dir, exist_ok=True)
                try:
                    os.close(os.open(self._lost_marker(fault),
                                     os.O_CREAT | os.O_WRONLY))
                except OSError:
                    pass
                os._exit(LOST_EXIT_CODE)
            elif fault.kind == "hang":
                if self.freeze_heartbeat is not None:
                    self.freeze_heartbeat()
                while True:  # wedged until the watchdog reaps us
                    time.sleep(3600)


# --------------------------------------------------------------------- #
# Numeric layer (anomaly-guardian faults, core/trainer.py build seams)   #
# --------------------------------------------------------------------- #
def numeric_faults() -> tuple:
    """Numeric-layer faults of the ambient ``RLA_TPU_CHAOS`` spec (empty
    tuple when unset — the zero-cost common case the trainer checks)."""
    spec = knobs.get_str(CHAOS_ENV, "")
    if not spec:
        return ()
    return tuple(f for f in parse_chaos(spec)
                 if f.layer == LAYER_NUMERIC)


def claim_numeric(fault: ChaosFault, rank: int = 0) -> bool:
    """Claim a numeric fault at step-BUILD time.  With a chaos namespace
    configured the claim is an atomic cross-process/cross-restart token
    (O_CREAT|O_EXCL), so the recompile after a guardian rewind builds a
    CLEAN step; without one every build re-arms the fault (single-fit
    unit tests that never rewind)."""
    ns_dir = knobs.get_raw(CHAOS_NS_ENV) or None
    if not ns_dir:
        return True
    os.makedirs(ns_dir, exist_ok=True)
    path = os.path.join(ns_dir, "numeric-" + fault.token(rank))
    try:
        os.close(os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY))
        return True
    except FileExistsError:
        return False


def poison_batch(batch):
    """``badbatch``'s host-side poison: NaN into the first element of
    every float leaf (copies — the loader's arrays stay clean).  Int-only
    batches pass through untouched (nothing to poison)."""
    import numpy as np

    def rec(x):
        if isinstance(x, dict):
            return {k: rec(v) for k, v in x.items()}
        if isinstance(x, tuple):
            return tuple(rec(v) for v in x)
        if isinstance(x, list):
            return [rec(v) for v in x]
        arr = np.asarray(x)
        if arr.dtype.kind == "f" and arr.size:
            arr = np.array(arr, copy=True)
            arr.reshape(-1)[0] = np.nan
            return arr
        return x

    return rec(batch)


def apply_traced_numeric(fault: ChaosFault, step, metrics, grads=None,
                         stacked=None):
    """Apply one TRACED numeric fault inside a jitted train step.

    ``step`` is the 0-based ``TrainState.step`` scalar (the fault's
    ``stepN`` is the 1-based global step about to complete); ``grads``
    is a global-view gradient tree, ``stacked`` a per-replica
    ``[n_replicas, ...]`` local-gradient tree (compressed paths) —
    whichever the calling builder has.  Everything is ``jnp.where``
    math on the traced values: injecting a fault never changes program
    structure, so the compile-guard retrace pins hold under chaos too.
    Returns ``(metrics, grads, stacked)`` with the transforms applied.
    """
    import jax
    import jax.numpy as jnp

    gate = jnp.asarray(step) == ((fault.step or 1) - 1)
    if fault.kind == "nanloss":
        loss = metrics.get("train_loss")
        if loss is not None:
            metrics = dict(metrics)
            metrics["train_loss"] = jnp.where(
                gate, jnp.asarray(jnp.nan, jnp.asarray(loss).dtype), loss)
        return metrics, grads, stacked

    tgt = stacked if stacked is not None else grads
    if tgt is None:
        return metrics, grads, stacked
    leaves, treedef = jax.tree.flatten(tgt)
    if not leaves:
        return metrics, grads, stacked

    if fault.kind == "gradspike":
        spike = jnp.where(gate, jnp.float32(1e4), jnp.float32(1.0))

        def sc(g):
            s = spike
            if stacked is not None and fault.rank is not None:
                # scale only the targeted replica's row
                row = jnp.arange(g.shape[0]) == fault.rank
                s = jnp.where(row, spike, 1.0).reshape(
                    (-1,) + (1,) * (g.ndim - 1))
            return (g.astype(jnp.float32) * s).astype(g.dtype)

        leaves = [sc(g) for g in leaves]
    elif fault.kind == "bitflip":
        # one exponent bit (1 << 27: +16 on the biased exponent, so the
        # value blows up by 2**16 — survives a bf16 round-trip) of one
        # element of the FIRST leaf; replica `rank`'s row when stacked
        g = leaves[0]
        f32 = g.astype(jnp.float32)
        bits = jax.lax.bitcast_convert_type(f32, jnp.uint32).reshape(-1)
        idx = 0
        if stacked is not None and fault.rank is not None and g.ndim > 0:
            per_row = 1
            for d in g.shape[1:]:
                per_row *= int(d)
            idx = min(fault.rank, g.shape[0] - 1) * per_row
        flipped = bits.at[idx].set(bits[idx] ^ jnp.uint32(1 << 27))
        out = jax.lax.bitcast_convert_type(
            jnp.where(gate, flipped, bits).reshape(f32.shape), jnp.float32)
        leaves = [out.astype(g.dtype)] + leaves[1:]
    else:  # badbatch is a HOST fault; nothing to do in-trace
        return metrics, grads, stacked

    tgt = jax.tree.unflatten(treedef, leaves)
    if stacked is not None:
        return metrics, grads, tgt
    return metrics, tgt, stacked
