"""Test-support subsystems (deterministic fault injection lives in
``testing.chaos``).  Import-light: nothing here pulls in jax."""

from .chaos import (CHAOS_ENV, CHAOS_EXIT_CODE, CHAOS_NS_ENV, ChaosFault,
                    ChaosInjector, parse_chaos)

__all__ = ["CHAOS_ENV", "CHAOS_EXIT_CODE", "CHAOS_NS_ENV", "ChaosFault",
           "ChaosInjector", "parse_chaos"]
