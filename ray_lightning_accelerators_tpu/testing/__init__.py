"""Test-support subsystems: deterministic fault injection
(``testing.chaos``) and the cross-rank collective sanitizer
(``testing.spmd_sanitizer``).  Import-light: nothing here pulls in jax
(the sanitizer patches jax.lax only when ``install()`` runs)."""

from .chaos import (CHAOS_ENV, CHAOS_EXIT_CODE, CHAOS_NS_ENV, ChaosFault,
                    ChaosInjector, parse_chaos)
from .spmd_sanitizer import (SANITIZER_ENV, CollectiveMismatch,
                             SpmdSanitizer, check_collective_sequences)

__all__ = ["CHAOS_ENV", "CHAOS_EXIT_CODE", "CHAOS_NS_ENV", "ChaosFault",
           "ChaosInjector", "parse_chaos", "SANITIZER_ENV",
           "CollectiveMismatch", "SpmdSanitizer",
           "check_collective_sequences"]
