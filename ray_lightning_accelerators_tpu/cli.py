"""``rla-tpu`` CLI: per-host agents + multi-machine driver launches.

The reference's multi-node entry is ``ray up cluster.yaml`` +
``ray submit cluster.yaml train.py`` (reference: README.md:57-62): Ray's
cluster launcher starts a daemon on every node, then the driver script
connects with ``ray.init(address=...)``.  The no-Ray equivalent:

1. on every host: ``rla-tpu agent --port 7777``
2. on the driver: ``rla-tpu launch --agents host1:7777,host2:7777 train.py``
   (or run the script directly with ``RLA_TPU_AGENTS`` set, or pass
   ``--address host1:7777,host2:7777`` to the examples)

``launch`` exports the agent list as ``RLA_TPU_AGENTS`` and runs the
script; anything calling ``runtime.bootstrap.launch_distributed`` (or an
accelerator with ``num_hosts > 1``) picks the agents up from the
environment via ``runtime.agent.agents_from_env``.
"""

from __future__ import annotations

import argparse
from typing import List, Optional


def main(argv: Optional[List[str]] = None) -> None:
    parser = argparse.ArgumentParser(
        "rla-tpu", description="TPU training control plane")
    sub = parser.add_subparsers(dest="cmd", required=True)

    ag = sub.add_parser("agent", help="run a per-host worker agent")
    ag.add_argument("--port", type=int, default=7777)
    ag.add_argument("--bind", default="127.0.0.1",
                    help="interface to listen on (agents execute arbitrary "
                         "pickled code; non-loopback binds should set "
                         "RLA_TPU_AGENT_TOKEN on agent and driver)")

    la = sub.add_parser(
        "launch", help="run a driver script against host agents")
    la.add_argument("--agents", required=True,
                    help="comma-separated host:port agent addresses")
    la.add_argument("script", help="driver python script")
    la.add_argument("script_args", nargs=argparse.REMAINDER)

    tr = sub.add_parser(
        "trace", help="summarize an XPlane device trace directory "
                      "(written by Profiler.start_trace) as a per-op / "
                      "per-category roofline table")
    tr.add_argument("trace_dir", help="directory passed to start_trace")
    tr.add_argument("--top", type=int, default=25,
                    help="rows in the per-op table (0 = all)")

    args = parser.parse_args(argv)
    if args.cmd == "agent":
        from .runtime.agent import HostAgent
        # a tokenless non-loopback bind raises inside HostAgent (RCE
        # surface; RLA_TPU_ALLOW_TOKENLESS_BIND=1 is the explicit opt-out)
        HostAgent(args.port, args.bind).serve_forever()
    elif args.cmd == "launch":
        import os
        import runpy
        import sys

        os.environ["RLA_TPU_AGENTS"] = args.agents
        sys.argv = [args.script] + list(args.script_args)
        runpy.run_path(args.script, run_name="__main__")
    elif args.cmd == "trace":
        from .utils.profiler import trace_op_summary

        s = trace_op_summary(args.trace_dir, top=args.top)
        print(f"device total: {s['total_ms']:.2f} ms\n")
        print(f"{'category':<26} {'self ms':>10} {'GB/s':>8} "
              f"{'TF/s':>7} {'%':>6}")
        for cat, row in sorted(s["by_category"].items(),
                               key=lambda kv: -kv[1]["self_ms"]):
            print(f"{cat:<26} {row['self_ms']:>10.2f} {row['gbps']:>8.1f} "
                  f"{row['tfs']:>7.1f} {row['pct']:>6.1f}")
        print(f"\n{'op':<44} {'self ms':>10} {'n':>6} {'%':>6}")
        for op in s["ops"]:
            print(f"{op['name'][:44]:<44} {op['self_ms']:>10.2f} "
                  f"{op['count']:>6d} {op['pct']:>6.1f}")


if __name__ == "__main__":
    main()
