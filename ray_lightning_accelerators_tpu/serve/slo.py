"""Serve SLO engine: targets attached at admission, live burn rate out.

A serving tier scales and sheds on SERVICE-LEVEL objectives, not raw
latency reservoirs: "99% of requests see first token within X ms and a
token cadence within Y ms" is the contract an autoscaler can act on
(ROADMAP item 3 drives replica count and admission from exactly these
signals).  This module adds the three pieces the metrics layer was
missing:

- :class:`SloPolicy` — the declared targets (``ttft_target_s``,
  ``token_cadence_target_s``, ``deadline_s``, ``target_fraction``),
  attached to every request at admission (``AdmissionController``
  stamps the absolute deadline on the ``ServeRequest``, so it
  propagates through requeue and replica re-dispatch untouched —
  an infra retry never resets a client's clock);
- **deadline shed**: a request whose deadline passed while it queued is
  failed typed (:class:`DeadlineExceeded`) *before* prefill — spending
  compute on a response the client already abandoned is the worst way
  to handle overload.  Sheds are counted (``slo_deadline_shed``) and
  emit a typed ``slo_violation`` flight-recorder event;
- :class:`SloTracker` — rolling-window burn-rate accounting over the
  observations the engine already makes (TTFT at prefill, per-token
  cadence at decode).  ``burn_rate`` = observed violation fraction /
  allowed violation fraction (``1 - target_fraction``): 1.0 means the
  error budget is being consumed exactly at the sustainable rate,
  >1 means the SLO is burning down — the scale-up/admission signal.
  Exported live as the ``slo_burn_rate`` gauge and the
  ``slo_violations_total`` counter (ServeMetrics snapshot → registry →
  ``/metrics``).

Hot-path discipline: every observation is one lock + one deque append
of host scalars (the engine loop calls these per prefill/token); the
window prunes incrementally, never scans the reservoirs.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Dict, Mapping, Optional

from ..analysis import knobs
from ..telemetry import recorder as telemetry

TTFT_ENV = "RLA_TPU_SLO_TTFT_S"
CADENCE_ENV = "RLA_TPU_SLO_TOKEN_CADENCE_S"
DEADLINE_ENV = "RLA_TPU_SLO_DEADLINE_S"
WINDOW_ENV = "RLA_TPU_SLO_WINDOW_S"
TARGET_ENV = "RLA_TPU_SLO_TARGET"

DEFAULT_WINDOW_S = 60.0
DEFAULT_TARGET_FRACTION = 0.99
# bound on the rolling window's observation deque: at sane request
# rates 60s of observations fit easily; a pathological flood degrades
# to "the newest N observations", never unbounded memory
MAX_WINDOW_OBSERVATIONS = 16384

FAMILIES = ("ttft", "token_cadence", "deadline")


class DeadlineExceeded(RuntimeError):
    """Typed load shed: the request's SLO deadline passed while it was
    still queued, so the engine refused to spend prefill compute on it.
    Retryable in principle (the 504 analog), but the client's own
    deadline has passed — resubmission needs a fresh budget."""

    def __init__(self, request_id: int, waited_s: float,
                 deadline_s: float):
        super().__init__(
            f"request {request_id} shed before prefill: queued "
            f"{waited_s:.3f}s past its {deadline_s:.3f}s SLO deadline")
        self.request_id = request_id
        self.waited_s = waited_s
        self.deadline_s = deadline_s


class SloPolicy:
    """Declared service-level targets for one engine (or replica group).

    Any subset may be set; ``None`` disables that family.  All targets
    are judged at ``target_fraction`` (default 0.99 — "99% of
    requests"): the tracker's burn rate divides the observed violation
    fraction by the ``1 - target_fraction`` error budget."""

    def __init__(self, ttft_target_s: Optional[float] = None,
                 token_cadence_target_s: Optional[float] = None,
                 deadline_s: Optional[float] = None,
                 target_fraction: float = DEFAULT_TARGET_FRACTION):
        for name, v in (("ttft_target_s", ttft_target_s),
                        ("token_cadence_target_s", token_cadence_target_s),
                        ("deadline_s", deadline_s)):
            if v is not None and v <= 0:
                raise ValueError(f"{name} must be > 0, got {v}")
        if not (0.0 < target_fraction < 1.0):
            raise ValueError(
                f"target_fraction must be in (0, 1), got {target_fraction}")
        self.ttft_target_s = ttft_target_s
        self.token_cadence_target_s = token_cadence_target_s
        self.deadline_s = deadline_s
        self.target_fraction = target_fraction

    @property
    def enabled(self) -> bool:
        return any(v is not None for v in
                   (self.ttft_target_s, self.token_cadence_target_s,
                    self.deadline_s))

    @classmethod
    def from_env(cls, env: Optional[Mapping[str, str]] = None
                 ) -> Optional["SloPolicy"]:
        """The knob-configured policy, or None when none of the SLO
        knobs is set (the zero-overhead default)."""
        policy = cls(
            ttft_target_s=knobs.get_float(TTFT_ENV, None, env=env),
            token_cadence_target_s=knobs.get_float(CADENCE_ENV, None,
                                                   env=env),
            deadline_s=knobs.get_float(DEADLINE_ENV, None, env=env),
            target_fraction=knobs.get_float(TARGET_ENV,
                                            DEFAULT_TARGET_FRACTION,
                                            env=env))
        return policy if policy.enabled else None

    def describe(self) -> Dict[str, Any]:
        return {"ttft_target_s": self.ttft_target_s,
                "token_cadence_target_s": self.token_cadence_target_s,
                "deadline_s": self.deadline_s,
                "target_fraction": self.target_fraction}


class SloTracker:
    """Rolling-window SLO accounting for one engine.

    The engine reports what it already measures — TTFT at prefill,
    per-token cadence at decode, deadline sheds at admission pop — and
    the tracker keeps a bounded ``(ts, violated)`` window per family.
    ``burn_rate()`` is the max across enabled families (the tier is as
    unhealthy as its worst objective); per-family rates ride the
    snapshot for diagnosis."""

    def __init__(self, policy: SloPolicy, metrics: Any = None,
                 window_s: Optional[float] = None,
                 env: Optional[Mapping[str, str]] = None):
        if window_s is None:
            window_s = knobs.get_float(WINDOW_ENV, DEFAULT_WINDOW_S,
                                       env=env)
        self.policy = policy
        self.window_s = max(0.1, float(window_s))
        self.metrics = metrics
        self._lock = threading.Lock()
        self._obs: Dict[str, deque] = {
            f: deque(maxlen=MAX_WINDOW_OBSERVATIONS) for f in FAMILIES}

    # -- engine-side observations --------------------------------------- #
    def _observe(self, family: str, violated: bool, req: Any = None,
                 value_s: Optional[float] = None,
                 target_s: Optional[float] = None) -> bool:
        now = time.monotonic()
        with self._lock:
            dq = self._obs[family]
            dq.append((now, violated))
            cutoff = now - self.window_s
            while dq and dq[0][0] < cutoff:
                dq.popleft()
        if violated:
            if self.metrics is not None:
                self.metrics.inc("slo_violations")
            telemetry.emit(
                "slo_violation",
                trace=getattr(req, "trace_id", None),
                request=getattr(req, "request_id", None),
                family=family,
                value_ms=(round(value_s * 1e3, 3)
                          if value_s is not None else None),
                target_ms=(round(target_s * 1e3, 3)
                           if target_s is not None else None))
        return violated

    def observe_ttft(self, ttft_s: float, req: Any = None) -> bool:
        """One request's measured TTFT; returns whether it violated."""
        target = self.policy.ttft_target_s
        if target is None:
            return False
        return self._observe("ttft", ttft_s > target, req,
                             value_s=ttft_s, target_s=target)

    def observe_token(self, gap_s: float, req: Any = None) -> bool:
        """One inter-token gap of one request's stream."""
        target = self.policy.token_cadence_target_s
        if target is None:
            return False
        return self._observe("token_cadence", gap_s > target, req,
                             value_s=gap_s, target_s=target)

    def observe_deadline_met(self, req: Any = None) -> None:
        """A request that made it to prefill within its deadline — the
        non-violation half of the deadline family's window (without it,
        one shed would read as a 100% violation rate).  Called at the
        PREFILL seam (once per served request), never at queue pop:
        a pool-full head request is re-popped every engine-loop
        iteration, and per-pop observations would drown real sheds in
        spurious non-violations exactly under the overload the burn
        rate exists to flag."""
        if self.policy.deadline_s is not None:
            self._observe("deadline", False, req)

    def shed(self, req: Any, waited_s: float) -> DeadlineExceeded:
        """Account one deadline shed and build its typed failure (the
        engine fails the popped request's future with it)."""
        if self.metrics is not None:
            self.metrics.inc("slo_deadline_shed")
        self._observe("deadline", True, req, value_s=waited_s,
                      target_s=self.policy.deadline_s)
        return DeadlineExceeded(getattr(req, "request_id", -1),
                                waited_s, self.policy.deadline_s or 0.0)

    # -- exports --------------------------------------------------------- #
    def _family_rates(self) -> Dict[str, Dict[str, Any]]:
        now = time.monotonic()
        cutoff = now - self.window_s
        out: Dict[str, Dict[str, Any]] = {}
        with self._lock:
            for family, dq in self._obs.items():
                while dq and dq[0][0] < cutoff:
                    dq.popleft()
                n = len(dq)
                v = sum(1 for _ts, bad in dq if bad)
                out[family] = {"observations": n, "violations": v,
                               "violation_fraction":
                                   round(v / n, 6) if n else 0.0}
        return out

    def _burn_from(self, rates: Mapping[str, Mapping[str, Any]]) -> float:
        allowed = 1.0 - self.policy.target_fraction
        if allowed <= 0:
            return 0.0
        enabled = {
            "ttft": self.policy.ttft_target_s,
            "token_cadence": self.policy.token_cadence_target_s,
            "deadline": self.policy.deadline_s,
        }
        burn = 0.0
        for family, target in enabled.items():
            if target is None:
                continue
            burn = max(burn,
                       rates[family]["violation_fraction"] / allowed)
        return round(burn, 6)

    def family_rates(self) -> Dict[str, Dict[str, Any]]:
        """Per-family windowed rates (observations, violations,
        violation_fraction) — the ttft-vs-cadence burn SPLIT the
        disaggregated-lane autoscaler sizes its two lanes off
        (serve/controller.py ``_lane_for_growth_locked``).  Ships with
        every chunk's stats snapshot, so the driver reads it without
        extra dispatches."""
        return self._family_rates()

    def burn_rate(self) -> float:
        """Observed violation fraction over the allowed fraction
        (``1 - target_fraction``), maxed across enabled families.
        0 = clean window; 1 = consuming the error budget exactly;
        saturates at ``1/allowed`` when every observation violates."""
        return self._burn_from(self._family_rates())

    def gauges(self) -> Dict[str, float]:
        """The live gauge set ServeMetrics merges into every snapshot
        (``bind_slo``) — the exact signals ROADMAP item 3's autoscaler
        and admission control consume.  One window scan per call: the
        rates feed both gauges (this runs on every /metrics scrape)."""
        rates = self._family_rates()
        return {
            "slo_burn_rate": self._burn_from(rates),
            "slo_window_observations": float(sum(
                r["observations"] for r in rates.values())),
        }

    def snapshot(self) -> Dict[str, Any]:
        rates = self._family_rates()
        return {"policy": self.policy.describe(),
                "window_s": self.window_s,
                "burn_rate": self._burn_from(rates),
                "families": rates}
