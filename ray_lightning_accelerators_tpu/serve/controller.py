"""Self-healing serve-tier controller: routing, retries, hedging,
circuit-breaker revival, and SLO-burn autoscaling over ``ServeReplicas``.

PR 14 built the signal plane — live SLO burn rate, deadline sheds, pool
occupancy, per-rank health — but nothing consumed it: the replica tier
round-robin-dispatched chunks, a failed replica stayed down until a
human called ``revive(rank)``, and load had nowhere to go but the
queue.  This module is the closed loop that consumes those signals:

- **Health/load-aware routing** (`route`): every dispatch picks the
  live replica with the least in-flight work, skipping replicas the
  watchdog classifies slow/wedged, replicas whose own engine snapshot
  (shipped back with every chunk result) shows a p99 decode-step
  latency past the slow threshold, and replicas whose circuit is open
  or that are draining.  Slow replicas are used only when no healthy
  one has capacity — degraded beats unavailable.

- **Retry budgets with backoff** (`charge_retry`): an infra-failed
  request re-queues head-of-line with an exponential-backoff-with-half-
  jitter ``not_before`` stamp (``utils/backoff.py`` — the exact
  schedule ``ElasticRunner`` uses), bounded by ``max_retries``; the
  requeue LANE holds until the backoff expires so a retry never loses
  its place to newer admissions.

- **Hedging** (`maybe_hedge`): when a replica goes slow (watchdog
  straggler state, stale-but-not-wedged heartbeat, or p99 over the
  threshold), its OLDEST in-flight chunk is speculatively re-dispatched
  to a healthy replica.  Exactly-once responses are preserved by the
  ``ServeResponse`` first-completion-wins contract — whichever copy
  answers first wins, the loser's completions report False and are
  never double-counted.

- **Circuit breaker + auto-revive** (`maybe_revive`): an infra failure
  opens the replica's circuit; the reopen delay backs off exponentially
  with the number of recent failures in the breaker window (N failures
  in window ⇒ exponentially longer open).  When the open period
  expires the breaker goes HALF-OPEN: the controller restarts the
  worker, re-initializes its engine, and sends one probe dispatch —
  only a successful probe closes the circuit and rejoins rotation.

- **Autoscale + brownout** (`autoscale`, `should_shed`): sustained SLO
  burn (the PR 14 ``slo_burn_rate`` gauge riding every chunk's stats)
  or queue occupancy past the high watermark scales the replica count
  up (bounded by ``max_replicas``); a sustained idle tier drains one
  replica gracefully — stop routing to it, let its in-flight chunks
  finish on the existing retire path, then stop the worker.  A
  saturated tier with no scale-up headroom sheds typed
  (``BrownoutShed(QueueFull)``) at the watermark, before the queue
  grows to its hard cap.

The controller is driver-side bookkeeping only: host scalars, one lock,
no device values, no dispatches under the lock (revive/scale block on
worker round-trips and run in the tick thread with the lock released).
"""

from __future__ import annotations

import itertools
import threading
import time
from collections import deque
from dataclasses import dataclass, fields, replace
from typing import Any, Dict, List, Optional, Tuple

from ..analysis import knobs
from ..telemetry import recorder as telemetry
from ..utils.backoff import backoff_delay_s
from ..utils.logging import log

MAX_RETRIES_ENV = "RLA_TPU_SERVE_MAX_RETRIES"
RETRY_BACKOFF_ENV = "RLA_TPU_SERVE_RETRY_BACKOFF_S"
RETRY_BACKOFF_CAP_ENV = "RLA_TPU_SERVE_RETRY_BACKOFF_CAP_S"
HEDGE_ENV = "RLA_TPU_SERVE_HEDGE"
SLOW_P99_ENV = "RLA_TPU_SERVE_SLOW_P99_S"
BREAKER_FAILURES_ENV = "RLA_TPU_SERVE_BREAKER_FAILURES"
BREAKER_WINDOW_ENV = "RLA_TPU_SERVE_BREAKER_WINDOW_S"
REVIVE_BACKOFF_ENV = "RLA_TPU_SERVE_REVIVE_BACKOFF_S"
REVIVE_BACKOFF_CAP_ENV = "RLA_TPU_SERVE_REVIVE_BACKOFF_CAP_S"
MAX_REPLICAS_ENV = "RLA_TPU_SERVE_MAX_REPLICAS"
SCALE_UP_BURN_ENV = "RLA_TPU_SERVE_SCALE_UP_BURN"
BROWNOUT_FRAC_ENV = "RLA_TPU_SERVE_BROWNOUT_FRAC"
AFFINITY_ENV = "RLA_TPU_SERVE_AFFINITY"
AFFINITY_VNODES_ENV = "RLA_TPU_SERVE_AFFINITY_VNODES"
AFFINITY_RESIDENCY_ENV = "RLA_TPU_SERVE_AFFINITY_RESIDENCY"
PREFILL_REPLICAS_ENV = "RLA_TPU_SERVE_PREFILL_REPLICAS"
HANDOFF_MIN_BLOCKS_ENV = "RLA_TPU_SERVE_HANDOFF_MIN_BLOCKS"

# replica states (the rla_top table vocabulary)
STATE_OK = "ok"
STATE_SLOW = "slow"
STATE_OPEN = "open"            # circuit open: down, waiting out backoff
STATE_HALF_OPEN = "half-open"  # revival probe in flight
STATE_DRAINING = "draining"    # scale-down: no new chunks, finishing

# disaggregated lanes (the rla_top "lane" column vocabulary)
LANE_PREFILL = "prefill"
LANE_DECODE = "decode"


@dataclass(frozen=True)
class ControllerConfig:
    """Policy knobs for one :class:`ReplicaController`.

    A plain ``ControllerConfig(...)`` is taken LITERALLY (its field
    values are the policy, env knobs ignored);
    ``ControllerConfig.from_env(**overrides)`` builds the env-knob
    policy with explicit overrides winning — use it when both should
    apply.  ``ServeReplicas(controller=None)`` defaults to
    ``from_env()``.  ``None`` thresholds disable their signal."""

    # routing / dispatch
    max_inflight_chunks: int = 2     # per replica, hedges included
    # retry budget (infra failures per request) + backoff schedule
    max_retries: int = 2
    retry_backoff_s: float = 0.02
    retry_backoff_cap_s: float = 1.0
    # hedging
    hedge: bool = True
    hedge_age_s: Optional[float] = None   # None = watchdog slow trigger
    slow_p99_s: Optional[float] = None    # p99 decode-step slow threshold
    # circuit breaker / revival
    breaker_failures: int = 3
    breaker_window_s: float = 30.0
    revive_backoff_s: float = 0.5
    revive_backoff_cap_s: float = 15.0
    auto_revive: bool = True
    probe_timeout_s: float = 60.0
    # autoscale / brownout
    max_replicas: Optional[int] = None    # None = no scale-up
    min_replicas: Optional[int] = None    # None = the initial count
    scale_up_burn: float = 1.0
    occupancy_high: float = 0.5           # queue-depth fraction
    scale_sustain_s: float = 2.0
    idle_sustain_s: float = 10.0
    # burn signals ride chunk COMPLETIONS: once traffic stops they
    # would never refresh, so a reading older than this counts as 0 —
    # without it an idle tier would stay "hot" on its last overloaded
    # chunk forever and never drain
    burn_stale_s: float = 5.0
    brownout: bool = True
    brownout_frac: float = 0.9
    # prefix-affinity routing: route to the replica whose cache holds
    # the longest resident run of the request's chain-hashed prefix
    # keys; health/breaker/drain states always override, hedges count
    # as deliberate misses
    affinity: bool = True
    affinity_vnodes: int = 32
    affinity_residency: int = 4096
    # disaggregated lanes: the lowest `prefill_replicas` ranks form a
    # prefill-heavy lane; prompts with at least `handoff_min_blocks`
    # full KV blocks prefill there and hand their blocks off to a
    # decode-lane replica (0 = lanes disabled, end-to-end serving)
    prefill_replicas: int = 0
    handoff_min_blocks: int = 1
    # tick cadence
    poll_s: float = 0.1

    @classmethod
    def from_env(cls, **overrides: Any) -> "ControllerConfig":
        """Env-knob defaults, overridden by explicit kwargs."""
        cfg = cls(
            max_retries=knobs.get_int(MAX_RETRIES_ENV, cls.max_retries),
            retry_backoff_s=knobs.get_float(RETRY_BACKOFF_ENV,
                                            cls.retry_backoff_s),
            retry_backoff_cap_s=knobs.get_float(RETRY_BACKOFF_CAP_ENV,
                                                cls.retry_backoff_cap_s),
            hedge=knobs.get_bool(HEDGE_ENV, cls.hedge),
            slow_p99_s=knobs.get_float(SLOW_P99_ENV, cls.slow_p99_s),
            breaker_failures=knobs.get_int(BREAKER_FAILURES_ENV,
                                           cls.breaker_failures),
            breaker_window_s=knobs.get_float(BREAKER_WINDOW_ENV,
                                             cls.breaker_window_s),
            revive_backoff_s=knobs.get_float(REVIVE_BACKOFF_ENV,
                                             cls.revive_backoff_s),
            revive_backoff_cap_s=knobs.get_float(
                REVIVE_BACKOFF_CAP_ENV, cls.revive_backoff_cap_s),
            max_replicas=knobs.get_int(MAX_REPLICAS_ENV,
                                       cls.max_replicas),
            scale_up_burn=knobs.get_float(SCALE_UP_BURN_ENV,
                                          cls.scale_up_burn),
            brownout_frac=knobs.get_float(BROWNOUT_FRAC_ENV,
                                          cls.brownout_frac),
            affinity=knobs.get_bool(AFFINITY_ENV, cls.affinity),
            affinity_vnodes=knobs.get_int(AFFINITY_VNODES_ENV,
                                          cls.affinity_vnodes),
            affinity_residency=knobs.get_int(AFFINITY_RESIDENCY_ENV,
                                             cls.affinity_residency),
            prefill_replicas=knobs.get_int(PREFILL_REPLICAS_ENV,
                                           cls.prefill_replicas),
            handoff_min_blocks=knobs.get_int(HANDOFF_MIN_BLOCKS_ENV,
                                             cls.handoff_min_blocks),
        )
        known = {f.name for f in fields(cls)}
        unknown = set(overrides) - known
        if unknown:
            raise TypeError(f"unknown ControllerConfig fields: "
                            f"{sorted(unknown)}")
        return replace(cfg, **overrides) if overrides else cfg


class PrefixAffinityRing:
    """Consistent-hash ring + per-replica prefix-residency tracking.

    Two structures behind one idea — keep a hot shared prefix's KV
    blocks on ONE replica instead of re-prefilling it everywhere:

    - **Residency**: a bounded per-replica LRU of the chain-hashed
      prefix keys (serve/batcher.py ``chain_prefix_keys``) last routed
      there.  ``resident_run`` scores a candidate by the longest
      CONSECUTIVE run of a request's keys it holds — the chain hash
      makes any suffix-after-a-gap unusable, exactly like the
      allocator's ``lookup_run``.  This is the router's MODEL of each
      replica's cache, not the cache itself: it is bounded separately
      (``residency_cap``) and cleared whenever a replica's circuit
      opens, because a restarted engine comes back blank.

    - **Ring**: ``vnodes`` virtual nodes per rank.  A request whose
      keys are resident nowhere places on the ring owner of its FIRST
      key, so repeats of a cold prefix converge on one replica instead
      of spraying least-loaded; rank arrival/departure only moves the
      keyspace the consistent hash says it must.

    Not thread-safe on its own: every method is called with the
    owning controller's lock held."""

    def __init__(self, vnodes: int = 32, residency_cap: int = 4096):
        import hashlib

        self._hashlib = hashlib
        self.vnodes = max(1, int(vnodes))
        self.residency_cap = max(1, int(residency_cap))
        self._ring: List[Tuple[int, int]] = []   # (point, rank) sorted
        self._resident: Dict[int, Any] = {}      # rank -> OrderedDict

    def _point(self, token: str) -> int:
        digest = self._hashlib.blake2b(
            token.encode(), digest_size=8).digest()
        return int.from_bytes(digest, "big")

    def add_rank(self, rank: int) -> None:
        if rank in self._resident:
            return
        from collections import OrderedDict
        self._resident[rank] = OrderedDict()
        for v in range(self.vnodes):
            self._ring.append((self._point(f"{rank}:{v}"), rank))
        self._ring.sort()

    def remove_rank(self, rank: int) -> None:
        self._resident.pop(rank, None)
        self._ring = [(p, r) for p, r in self._ring if r != rank]

    def clear_rank(self, rank: int) -> None:
        """Forget a replica's residency (its engine restarted blank)
        without moving its keyspace off the ring."""
        if rank in self._resident:
            self._resident[rank].clear()

    def owner_among(self, key: str, allowed: Any) -> Optional[int]:
        """Ring owner of ``key`` restricted to ``allowed`` ranks: the
        first allowed rank at/after the key's point, wrapping — the
        consistent-hash successor walk, so an unroutable owner's
        keyspace falls to its ring successor, not to a reshuffle."""
        allowed = set(allowed)
        if not self._ring or not allowed:
            return None
        import bisect
        i = bisect.bisect_left(self._ring, (self._point(key), -1))
        for j in range(len(self._ring)):
            rank = self._ring[(i + j) % len(self._ring)][1]
            if rank in allowed:
                return rank
        return None

    def resident_run(self, rank: int, keys: Any) -> int:
        """Longest consecutive run of ``keys`` (from key 0) the rank's
        tracked residency holds."""
        res = self._resident.get(rank)
        if not res:
            return 0
        run = 0
        for key in keys:
            if key not in res:
                break
            run += 1
        return run

    def note(self, rank: int, keys: Any) -> None:
        """MRU-admit ``keys`` into the rank's residency (called at
        route time: the replica is about to prefill-and-register
        exactly these keys)."""
        res = self._resident.get(rank)
        if res is None:
            return
        for key in keys:
            res.pop(key, None)
            res[key] = None
        while len(res) > self.residency_cap:
            res.popitem(last=False)

    def state(self) -> Dict[str, Any]:
        """JSON-able ring view for the controller snapshot."""
        return {
            "vnodes": self.vnodes,
            "residency_cap": self.residency_cap,
            "ranks": sorted(self._resident),
            "residency": {str(rank): len(res)
                          for rank, res in sorted(
                              self._resident.items())},
        }


class _Chunk:
    """One in-flight chunk dispatch (driver-side record)."""

    __slots__ = ("chunk_id", "rank", "items", "t_dispatch", "hedged",
                 "hedge_of")

    def __init__(self, chunk_id: int, rank: int,
                 items: List[Tuple[Any, Any]], hedge_of=None):
        self.chunk_id = chunk_id
        self.rank = rank
        self.items = items          # [(ServeRequest, ServeResponse)]
        self.t_dispatch = time.monotonic()
        self.hedged = False         # a hedge copy was already fired
        self.hedge_of = hedge_of    # (orig rank, orig chunk_id) | None


class ReplicaHealth:
    """Driver-side health/load record of one replica."""

    def __init__(self, rank: int, scaled: bool = False):
        self.rank = rank
        self.state = STATE_OK
        self.scaled = scaled          # added by autoscale: drains first
        self.inflight_chunks = 0
        self.inflight_requests = 0
        self.dispatched_chunks = 0
        self.completed_chunks = 0
        self.app_failures = 0
        self.infra_failures = 0
        self.retries_charged = 0      # requeues this replica caused
        self.hedges = 0               # hedges fired AGAINST this replica
        self.failures: deque = deque()  # breaker window (monotonic ts)
        self.open_until = 0.0
        self.revive_attempts = 0      # consecutive failed revivals
        self.revivals = 0
        self.last_detail = ""
        self.last_stats: Dict[str, Any] = {}
        self.p99_step_s: Optional[float] = None
        self.lane = LANE_DECODE       # disaggregated-lane assignment
        self.prefix_hits = 0          # affinity routes that found a run
        self.prefix_misses = 0        # affinity routes that found none
        self.slo_families: Dict[str, Any] = {}  # per-family SLO rates
        self.slo_burn = 0.0
        self.burn_updated = 0.0       # monotonic ts of the last reading
        self.compile_count: Optional[int] = None
        self.chunks: Dict[int, _Chunk] = {}

    def row(self, now: float) -> Dict[str, Any]:
        """JSON-able snapshot row (the /statusz + rla_top shape)."""
        return {
            "rank": self.rank,
            "state": self.state,
            "scaled": self.scaled,
            "inflight_chunks": self.inflight_chunks,
            "inflight_requests": self.inflight_requests,
            "dispatched_chunks": self.dispatched_chunks,
            "completed_chunks": self.completed_chunks,
            "app_failures": self.app_failures,
            "infra_failures": self.infra_failures,
            "retries": self.retries_charged,
            "hedges": self.hedges,
            "revivals": self.revivals,
            "open_for_s": (round(self.open_until - now, 3)
                           if self.state == STATE_OPEN
                           and self.open_until > now else 0.0),
            "p99_step_ms": (round(self.p99_step_s * 1e3, 3)
                            if self.p99_step_s is not None else None),
            "lane": self.lane,
            "prefix_hits": self.prefix_hits,
            "prefix_misses": self.prefix_misses,
            "prefix_hit_rate": (
                round(self.prefix_hits
                      / (self.prefix_hits + self.prefix_misses), 4)
                if self.prefix_hits + self.prefix_misses else None),
            "slo_burn": round(float(self.slo_burn), 4),
            "compile_count": self.compile_count,
            "detail": self.last_detail,
        }


class ReplicaController:
    """The policy brain over one ``ServeReplicas`` group.

    The group delegates every routing/recovery/scale decision here and
    provides the mechanics: ``group._worker(rank)``,
    ``group._dispatch(rank, chunk, hedge_of=)``,
    ``group._revive_replica(rank)``, ``group._add_replica()`` and
    ``group._retire_replica(rank)``.  All controller state lives behind
    one lock; blocking worker round-trips (revive probes, scale-up
    spawns) run in the tick thread with the lock released."""

    def __init__(self, group: Any, config: Optional[ControllerConfig]
                 = None):
        self.group = group
        self.cfg = config or ControllerConfig.from_env()
        self.metrics = group.metrics
        self._lock = threading.RLock()
        self._replicas: Dict[int, ReplicaHealth] = {
            w.rank: ReplicaHealth(w.rank) for w in group.pool.workers}
        self.affinity = PrefixAffinityRing(self.cfg.affinity_vnodes,
                                           self.cfg.affinity_residency)
        # lane assignment: the lowest `prefill_replicas` ranks form the
        # prefill lane (deterministic, so a restart reproduces it)
        for i, rank in enumerate(sorted(self._replicas)):
            self.affinity.add_rank(rank)
            if self.cfg.prefill_replicas > 0 \
                    and i < self.cfg.prefill_replicas:
                self._replicas[rank].lane = LANE_PREFILL
        self._chunk_ids = itertools.count()
        self._min_replicas = (self.cfg.min_replicas
                              if self.cfg.min_replicas is not None
                              else len(self._replicas))
        self._hot_since: Optional[float] = None
        self._idle_since: Optional[float] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------------ #
    # Lifecycle                                                          #
    # ------------------------------------------------------------------ #
    def start(self) -> "ReplicaController":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, daemon=True,
                name="rla-tpu-serve-controller")
            self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.cfg.poll_s):
            try:
                self.tick()
            except Exception as e:  # policy must never kill the tier
                log.warning("serve controller tick failed: %s", e)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=30)
            self._thread = None

    # ------------------------------------------------------------------ #
    # Routing                                                            #
    # ------------------------------------------------------------------ #
    def route(self, exclude: Any = (),
              prefix_keys: Optional[Any] = None,
              lane: Optional[str] = None) -> Optional[int]:
        """The replica the next chunk should go to, or None when no
        replica can take work right now.

        Health always wins: open/half-open/draining circuits, dead
        processes, full in-flight budgets and the exclude set are
        filtered BEFORE affinity ever looks — a resident prefix on a
        broken replica is not a destination.  ``lane`` restricts to
        one disaggregated lane when lanes are enabled, spilling to any
        lane rather than returning None (availability beats
        disaggregation).  Within the survivors: the longest resident
        run of ``prefix_keys`` wins (tier + per-replica hit counted),
        a cold prefix places on its consistent-hash ring owner so
        repeats converge (counted as a miss), and with affinity off or
        no keys it is least-loaded first (in-flight requests, then
        chunks, then p99).  ``slow`` replicas are used only when no
        healthy replica has capacity."""
        skip = set(exclude)
        opened: List[Dict[str, Any]] = []
        counted: Optional[str] = None
        pick_rank: Optional[int] = None
        with self._lock:
            cands: List[Tuple[Tuple[Any, ...], ReplicaHealth]] = []
            for r in self._replicas.values():
                if r.rank in skip or r.state in (STATE_OPEN,
                                                 STATE_HALF_OPEN,
                                                 STATE_DRAINING):
                    continue
                w = self.group._worker(r.rank)
                if w is None or not w.is_alive:
                    opened.append(self._open_locked(r, "process dead"))
                    continue
                if r.inflight_chunks >= self.cfg.max_inflight_chunks:
                    continue
                key = (r.inflight_requests, r.inflight_chunks,
                       r.p99_step_s or 0.0)
                cands.append((key, r))
            if lane is not None and self.cfg.prefill_replicas > 0:
                in_lane = [c for c in cands if c[1].lane == lane]
                if in_lane:  # an empty/down lane spills cross-lane
                    cands = in_lane
            healthy = [c for c in cands if c[1].state != STATE_SLOW]
            tier = healthy or cands
            pick: Optional[ReplicaHealth] = None
            hit = False
            if tier and self.cfg.affinity and prefix_keys:
                best_run, best = 0, None
                for key, r in tier:
                    run = self.affinity.resident_run(r.rank,
                                                     prefix_keys)
                    if run > best_run or (run == best_run > 0
                                          and key < best[0]):
                        best_run, best = run, (key, r)
                if best is not None:
                    pick, hit = best[1], True
                else:
                    owner = self.affinity.owner_among(
                        prefix_keys[0], [r.rank for _, r in tier])
                    if owner is not None:
                        pick = next(r for _, r in tier
                                    if r.rank == owner)
            if pick is None and tier:
                pick = min(tier, key=lambda c: c[0])[1]
            if pick is not None:
                pick_rank = pick.rank
                if self.cfg.affinity and prefix_keys:
                    if hit:
                        pick.prefix_hits += 1
                        counted = "prefix_route_hits"
                    else:
                        pick.prefix_misses += 1
                        counted = "prefix_route_misses"
                    self.affinity.note(pick.rank, prefix_keys)
        self._emit_opened(opened)
        if counted is not None:  # metrics lock outside the controller's
            self.metrics.inc(counted)
        return pick_rank

    def note_import(self, rank: int, prefix_keys: Optional[Any]) -> None:
        """Record prefix residency a KV IMPORT just landed on ``rank``
        (the decode replica registered the shipped blocks under their
        chain keys), WITHOUT counting a route: the request's hit/miss
        was already accounted where the prefill routed.  Keeps the ring
        truthful so future same-prefix requests route to the replica
        that actually holds the KV now."""
        if not self.cfg.affinity or not prefix_keys:
            return
        with self._lock:
            if rank in self._replicas:
                self.affinity.note(rank, list(prefix_keys))

    def serving_possible(self) -> bool:
        """False only when NO replica can ever take work again: every
        circuit is open/draining and auto-revive is off (with revival
        on, a fully-down tier is a transient the queue waits out)."""
        with self._lock:
            if any(r.state in (STATE_OK, STATE_SLOW, STATE_HALF_OPEN)
                   for r in self._replicas.values()):
                return True
            return self.cfg.auto_revive and bool(self._replicas)

    # ------------------------------------------------------------------ #
    # Dispatch accounting                                                #
    # ------------------------------------------------------------------ #
    def on_dispatch(self, rank: int, items: List[Tuple[Any, Any]],
                    hedge_of=None) -> int:
        with self._lock:
            chunk_id = next(self._chunk_ids)
            r = self._replicas.get(rank)
            if r is not None:
                c = _Chunk(chunk_id, rank, list(items), hedge_of)
                r.chunks[chunk_id] = c
                r.inflight_chunks += 1
                r.inflight_requests += len(items)
                r.dispatched_chunks += 1
            return chunk_id

    def _finish_chunk_locked(self, rank: int,
                             chunk_id: int) -> Optional[_Chunk]:
        r = self._replicas.get(rank)
        if r is None:
            return None
        c = r.chunks.pop(chunk_id, None)
        if c is not None:
            r.inflight_chunks = max(0, r.inflight_chunks - 1)
            r.inflight_requests = max(
                0, r.inflight_requests - len(c.items))
        return c

    def note_success(self, rank: int, chunk_id: int,
                     stats: Optional[Dict[str, Any]] = None) -> None:
        """A chunk completed; ``stats`` is the replica engine's own
        snapshot shipped back with the result — the load/SLO signal
        routing and autoscaling consume (no extra dispatches)."""
        with self._lock:
            self._finish_chunk_locked(rank, chunk_id)
            r = self._replicas.get(rank)
            if r is None:
                return
            r.completed_chunks += 1
            if stats:
                r.last_stats = dict(stats)
                step = stats.get("decode_step_s") or {}
                r.p99_step_s = step.get("p99_s")
                burn = stats.get("slo_burn_rate")
                r.slo_burn = float(burn) if isinstance(
                    burn, (int, float)) else 0.0
                fam = stats.get("slo_families")
                if isinstance(fam, dict):
                    # the ttft-vs-cadence split lane autoscaling reads
                    r.slo_families = fam
                r.burn_updated = time.monotonic()
                cc = stats.get("compile_count")
                if isinstance(cc, int):
                    r.compile_count = cc
            # a replica answering chunks with a healthy p99 is not slow
            if r.state == STATE_SLOW and not self._p99_slow(r):
                r.state = STATE_OK
                r.last_detail = ""

    def note_app_failure(self, rank: int, chunk_id: int) -> None:
        """Deterministic application failure: the requests fail typed,
        the replica keeps serving and the breaker does NOT count it."""
        with self._lock:
            self._finish_chunk_locked(rank, chunk_id)
            r = self._replicas.get(rank)
            if r is not None:
                r.app_failures += 1

    def note_infra_failure(self, rank: int, chunk_id: int,
                           exc: BaseException) -> None:
        """Replica died or was reaped wedged: open its circuit.  The
        reopen backoff starts at the base delay and grows exponentially
        once the breaker window holds ``breaker_failures`` failures —
        N failures in window ⇒ exponentially longer open period."""
        opened = None
        with self._lock:
            self._finish_chunk_locked(rank, chunk_id)
            r = self._replicas.get(rank)
            if r is not None:
                r.infra_failures += 1
                opened = self._open_locked(
                    r, f"{type(exc).__name__}: {str(exc)[:120]}")
        self._emit_opened([opened] if opened else [])

    def charge_retry(self, rank: Optional[int], req: Any) -> float:
        """Account one requeue against ``rank`` and return the retry
        backoff delay for this request's next dispatch (half-jitter
        exponential in its requeue count — the elastic schedule)."""
        with self._lock:
            r = self._replicas.get(rank) if rank is not None else None
            if r is not None:
                r.retries_charged += 1
        return backoff_delay_s(req.requeues + 1,
                               self.cfg.retry_backoff_s,
                               self.cfg.retry_backoff_cap_s)

    def _reopen_attempt_locked(self, r: ReplicaHealth) -> int:
        """The reopen-backoff exponent: 1 (base delay) until the
        breaker window holds ``breaker_failures`` failures, then
        growing with the excess — the breaker's "N failures in window
        ⇒ exponentially longer open" — and never below what the
        consecutive failed-revival count already earned."""
        over = len(r.failures) - max(1, self.cfg.breaker_failures) + 1
        return max(1, 1 + over, r.revive_attempts + 1)

    def _open_locked(self, r: ReplicaHealth,
                     detail: str) -> Optional[Dict[str, Any]]:
        """Transition ``r`` to circuit-open (no-op if already open: one
        replica death must count ONE breaker failure, not one per
        in-flight chunk callback).  Returns the transition event for
        the caller to emit OUTSIDE the controller lock — a recorder
        spill is disk I/O, and route()/note_* must not stall on it."""
        if r.state == STATE_OPEN:
            return None
        now = time.monotonic()
        r.failures.append(now)
        cutoff = now - self.cfg.breaker_window_s
        while r.failures and r.failures[0] < cutoff:
            r.failures.popleft()
        prev = r.state
        r.state = STATE_OPEN
        r.last_detail = detail
        # the revive path rebuilds the engine blank: the router's
        # residency model must forget, or post-revival affinity would
        # "hit" a cache that no longer exists
        self.affinity.clear_rank(r.rank)
        r.open_until = now + backoff_delay_s(
            self._reopen_attempt_locked(r), self.cfg.revive_backoff_s,
            self.cfg.revive_backoff_cap_s)
        return {"replica": r.rank, "prev": prev, "detail": detail,
                "reopen_s": round(r.open_until - now, 3)}

    def _emit_opened(self, opened: List[Optional[Dict[str, Any]]]
                     ) -> None:
        for ev in opened:
            if not ev:
                continue
            telemetry.emit("serve_replica_state", replica=ev["replica"],
                           prev=ev["prev"], state=STATE_OPEN,
                           detail=ev["detail"])
            log.warning("serve replica %d circuit OPEN (%s); reopen in "
                        "%.2fs", ev["replica"], ev["detail"],
                        ev["reopen_s"])

    # ------------------------------------------------------------------ #
    # Tick: health refresh, hedging, revival, autoscale                  #
    # ------------------------------------------------------------------ #
    def tick(self) -> None:
        now = time.monotonic()
        self._refresh_health(now)
        if self.cfg.hedge:
            self.maybe_hedge(now)
        if self.cfg.auto_revive:
            self.maybe_revive(now)
        self.autoscale(now)

    def _p99_slow(self, r: ReplicaHealth) -> bool:
        return (self.cfg.slow_p99_s is not None
                and r.p99_step_s is not None
                and r.p99_step_s > self.cfg.slow_p99_s)

    def _refresh_health(self, now: float) -> None:
        wd = getattr(self.group, "watchdog", None)
        wd_states = wd.states() if wd is not None else {}
        slowed: List[Tuple[int, str]] = []
        with self._lock:
            for r in self._replicas.values():
                if r.state in (STATE_OPEN, STATE_HALF_OPEN,
                               STATE_DRAINING):
                    continue
                wd_state = wd_states.get(r.rank)
                slow = wd_state == "slow" or self._p99_slow(r)
                if slow and r.state == STATE_OK:
                    r.state = STATE_SLOW
                    r.last_detail = ("watchdog straggler"
                                     if wd_state == "slow" else
                                     f"p99 {r.p99_step_s:.3f}s > "
                                     f"{self.cfg.slow_p99_s:.3f}s")
                    slowed.append((r.rank, r.last_detail))
                elif not slow and r.state == STATE_SLOW \
                        and not r.chunks:
                    # stale chunks keep it slow until hedge/failure
                    r.state = STATE_OK
                    r.last_detail = ""
        # emitted outside the lock: recorder spills are disk I/O and
        # the dispatcher's route() must not stall behind them
        for rank, detail in slowed:
            telemetry.emit("serve_replica_state", replica=rank,
                           prev=STATE_OK, state=STATE_SLOW,
                           detail=detail)

    def _hedge_age_s(self) -> float:
        if self.cfg.hedge_age_s is not None:
            return self.cfg.hedge_age_s
        wd = getattr(self.group, "watchdog", None)
        if wd is not None:
            return max(0.25, float(wd.slow_after_s))
        return 1.0

    def maybe_hedge(self, now: Optional[float] = None) -> int:
        """Re-dispatch the oldest unhedged in-flight chunk of every
        slow replica to a healthy one.  Returns hedges fired."""
        now = time.monotonic() if now is None else now
        age_bar = self._hedge_age_s()
        to_hedge: List[Tuple[int, _Chunk]] = []
        with self._lock:
            for r in self._replicas.values():
                if r.state != STATE_SLOW or not r.chunks:
                    continue
                oldest = min(r.chunks.values(),
                             key=lambda c: c.t_dispatch)
                if oldest.hedged or oldest.hedge_of is not None:
                    continue
                if now - oldest.t_dispatch < age_bar:
                    continue
                to_hedge.append((r.rank, oldest))
        fired = 0
        for rank, chunk in to_hedge:
            target = self.route(exclude=(rank,))
            if target is None:
                continue  # nowhere healthy to hedge to right now
            items = [(req, resp) for req, resp in chunk.items
                     if not resp.done()]
            if not items:
                continue
            with self._lock:
                chunk.hedged = True
                r = self._replicas.get(rank)
                if r is not None:
                    r.hedges += 1
                if self.cfg.affinity:
                    # a hedge deliberately abandons prefix locality —
                    # latency rescue outranks cache reuse — so it is
                    # accounted as a miss on the target, keeping the
                    # hit-rate honest about re-prefill cost
                    t = self._replicas.get(target)
                    if t is not None:
                        t.prefix_misses += 1
            self.metrics.inc("hedged")
            if self.cfg.affinity:
                self.metrics.inc("prefix_route_misses")
            telemetry.emit("serve_hedge", slow_replica=rank,
                           target=target, requests=len(items),
                           chunk_age_ms=round(
                               (now - chunk.t_dispatch) * 1e3, 1))
            log.warning("hedging %d request(s) of slow replica %d "
                        "onto replica %d", len(items), rank, target)
            self.group._dispatch(target, items,
                                 hedge_of=(rank, chunk.chunk_id))
            fired += 1
        return fired

    def maybe_revive(self, now: Optional[float] = None) -> int:
        """Half-open probe for every open circuit whose backoff
        expired (one replica per call — revival blocks on a worker
        restart round-trip).  Returns successful revivals."""
        now = time.monotonic() if now is None else now
        candidate: Optional[int] = None
        with self._lock:
            for r in self._replicas.values():
                if r.state == STATE_OPEN and now >= r.open_until:
                    r.state = STATE_HALF_OPEN
                    candidate = r.rank
                    break
        if candidate is None:
            return 0
        ok = False
        try:
            # blocking: restart + engine init + one probe dispatch
            self.group._revive_replica(candidate)
            ok = True
        except BaseException as e:
            log.warning("half-open probe of replica %d failed: %s",
                        candidate, e)
        with self._lock:
            r = self._replicas.get(candidate)
            if r is None:
                return 0
            if ok:
                r.state = STATE_OK
                r.last_detail = ""
                r.revive_attempts = 0
                r.revivals += 1
                self.metrics.inc("revived")
                telemetry.emit("serve_revive", replica=candidate)
                log.warning("serve replica %d revived (circuit closed)",
                            candidate)
            else:
                r.revive_attempts += 1
                r.state = STATE_OPEN
                r.open_until = time.monotonic() + backoff_delay_s(
                    self._reopen_attempt_locked(r),
                    self.cfg.revive_backoff_s,
                    self.cfg.revive_backoff_cap_s)
        return 1 if ok else 0

    def note_revived(self, rank: int) -> None:
        """Manual ``revive(rank)`` succeeded outside the breaker."""
        with self._lock:
            r = self._replicas.get(rank)
            if r is None:
                return
            r.state = STATE_OK
            r.last_detail = ""
            r.revive_attempts = 0
            r.revivals += 1
        self.metrics.inc("revived")

    # ------------------------------------------------------------------ #
    # Autoscale / brownout                                               #
    # ------------------------------------------------------------------ #
    def _can_grow_locked(self) -> bool:
        return (self.cfg.max_replicas is not None
                and len(self._replicas) < self.cfg.max_replicas)

    def _overload_signals(self, now: float) -> Tuple[float, float, int]:
        """(max FRESH burn over live replicas, queue occupancy,
        in-flight requests).  Burn readings older than ``burn_stale_s``
        count as 0 — they only refresh with chunk completions."""
        depth = self.group.batcher.depth
        cap = max(1, self.group.queue_depth)
        with self._lock:
            burn = max((r.slo_burn for r in self._replicas.values()
                        if r.state in (STATE_OK, STATE_SLOW)
                        and now - r.burn_updated
                        <= self.cfg.burn_stale_s),
                       default=0.0)
            inflight = sum(r.inflight_requests
                           for r in self._replicas.values())
        return burn, depth / cap, inflight

    def _lane_for_growth_locked(self, now: float) -> str:
        """Which lane a scale-up replica joins: the ttft-vs-cadence
        burn split the SloTracker ships per chunk decides.  TTFT
        violations dominating means prefill is the bottleneck — grow
        the prefill lane; cadence dominating (or no fresh signal)
        grows decode.  Only meaningful with lanes enabled."""
        ttft = cadence = 0.0
        for r in self._replicas.values():
            if r.state not in (STATE_OK, STATE_SLOW):
                continue
            if now - r.burn_updated > self.cfg.burn_stale_s:
                continue
            fam = r.slo_families or {}
            ttft = max(ttft, float((fam.get("ttft") or {}).get(
                "violation_fraction") or 0.0))
            cadence = max(cadence, float(
                (fam.get("token_cadence") or {}).get(
                    "violation_fraction") or 0.0))
        return LANE_PREFILL if ttft > cadence else LANE_DECODE

    def autoscale(self, now: Optional[float] = None) -> None:
        now = time.monotonic() if now is None else now
        burn, occupancy, inflight = self._overload_signals(now)
        hot = (burn >= self.cfg.scale_up_burn
               or occupancy >= self.cfg.occupancy_high)
        # idle = the occupancy watermark at zero with nothing in
        # flight; sustained over idle_sustain_s before any drain
        idle = (occupancy == 0.0 and inflight == 0)
        # -- scale up ---------------------------------------------------- #
        if hot:
            self._idle_since = None
            if self._hot_since is None:
                self._hot_since = now
            elif now - self._hot_since >= self.cfg.scale_sustain_s:
                grow = False
                with self._lock:
                    grow = self._can_grow_locked()
                if grow:
                    self._hot_since = None  # re-arm the sustain window
                    try:
                        # blocking spawn+init in the tick thread
                        rank = self.group._add_replica()
                    except BaseException as e:
                        log.warning("serve scale-up failed: %s", e)
                        return
                    with self._lock:
                        health = ReplicaHealth(rank, scaled=True)
                        if self.cfg.prefill_replicas > 0:
                            health.lane = self._lane_for_growth_locked(
                                now)
                        self._replicas[rank] = health
                        self.affinity.add_rank(rank)
                        lane = health.lane
                    self.metrics.inc("scale_ups")
                    telemetry.emit("serve_scale_up", replica=rank,
                                   lane=lane,
                                   burn=round(burn, 3),
                                   occupancy=round(occupancy, 3))
                    log.warning("serve scale-UP: added replica %d "
                                "to %s lane (burn %.2f, occupancy "
                                "%.2f)", rank, lane, burn, occupancy)
            return
        self._hot_since = None
        # -- scale down (graceful drain) --------------------------------- #
        retire: Optional[int] = None
        drained: Optional[Tuple[int, str]] = None
        with self._lock:
            serving = [r for r in self._replicas.values()
                       if r.state != STATE_DRAINING]
            if idle and len(serving) > self._min_replicas:
                if self._idle_since is None:
                    self._idle_since = now
                elif now - self._idle_since >= self.cfg.idle_sustain_s:
                    self._idle_since = None
                    # drain preference: autoscaled first, then highest
                    # rank; never a replica with work in flight
                    cands = [r for r in serving
                             if r.state in (STATE_OK, STATE_SLOW)
                             and not r.chunks]
                    if self.cfg.prefill_replicas > 0:
                        # lanes enabled: never drain a lane to zero —
                        # an empty lane forces every request cross-lane
                        # and silently undoes the disaggregation
                        lane_counts: Dict[str, int] = {}
                        for r in serving:
                            lane_counts[r.lane] = lane_counts.get(
                                r.lane, 0) + 1
                        cands = [r for r in cands
                                 if lane_counts.get(r.lane, 0) > 1]
                    if cands:
                        victim = sorted(
                            cands, key=lambda r: (not r.scaled,
                                                  -r.rank))[0]
                        prev = victim.state
                        victim.state = STATE_DRAINING
                        victim.last_detail = "scale-down drain"
                        drained = (victim.rank, prev)
            elif not idle:
                self._idle_since = None
            # drained and empty => retire now (one per tick)
            for r in self._replicas.values():
                if r.state == STATE_DRAINING and not r.chunks:
                    retire = r.rank
                    break
        if drained is not None:  # emit outside the lock (disk I/O)
            telemetry.emit("serve_replica_state", replica=drained[0],
                           prev=drained[1], state=STATE_DRAINING,
                           detail="scale-down")
        if retire is not None:
            try:
                self.group._retire_replica(retire)
            except BaseException as e:
                log.warning("retiring drained replica %d failed: %s",
                            retire, e)
            with self._lock:
                self._replicas.pop(retire, None)
                self.affinity.remove_rank(retire)
            self.metrics.inc("scale_downs")
            telemetry.emit("serve_scale_down", replica=retire)
            log.warning("serve scale-DOWN: drained and retired "
                        "replica %d", retire)

    def should_shed(self) -> Optional[Tuple[int, int, int]]:
        """Brownout decision at admission: ``(depth, watermark, cap)``
        when the tier must shed this request typed, else None.  Sheds
        only when the queue is past the watermark AND no scale-up
        headroom remains — a tier that can still grow queues instead."""
        if not self.cfg.brownout:
            return None
        depth = self.group.batcher.depth
        cap = self.group.queue_depth
        watermark = max(1, int(self.cfg.brownout_frac * cap))
        if depth < watermark:
            return None
        with self._lock:
            if self._can_grow_locked():
                return None
        return depth, watermark, cap

    # ------------------------------------------------------------------ #
    # Export                                                             #
    # ------------------------------------------------------------------ #
    def states(self) -> Dict[int, str]:
        with self._lock:
            return {r.rank: r.state for r in self._replicas.values()}

    def lane_gauges(self) -> Dict[str, float]:
        """Per-lane occupancy gauges ``ServeMetrics`` merges into every
        snapshot (``bind_lanes``): replica count and in-flight requests
        per disaggregated lane.  With lanes disabled every replica
        reports under decode — the gauges stay live, not absent."""
        with self._lock:
            out = {"lane_prefill_replicas": 0.0,
                   "lane_decode_replicas": 0.0,
                   "lane_prefill_inflight": 0.0,
                   "lane_decode_inflight": 0.0}
            for r in self._replicas.values():
                lane = (r.lane if r.lane in (LANE_PREFILL, LANE_DECODE)
                        else LANE_DECODE)
                out[f"lane_{lane}_replicas"] += 1.0
                out[f"lane_{lane}_inflight"] += float(
                    r.inflight_requests)
        return out

    def down_ranks(self) -> List[int]:
        """Ranks currently out of rotation (open/half-open circuits) —
        the ``replicas_down`` compatibility view."""
        with self._lock:
            return sorted(r.rank for r in self._replicas.values()
                          if r.state in (STATE_OPEN, STATE_HALF_OPEN))

    def snapshot(self) -> Dict[str, Any]:
        """JSON-able controller view: per-replica rows + tier-level
        gauges (what /statusz embeds and rla_top renders)."""
        now = time.monotonic()
        depth = self.group.batcher.depth
        cap = self.group.queue_depth
        with self._lock:
            rows = {str(r.rank): r.row(now)
                    for r in self._replicas.values()}
            burn = max((r.slo_burn for r in self._replicas.values()),
                       default=0.0)
            affinity = self.affinity.state()
            affinity["enabled"] = self.cfg.affinity
        return {
            "replicas": rows,
            "affinity": affinity,
            "queue_depth": depth,
            "queue_cap": cap,
            "brownout_watermark": max(1, int(self.cfg.brownout_frac
                                             * cap)),
            "max_burn": round(burn, 4),
            "max_replicas": self.cfg.max_replicas,
            "min_replicas": self._min_replicas,
            "config": {
                "max_retries": self.cfg.max_retries,
                "hedge": self.cfg.hedge,
                "auto_revive": self.cfg.auto_revive,
                "scale_up_burn": self.cfg.scale_up_burn,
                "occupancy_high": self.cfg.occupancy_high,
                "brownout_frac": self.cfg.brownout_frac,
                "affinity": self.cfg.affinity,
                "prefill_replicas": self.cfg.prefill_replicas,
                "handoff_min_blocks": self.cfg.handoff_min_blocks,
            },
        }
