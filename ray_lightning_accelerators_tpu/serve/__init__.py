"""Serve subsystem: continuous-batching inference over the actor runtime.

The training stack already owned every ingredient a server needs — a
static-shaped KV-cache decode loop (models/transformer.py), watchdog-
supervised workers (runtime/watchdog.py), and reservoir-percentile
profiling (utils/profiler.py).  This package composes them into a
request-serving engine:

- **batcher**: bounded admission with typed backpressure (``QueueFull``,
  ``PoolExhausted``, ``RequestRejected``, ``ServeCancelled``);
- **engine**: the continuous-batching driver loop — a block-paged KV
  pool read through traced per-slot block tables (with chain-hashed
  shared-prefix reuse and an optional speculative lane), so
  joining/retiring/growing sequences mid-flight is a table write,
  never a recompile (``paged=False`` keeps the dense up-front
  [L, B, H, total_len, D] cache);
- **metrics**: throughput, queue depth, TTFT and per-token latency at
  p50/p95/p99/max via the profiler's reservoir percentiles;
- **replicas**: N engine replicas on the existing ``ActorPool`` with
  watchdog supervision — a wedged replica is reaped and its in-flight
  requests re-queued onto survivors, never lost or duplicated;
- **controller**: the self-healing closed loop over the replica tier —
  health/load-aware routing, retry budgets with shared exponential
  backoff, hedged re-dispatch of a slow replica's oldest chunk,
  circuit-breaker auto-revival, SLO-burn/occupancy autoscaling and
  typed brownout shedding (``BrownoutShed``).

Exactness is the contract: every response is token-identical to a
standalone greedy ``GPT.generate()`` of the same prompt.
"""

from .batcher import (AdmissionController, BrownoutShed, PoolExhausted,
                      QueueFull, RequestRejected, ServeCancelled,
                      ServeRequest, ServeResponse, blocks_for_request)
from .controller import ControllerConfig, ReplicaController
from .engine import BlockAllocator, ServeEngine
from .metrics import ServeMetrics
from .replicas import ServeReplicas
from .slo import DeadlineExceeded, SloPolicy, SloTracker

__all__ = [
    "AdmissionController", "BrownoutShed", "PoolExhausted", "QueueFull",
    "RequestRejected", "ServeCancelled", "ServeRequest", "ServeResponse",
    "BlockAllocator", "ServeEngine", "ServeMetrics", "ServeReplicas",
    "ControllerConfig", "ReplicaController",
    "blocks_for_request",
    "SloPolicy", "SloTracker", "DeadlineExceeded",
]
