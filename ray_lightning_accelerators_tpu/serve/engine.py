"""Continuous-batching serve engine over the static-shaped decode loop.

**Paged KV cache (default)**: instead of one dense
``[L, max_slots, H, max_total_len, D]`` cache — which pins HBM
proportional to ``max_total_len − actual_len`` for every slot — the
engine owns a fixed pool of ``[L, n_blocks, H, block_len, D]`` KV blocks
plus a per-slot int32 block table.  Decode attention reads through the
indirection (a gather over the table INSIDE the jitted step; tables are
traced operands), so the engine's whole lifecycle is TWO compiled
program families, none ever retraced per request:

- **chunk prefill** (one per suffix-length bucket): run the right-padded
  un-shared part of a prompt through `GPT.decode_chunk_paged`, writing
  its k/v into the request's table-mapped blocks and returning the first
  greedy token;
- **step**: one ``decode_step_rows_paged`` over ALL slots at per-row
  positions, argmax per row.

Joining, retiring and GROWING a sequence (its position crossing a block
boundary into the next pre-reserved block) are host-side table writes —
the PR 2 no-recompile invariant, preserved through the indirection and
pinned by ``analysis.compile_guard`` in the tests.

**Shared-prefix reuse**: prompts are hashed block-wise at admission
(a chain hash, so a block key commits to the WHOLE prefix before it);
full blocks matching the allocator's LRU prefix index are mapped into
the new request's table with a refcount instead of re-prefilled —
system-prompt-heavy traffic skips most of its prefill compute and
shares the HBM.  This is copy-on-write where the copy branch is
provably unreachable: sharers only ever WRITE at positions past their
shared full-prefix blocks (suffix prefill starts at the first un-shared
block; decode writes at ``pos >= prompt_len``), so refcounts alone
guarantee safety.  Evicting an unreferenced cached block is an LRU pop.

**Speculative lane**: constructed with a draft model, an idle engine
routes ``submit(..., speculative=True)`` requests through greedy
speculative decode — the draft proposes ``spec_k`` tokens per round
(`models.speculative.build_draft_proposer`), the target verifies them
in ONE paged chunk pass that drafts into the request's scratch blocks,
and only accepted tokens' positions survive (rejected positions are
rewritten before the causal mask can expose them — the linear-cache
no-rollback property, inherited by the paged layout).  A busy engine
decodes the same request in a normal slot; either lane obeys the
exactness contract, so clients cannot tell them apart.

**Exactness contract**: greedy only; every response is token-identical
to a standalone ``GPT.generate(prompt, max_new_tokens)`` of that
prompt.  This holds because the paged attention performs the same
arithmetic per attended position as the dense decode paths (gathers are
exact value copies; masked positions contribute exactly-zero softmax
terms), pad positions are rewritten before the mask exposes them, and
shared prefix blocks hold bit-identical k/v for an identical token
prefix (k/v are deterministic functions of the prefix).  The CPU test
suite asserts it token-for-token — across staggered join/retire, block
growth, prefix hits and the speculative lane.

``paged=False`` keeps the PR 2 dense allocator (three compiled
programs: bucketed pad-prefill, slot join, batched step) — the probe
uses it as the placed-bytes baseline the paged pool is judged against.
"""

from __future__ import annotations

import itertools
import threading
import time
from collections import OrderedDict, deque
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..telemetry import recorder as telemetry
from ..utils.logging import log
from .batcher import (AdmissionController, ServeCancelled, ServeRequest,
                      ServeResponse, blocks_for_request,
                      chain_prefix_keys)
from .metrics import ServeMetrics

# live-plane labels for engines sharing one process (telemetry/live.py)
_ENGINE_SEQ = itertools.count()


class BlockAllocator:
    """Host-side bookkeeping for the paged pool's physical blocks: a
    free list, per-block refcounts, and an LRU prefix index mapping
    chain-hash keys of FULL prompt blocks to the physical block holding
    their k/v.

    Lifetimes: a freshly allocated block starts at refcount 1 (its
    owner); a prefix hit retains (+1) the shared block for the new
    sharer.  ``release`` drops a reference; an unreferenced block
    returns to the free list UNLESS it is registered in the prefix
    index, where it stays resident as reusable cache until LRU eviction
    reclaims it for a new allocation.  Block 0 is reserved as the
    garbage block (inactive decode rows scatter there) and is never
    handed out.

    Thread-safety: a single lock — the engine loop owns alloc/release,
    but the metrics gauge reads ``stats()`` from other threads.
    """

    def __init__(self, n_blocks: int, block_len: int):
        if n_blocks < 2:
            raise ValueError("the pool needs >= 2 blocks (block 0 is "
                             "the reserved garbage block)")
        if block_len < 1:
            raise ValueError("block_len must be >= 1")
        self.n_blocks = n_blocks
        self.block_len = block_len
        self._lock = threading.Lock()
        self._free: deque = deque(range(1, n_blocks))
        self._ref = np.zeros((n_blocks,), np.int32)
        self._index: "OrderedDict[str, int]" = OrderedDict()  # LRU
        self._key_of: Dict[int, str] = {}

    # ------------------------------------------------------------------ #
    def alloc(self, n: int) -> Optional[List[int]]:
        """``n`` fresh blocks at refcount 1, or None when even evicting
        every unreferenced cached prefix block cannot free enough."""
        with self._lock:
            if n <= 0:
                return []
            while len(self._free) < n:
                if not self._evict_one_locked():
                    return None
            out = [self._free.popleft() for _ in range(n)]
            for b in out:
                self._ref[b] = 1
            return out

    def _evict_one_locked(self) -> bool:
        victim = None
        for key, blk in self._index.items():  # oldest (LRU) first
            if self._ref[blk] == 0:
                victim = (key, blk)
                break
        if victim is None:
            return False
        key, blk = victim
        del self._index[key]
        del self._key_of[blk]
        self._free.append(blk)
        return True

    def lookup_run(self, keys: List[str], max_blocks: int) -> List[int]:
        """Longest run of prefix-index hits from block 0, each RETAINED
        for the caller (and bumped to MRU).  ``max_blocks`` caps the run
        (the engine keeps >= 1 suffix token so the last prompt hidden
        state is actually computed)."""
        out: List[int] = []
        with self._lock:
            for key in keys[:max_blocks]:
                blk = self._index.get(key)
                if blk is None:
                    break
                self._index.move_to_end(key)
                self._ref[blk] += 1
                out.append(blk)
        return out

    def release(self, block: int) -> None:
        """Drop one reference; unreferenced unregistered blocks go back
        to the free list, registered ones stay cached (evictable)."""
        with self._lock:
            self._ref[block] -= 1
            if self._ref[block] <= 0:
                self._ref[block] = 0
                if block not in self._key_of:
                    self._free.append(block)

    def register(self, key: str, block: int) -> bool:
        """Publish a full prompt block under its chain-hash key for
        future prefix hits.  First writer wins: if another block already
        carries the key (two identical prompts admitted concurrently),
        the caller's block stays private and is freed at retire."""
        with self._lock:
            if key in self._index or block in self._key_of:
                return False
            self._index[key] = block
            self._key_of[block] = key
            return True

    def stats(self) -> Dict[str, int]:
        with self._lock:
            used = int((self._ref[1:] > 0).sum())
            cached = sum(1 for b in self._key_of
                         if self._ref[b] == 0)
            return {"total": self.n_blocks - 1, "used": used,
                    "cached": cached, "free": len(self._free)}


class _Slot:
    """Host-side state of one active decode slot."""

    __slots__ = ("req", "resp", "pos", "last", "generated", "remaining",
                 "t_last", "blocks")

    def __init__(self, req: ServeRequest, resp: ServeResponse, pos: int,
                 first_token: int, t_now: float,
                 blocks: Optional[List[int]] = None):
        self.req = req
        self.resp = resp
        self.pos = pos                    # position of the token to feed
        self.last = first_token           # token to feed next step
        self.generated = [first_token]
        self.remaining = req.max_new_tokens - 1
        self.t_last = t_now               # per-token latency anchor
        self.blocks = blocks or []        # physical KV blocks (paged)


class _PrefillCursor:
    """A long prompt streaming in through chunked prefill: blocks-so-far
    plus the next prompt position to feed.  The cursor owns its blocks
    (released exactly once on completion-failure/cancel/death, like a
    slot's), but its slot's row in the engine's table array stays ZEROED
    until completion — decode feeds inactive rows token 0 at position 0,
    and that write must keep routing to the reserved garbage block, not
    into a half-prefilled prompt's block 0."""

    __slots__ = ("req", "resp", "blocks", "shared", "keys", "pos",
                 "chunks", "t_start")

    def __init__(self, req: ServeRequest, resp: ServeResponse,
                 shared: List[int], keys: List[str], pos: int,
                 t_start: float):
        self.req = req
        self.resp = resp
        self.blocks = list(shared)   # grows as chunks land
        self.shared = list(shared)   # prefix-cache hits (refcounted)
        self.keys = keys
        self.pos = pos               # next prompt position to feed
        self.chunks = 0
        self.t_start = t_start       # prefill-duration anchor


class ServeEngine:
    """Continuous-batching greedy inference over one model replica.

    ``max_slots``: fixed decode batch.  ``queue_depth``: admission cap
    beyond the slots (backpressure).  ``max_total_len``: per-slot token
    budget; prompt + max_new_tokens of every request must fit (defaults
    to the model's max_seq_len).

    Paged knobs (``paged=True``, the default): ``block_len`` tokens per
    KV block; ``n_blocks`` physical blocks in the pool (+1 reserved
    garbage block; default gives every slot its full ``max_total_len``
    worth — shrink it to trade worst-case capacity for HBM, admission
    rejects/backpressures typed against the real pool);
    ``prefix_cache`` enables shared-prefix reuse;
    ``pool_overcommit`` scales the admission-time worst-case block
    budget (> 1.0 banks on prefix sharing).  ``draft_model`` /
    ``draft_params`` / ``spec_k`` arm the speculative lane.

    ``chunked_prefill`` (default on, paged only): prompts spanning more
    than RLA_TPU_SERVE_CHUNK_BLOCKS KV blocks stream through
    ``decode_chunk_paged`` in pool-bounded chunks INTERLEAVED with live
    decode steps — big chunks while decode is idle, small
    (RLA_TPU_SERVE_CHUNK_MIN_BLOCKS) chunks between decode waves — so
    one long prompt monopolizes neither the decode cadence nor its
    disaggregated prefill lane.  Admission then judges prompts against
    the model's ``max_seq_len`` rather than the ``max_total_len``
    bucket (the per-slot block table spans the model), a paused prefill
    holds only its blocks-so-far, and the chunk buckets are the
    existing prefill buckets so steady state compiles nothing new.
    Token-identical to whole-prompt prefill (greedy argmax over the
    same positions).  The speculative lane keeps blocking prefill (and
    a draft model pins the table span to ``max_total_len`` — its dense
    cache must cover every padded bucket).

    ``paged=False``: the PR 2 dense allocator; ``prompt_block`` then
    bounds prefill compile count (paged mode buckets by ``block_len``).
    """

    def __init__(self, model: Any, params: Any, *, max_slots: int = 4,
                 queue_depth: int = 64,
                 max_total_len: Optional[int] = None,
                 max_new_tokens_cap: Optional[int] = None,
                 prompt_block: int = 8,
                 metrics: Optional[ServeMetrics] = None,
                 perf_timeline: Any = None,
                 idle_poll_s: float = 0.05,
                 paged: bool = True,
                 block_len: int = 16,
                 n_blocks: Optional[int] = None,
                 prefix_cache: bool = True,
                 pool_overcommit: float = 1.0,
                 draft_model: Any = None,
                 draft_params: Any = None,
                 spec_k: int = 4,
                 slo: Any = "env",
                 handoff_wave_bytes: Optional[int] = None,
                 chunked_prefill: bool = True):
        import jax

        if model.cfg.sliding_window is not None:
            raise ValueError(
                "the serve engine needs linear cache slots; "
                "sliding_window models are unsupported (their rolling "
                "ring cache cannot slot-join)")
        if max_slots < 1:
            raise ValueError("max_slots must be >= 1")
        W = (max_total_len if max_total_len is not None
             else model.cfg.max_seq_len)
        if W > model.cfg.max_seq_len:
            raise ValueError(
                f"max_total_len {W} exceeds the model's max_seq_len "
                f"{model.cfg.max_seq_len}")
        self.model = model
        self.params = jax.tree.map(jax.numpy.asarray, params)
        self.max_slots = max_slots
        self.max_total_len = W
        self.paged = bool(paged)
        self.metrics = metrics or ServeMetrics()
        # optional telemetry.perf.StepTimeline: the engine loop feeds
        # its prefill/decode phase times into the same per-step ledger
        # the trainer uses (phases "prefill"/"decode"; aggregate-only —
        # the loop has no optimizer-step bracket)
        self.perf_timeline = perf_timeline
        self._idle_poll_s = idle_poll_s
        self._jax = jax
        # donate the cache/pool operand where donation is real (TPU/GPU):
        # the hot loop reassigns the cache every call, so without
        # donation each step/join copies the whole [L,...] pair and
        # doubles peak cache memory.  CPU ignores donation with a
        # warning per call site -- skip it there to keep test logs quiet.
        donate = jax.default_backend() != "cpu"
        self._donate = donate

        # -- SLO engine (serve/slo.py) --------------------------------- #
        # slo: an SloPolicy, None (disabled), or "env" (default — built
        # from the SLO knobs, see analysis/knobs.py; no knob set = no tracker, zero
        # per-request overhead).  With a policy attached: admission
        # stamps each request's absolute deadline, expired requests are
        # shed typed BEFORE prefill, TTFT/token-cadence observations
        # feed the rolling burn-rate window, and the slo_burn_rate /
        # slo_violations_total signals ride every metrics snapshot.
        from .slo import SloPolicy, SloTracker
        if slo == "env":
            slo = SloPolicy.from_env()
        if slo is not None and not isinstance(slo, SloPolicy):
            raise ValueError(
                "slo must be an SloPolicy, None, or 'env'; got "
                f"{type(slo).__name__}")
        self.slo_policy = slo if slo is not None and slo.enabled else None
        self._slo = (SloTracker(self.slo_policy, self.metrics)
                     if self.slo_policy is not None else None)
        if self._slo is not None:
            self.metrics.bind_slo(self._slo.gauges)

        # -- speculative lane ------------------------------------------ #
        self.draft_model = draft_model
        self.draft_params = None
        self.spec_k = int(spec_k)
        if draft_model is not None:
            if not self.paged:
                raise ValueError("the speculative lane needs the paged "
                                 "engine (its chunk scorer drafts into "
                                 "scratch blocks); pass paged=True")
            if spec_k < 1:
                raise ValueError("spec_k must be >= 1")
            if draft_model.cfg.sliding_window is not None:
                raise ValueError("speculative decoding needs a linear "
                                 "draft cache (sliding_window "
                                 "unsupported)")
            if draft_model.cfg.vocab_size != model.cfg.vocab_size:
                raise ValueError(
                    f"draft vocab {draft_model.cfg.vocab_size} != target "
                    f"vocab {model.cfg.vocab_size}")
            self.draft_params = jax.tree.map(jax.numpy.asarray,
                                             draft_params)
            from ..models.speculative import build_draft_proposer
            self._d_propose = build_draft_proposer(
                draft_model, self.draft_params, self.spec_k)

        if self.paged:
            self.block_len = int(block_len)
            if self.block_len < 1:
                raise ValueError("block_len must be >= 1")
            headroom = self.spec_k if draft_model is not None else 0
            self.max_blocks_per_slot = -(-(W + headroom) // self.block_len)
            # chunked long-prompt prefill: the per-slot block-table SPAN
            # widens to the model's max_seq_len so admission stops
            # refusing prompts longer than the max_total_len bucket —
            # the pool budget (not the table width) bounds what can
            # actually place.  Pool sizing, the one-full-request floor
            # and the dense-equivalent gauge all stay keyed to
            # max_total_len: capacity parity is about the DECODE working
            # set, and a streaming prefill holds only its blocks-so-far.
            self.chunked_prefill = bool(chunked_prefill)
            from ..analysis import knobs as _knobs
            self._chunk_blocks = max(1, _knobs.get_int(
                "RLA_TPU_SERVE_CHUNK_BLOCKS", 8))
            self._chunk_min_blocks = max(1, min(
                _knobs.get_int("RLA_TPU_SERVE_CHUNK_MIN_BLOCKS", 1),
                self._chunk_blocks))
            self.table_blocks = self.max_blocks_per_slot
            if self.chunked_prefill and draft_model is None:
                self.table_blocks = max(
                    self.table_blocks,
                    -(-model.cfg.max_seq_len // self.block_len))
            if n_blocks is None:
                # capacity parity with the dense allocator by default:
                # the HBM win comes from sizing the pool BELOW this
                n_blocks = max_slots * self.max_blocks_per_slot + 1
            if n_blocks < self.max_blocks_per_slot + 1:
                raise ValueError(
                    f"n_blocks {n_blocks} cannot hold even one full "
                    f"request ({self.max_blocks_per_slot} blocks + the "
                    "reserved garbage block)")
            self.n_blocks = int(n_blocks)
            if draft_model is not None:
                # the draft's FIXED dense cache must cover every padded
                # prompt bucket + drafting headroom (one program per
                # bucket; block rounding may admit prompts past W)
                self._draft_cache_len = (self.max_blocks_per_slot
                                         * self.block_len + self.spec_k)
                if draft_model.cfg.max_seq_len < self._draft_cache_len:
                    raise ValueError(
                        f"draft max_seq_len "
                        f"{draft_model.cfg.max_seq_len} < the engine's "
                        f"block-table span + spec_k "
                        f"({self._draft_cache_len})")
            self.prefix_cache = bool(prefix_cache)
            self.allocator = BlockAllocator(self.n_blocks, self.block_len)
            self.prompt_block = self.block_len  # buckets = block multiples
            self.batcher = AdmissionController(
                queue_depth=queue_depth,
                max_new_tokens_cap=max_new_tokens_cap,
                block_len=self.block_len,
                pool_blocks=self.n_blocks - 1,
                max_blocks_per_slot=self.table_blocks,
                spec_headroom=headroom,
                pool_overcommit=pool_overcommit,
                hard_total_cap=model.cfg.max_seq_len,
                slo_policy=self.slo_policy)
            self._tables = np.zeros(
                (max_slots, self.table_blocks), np.int32)
            self.metrics.bind_pool(self._pool_gauges)
            self.metrics.bind_chunks(lambda: {
                "active_long_prefills": sum(
                    1 for c in self._cursors if c is not None)})

            def step_tokens(p, pool, tables, t, pos):
                # argmax INSIDE the compiled step (compile-guard pins the
                # program count); D2H per step is [B] tokens + [B] health
                # bits (the numeric guard: per-row all-finite logits,
                # riding the feed-gate sync the loop pays anyway)
                logits, pool = model.decode_step_rows_paged(
                    p, pool, tables, t, pos)
                ok = jax.numpy.all(
                    jax.numpy.isfinite(logits),
                    axis=tuple(range(1, logits.ndim)))
                return jax.numpy.argmax(logits, -1).astype(
                    jax.numpy.int32), ok, pool

            self._step = jax.jit(step_tokens,
                                 donate_argnums=(1,) if donate else ())
        else:
            self.chunked_prefill = False  # dense rows cannot chunk-join
            self.prompt_block = max(1, prompt_block)
            self.batcher = AdmissionController(
                queue_depth=queue_depth, max_total_len=W,
                max_new_tokens_cap=max_new_tokens_cap,
                slo_policy=self.slo_policy)
            self._join = jax.jit(type(model).cache_join,
                                 donate_argnums=(0,) if donate else ())

            def step_tokens(p, c, t, pos):
                logits, cache = model.decode_step_rows(p, c, t, pos)
                ok = jax.numpy.all(
                    jax.numpy.isfinite(logits),
                    axis=tuple(range(1, logits.ndim)))
                return jax.numpy.argmax(logits, -1).astype(
                    jax.numpy.int32), ok, cache

            self._step = jax.jit(step_tokens,
                                 donate_argnums=(1,) if donate else ())
        self.metrics.bind_queue(lambda: self.batcher.depth)
        # -- KV handoff (disaggregated prefill/decode lanes) ------------ #
        # An export request's prefilled blocks stay pinned here (with
        # their object-store wave refs) until the decode side confirms
        # the copy landed and the driver calls release_handoff — the
        # exactly-once seam: a decode-replica crash mid-import can
        # always fall back to the still-resident source blocks.
        if handoff_wave_bytes is None:
            from ..analysis import knobs
            handoff_wave_bytes = knobs.get_int(
                "RLA_TPU_SERVE_HANDOFF_WAVE_BYTES", 4 << 20)
        self.handoff_wave_bytes = max(1, int(handoff_wave_bytes))
        self._handoff_lock = threading.Lock()
        self._handoffs: Dict[int, Tuple[ServeRequest, List[int],
                                        List[Any]]] = {}
        self._handoff_ids = itertools.count()
        self._prefills: Dict[Any, Any] = {}
        self._cache = None          # dense cache OR paged pool
        self._pool_bytes = 0        # measured placed pool bytes (paged)
        self._slots: List[Optional[_Slot]] = [None] * max_slots
        self._cursors: List[Optional[_PrefillCursor]] = [None] * max_slots
        self._spec_active = 0
        self._stop = threading.Event()
        self._cancel_active = False
        self._thread: Optional[threading.Thread] = None
        self._live_label: Optional[str] = None
        # mesh mutation LAST, after every validation that can raise: a
        # failed construction must not hand the caller back a model
        # silently stripped of its training mesh.  Decode runs
        # replicated, exactly like generate() — a training-time mesh
        # must not carve up step-sized activations (jit tracing is lazy,
        # so nulling here still precedes every trace).
        self._mesh_saved, model.mesh = model.mesh, None
        if draft_model is not None:
            self._draft_mesh_saved = draft_model.mesh
            draft_model.mesh = None

    # ------------------------------------------------------------------ #
    # Lifecycle                                                          #
    # ------------------------------------------------------------------ #
    def start(self) -> "ServeEngine":
        if self._thread is not None:
            raise RuntimeError("engine already started")
        if self.paged:
            self._cache = self.model.paged_cache_alloc(self.n_blocks,
                                                       self.block_len)
        else:
            self._cache = self.model.decode_cache_alloc(
                self.max_slots, self.max_total_len)
        # placed-bytes truth for the waste-ratio gauges (and the probe's
        # dense baseline): the real arrays' nbytes, not a formula
        self._pool_bytes = int(self._cache["k"].nbytes
                               + self._cache["v"].nbytes)
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="rla-tpu-serve-engine")
        self._thread.start()
        # live telemetry plane (telemetry/live.py): when
        # RLA_TPU_METRICS_PORT is configured, this engine's live
        # ServeMetrics (+ SLO burn rate) become scrapeable on the
        # process's /metrics and /statusz while it serves
        from ..telemetry import live as live_lib
        srv = live_lib.maybe_start_from_env()
        if srv is not None:
            self._live_label = f"engine{next(_ENGINE_SEQ)}"
            srv.sources.add_serve(self._live_label, self.metrics,
                                  slo=self._slo)
        return self

    def stop(self, cancel_active: bool = False,
             timeout: float = 60.0) -> None:
        """Stop admitting; by default FINISH the in-flight slots (their
        budgets bound the wait), cancel everything still queued with
        ``ServeCancelled``, then join the loop.  ``cancel_active=True``
        cancels in-flight requests too (fast teardown)."""
        self._cancel_active = cancel_active
        self._stop.set()
        self.batcher.kick()
        if self._thread is not None:
            self._thread.join(timeout=timeout)
            self._thread = None
        n = self.batcher.shutdown()
        if n:
            self.metrics.inc("cancelled", n)
        # any export holds never released by the driver (tier teardown
        # mid-handoff): free their blocks and object-store payloads now
        with self._handoff_lock:
            held = list(self._handoffs.keys())
        for hid in held:
            self.release_handoff(hid)
        if self._live_label is not None:
            from ..telemetry import live as live_lib
            srv = live_lib.get_server()
            if srv is not None:
                srv.sources.remove_serve(self._live_label)
            self._live_label = None
        self.model.mesh = self._mesh_saved
        if self.draft_model is not None:
            self.draft_model.mesh = self._draft_mesh_saved

    def __enter__(self) -> "ServeEngine":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # ------------------------------------------------------------------ #
    # Client surface                                                     #
    # ------------------------------------------------------------------ #
    def submit(self, prompt: Any, max_new_tokens: int,
               speculative: bool = False) -> ServeResponse:
        """Admit a request (typed QueueFull/PoolExhausted/RequestRejected
        backpressure); the response resolves to prompt + greedily
        generated tokens, token-identical to ``generate()``.
        ``speculative=True`` hints the engine to route this single-stream
        request through the speculative lane when it is idle (needs a
        draft model; a busy engine uses a normal slot)."""
        from .batcher import PoolExhausted, QueueFull, RequestRejected
        if speculative and self.draft_model is None:
            self.metrics.inc("rejected")  # typed rejections all count
            raise RequestRejected(
                "speculative routing needs a draft model: construct the "
                "engine with draft_model=/draft_params=")
        try:
            resp = self.batcher.submit(prompt, max_new_tokens,
                                       speculative=speculative)
        except PoolExhausted:
            self.metrics.inc("rejected")
            self.metrics.inc("pool_exhausted")
            raise
        except (QueueFull, RequestRejected):
            # admission rejections only: a ServeCancelled from a stopping
            # engine must not read as overload in the counters
            self.metrics.inc("rejected")
            raise
        self.metrics.inc("submitted")
        # per-request trace (minted at admission): the whole
        # admit -> prefill -> respond lifecycle shares it
        telemetry.emit("serve_admit", trace=resp.request.trace_id,
                       request=resp.request.request_id,
                       prompt_len=int(resp.request.prompt.size))
        return resp

    def submit_handoff(self, prompt: Any, max_new_tokens: int, *,
                       t_submit: Optional[float] = None,
                       deadline: Optional[float] = None,
                       trace_id: Optional[str] = None) -> ServeResponse:
        """Admit a PREFILL-ONLY request (the disaggregated prefill
        lane, serve/replicas.py): the engine prefills the prompt into
        its pool and the response resolves to a KV handoff DESCRIPTOR —
        a picklable dict a decode-lane engine turns back into a live
        slot via ``submit_import`` — instead of tokens.  The prefilled
        blocks stay pinned on this engine until ``release_handoff``.
        ``t_submit``/``deadline``/``trace_id`` carry the client's
        ORIGINAL stamps so the hop never resets the SLO clock."""
        from .batcher import PoolExhausted, QueueFull, RequestRejected
        if not self.paged:
            self.metrics.inc("rejected")
            raise RequestRejected(
                "KV handoff needs the paged engine (the descriptor is a "
                "block-table span); pass paged=True")
        try:
            resp = self.batcher.submit(prompt, max_new_tokens,
                                       export_handoff=True,
                                       t_submit=t_submit,
                                       deadline=deadline,
                                       trace_id=trace_id)
        except PoolExhausted:
            self.metrics.inc("rejected")
            self.metrics.inc("pool_exhausted")
            raise
        except (QueueFull, RequestRejected):
            self.metrics.inc("rejected")
            raise
        self.metrics.inc("submitted")
        telemetry.emit("serve_admit", trace=resp.request.trace_id,
                       request=resp.request.request_id,
                       prompt_len=int(resp.request.prompt.size),
                       export_handoff=True)
        return resp

    def submit_import(self, descriptor: Dict[str, Any]) -> ServeResponse:
        """Admit a request whose prefill ALREADY HAPPENED on a prefill-
        lane engine: ``descriptor`` is a ``submit_handoff`` result.  The
        engine allocates fresh physical blocks, replays the descriptor's
        object-store waves into them (the block-id remap), and the
        request starts life mid-decode — the response resolves to
        prompt + generated tokens exactly like ``submit``.  Bypasses the
        queue-depth cap (the request was admitted once at the tier) but
        not the pool check: the blocks are real memory here."""
        from .batcher import PoolExhausted, QueueFull, RequestRejected
        if not self.paged:
            self.metrics.inc("rejected")
            raise RequestRejected(
                "KV handoff import needs the paged engine; pass "
                "paged=True")
        if int(descriptor.get("block_len", -1)) != self.block_len:
            self.metrics.inc("rejected")
            raise RequestRejected(
                f"handoff block_len {descriptor.get('block_len')} != "
                f"this engine's block_len {self.block_len}: a block-id "
                "remap cannot re-tile blocks")
        try:
            resp = self.batcher.submit(
                descriptor["prompt"], int(descriptor["max_new_tokens"]),
                import_handoff=descriptor,
                t_submit=descriptor.get("t_submit"),
                deadline=descriptor.get("deadline"),
                trace_id=descriptor.get("trace_id"))
        except PoolExhausted:
            self.metrics.inc("rejected")
            self.metrics.inc("pool_exhausted")
            raise
        except (QueueFull, RequestRejected):
            self.metrics.inc("rejected")
            raise
        self.metrics.inc("submitted")
        telemetry.emit("serve_admit", trace=resp.request.trace_id,
                       request=resp.request.request_id,
                       prompt_len=int(resp.request.prompt.size),
                       import_handoff=True)
        return resp

    def release_handoff(self, handoff_id: int) -> bool:
        """Drop an export's hold: release its pinned blocks (registered
        full prompt blocks stay LRU-cached in the prefix index — the
        source keeps serving prefix hits until eviction reclaims them),
        return its admission reservation, and delete the object-store
        wave payloads.  Idempotent; safe from any thread (the allocator,
        admission controller and object store are each internally
        locked, and release never touches the device pool)."""
        with self._handoff_lock:
            held = self._handoffs.pop(handoff_id, None)
        if held is None:
            return False
        req, blocks, refs = held
        for b in blocks:
            self.allocator.release(b)
        self.batcher.release_blocks(req)
        from ..runtime import object_store
        store = object_store.global_store()
        for ref in refs:
            try:
                store.delete(ref)
            except Exception:
                pass  # best-effort: a dead owner already unlinked
        telemetry.emit("serve_kv_release", request=req.request_id,
                       handoff=handoff_id, blocks=len(blocks))
        return True

    def stats(self) -> Dict[str, Any]:
        out = self.metrics.snapshot()
        if self._slo is not None:
            # the ttft-vs-cadence burn split rides every stats snapshot
            # so the tier's lane autoscaler reads it for free
            # (serve/controller.py _lane_for_growth_locked)
            out["slo_families"] = self._slo.family_rates()
        return out

    # ------------------------------------------------------------------ #
    # Pool gauges (paged)                                                #
    # ------------------------------------------------------------------ #
    def _pool_gauges(self) -> Dict[str, Any]:
        """Live block-pool occupancy + HBM truth for the metrics
        snapshot.  ``dense_equivalent_bytes`` is what the PR 2 dense
        allocator would pin for the SAME live sequences (one full
        max-length row each); ``cache_waste_ratio`` is the fraction of
        that the paged layout avoids."""
        st = self.allocator.stats()
        per_block = (self._pool_bytes / self.n_blocks
                     if self._pool_bytes else 0.0)
        row_bytes = per_block * self.max_blocks_per_slot
        active = sum(1 for s in self._slots if s is not None) \
            + sum(1 for c in self._cursors if c is not None) \
            + self._spec_active
        used_bytes = st["used"] * per_block
        dense_eq = active * row_bytes
        return {
            "block_pool_total": st["total"],
            "block_pool_used": st["used"],
            "block_pool_cached": st["cached"],
            "block_pool_free": st["free"],
            "block_pool_occupancy": (st["used"] / st["total"]
                                     if st["total"] else 0.0),
            "block_len": self.block_len,
            "hbm_cache_bytes": self._pool_bytes,
            "hbm_used_bytes": int(used_bytes),
            "dense_equivalent_bytes": int(dense_eq),
            "cache_waste_ratio": (1.0 - used_bytes / dense_eq
                                  if dense_eq > 0 else 0.0),
        }

    # ------------------------------------------------------------------ #
    # Driver loop                                                        #
    # ------------------------------------------------------------------ #
    def _run(self) -> None:
        try:
            while True:
                if not self._stop.is_set():
                    self._admit()
                active = [i for i, s in enumerate(self._slots)
                          if s is not None]
                prefilling = self.paged and any(
                    c is not None for c in self._cursors)
                if self._stop.is_set() and self._cancel_active \
                        and (active or prefilling):
                    self._cancel_slots()
                    continue
                if prefilling:
                    # cadence-aware chunk budget: big chunks while
                    # decode is idle, small chunks between decode waves
                    self._advance_prefills(decode_active=bool(active))
                    # a cursor that completed THIS iteration just armed
                    # its slot's block table: recompute the wave so the
                    # row decodes now — a stale wave would feed token 0
                    # at position 0 THROUGH the armed table and stomp
                    # the prompt's first block of KV
                    active = [i for i, s in enumerate(self._slots)
                              if s is not None]
                if active:
                    self._decode_step(active)
                elif prefilling:
                    continue  # cursors advancing; no decode, no sleep
                elif self._stop.is_set():
                    return
                else:
                    self.batcher.wait_for_work(self._idle_poll_s)
        except BaseException as e:  # engine death must fail loudly, typed
            log.error("serve engine loop died: %s", e)
            for i, s in enumerate(self._slots):
                if s is not None:
                    if s.resp._fail(e):
                        self.metrics.inc("failed")
                    self._release_request(s.req, s.blocks)
                self._slots[i] = None
            for i, cur in enumerate(self._cursors):
                # a mid-stream prefill's blocks-so-far release exactly
                # once, like a slot's (the tier requeues the request)
                if cur is not None:
                    if cur.resp._fail(e):
                        self.metrics.inc("failed")
                    self._release_request(cur.req, cur.blocks)
                self._cursors[i] = None
            n = self.batcher.shutdown()
            if n:  # keep completed+failed+cancelled == submitted honest
                self.metrics.inc("cancelled", n)
            raise

    def _bucket(self, s0: int) -> int:
        b = self.prompt_block
        return min(-(-s0 // b) * b, self.max_total_len)

    # -- compiled-program memos ---------------------------------------- #
    def _prefill_fn(self, padded_len: int):
        """Dense bucketed pad-prefill (paged=False)."""
        key = ("dense", padded_len)
        if key not in self._prefills:
            jax, model = self._jax, self.model
            jnp = jax.numpy

            def fn(params, tokens, last_index):
                h_last, cache = model._prefill(params, tokens, padded_len,
                                               last_index=last_index)
                logits = model._unembed_matmul(h_last, params,
                                               model.compute_dtype)
                return jnp.argmax(logits, -1).astype(jnp.int32), cache

            # memoized per prompt bucket: each padded length compiles
            # exactly once for the engine's lifetime, bounded by
            # max_total_len / prompt_block buckets
            self._prefills[key] = jax.jit(fn)  # graftlint: ok(retrace) — memoized per bucket
        return self._prefills[key]

    def _chunk_prefill_fn(self, padded_len: int):
        """Paged chunk prefill per suffix-length bucket: run the padded
        un-shared suffix at its true positions through the block table,
        return the first greedy token.  The pool operand is donated; the
        block table and start position are traced, so prefix hits of any
        depth reuse one program per bucket."""
        key = ("chunk", padded_len)
        if key not in self._prefills:
            jax, model = self._jax, self.model
            jnp = jax.numpy

            def fn(params, pool, table, tokens, pos0, last_rel):
                logits, pool = model.decode_chunk_paged(
                    params, pool, table, tokens, pos0,
                    last_index=last_rel)
                return jnp.argmax(logits, -1).astype(jnp.int32), pool

            self._prefills[key] = jax.jit(  # graftlint: ok(retrace) — memoized per bucket
                fn, donate_argnums=(1,) if self._donate else ())
        return self._prefills[key]

    def _spec_score_fn(self):
        """Speculative chunk scorer (one program: spec_k is static):
        feed [last, d_1..d_{k-1}] at pos..pos+k-1, return the target's
        greedy token per position."""
        key = ("spec", self.spec_k)
        if key not in self._prefills:
            jax, model = self._jax, self.model
            jnp = jax.numpy

            def fn(params, pool, table, chunk, pos0):
                logits, pool = model.decode_chunk_paged(
                    params, pool, table, chunk, pos0)
                return jnp.argmax(logits[0], -1).astype(jnp.int32), pool

            self._prefills[key] = jax.jit(  # graftlint: ok(retrace) — memoized once (spec_k static)
                fn, donate_argnums=(1,) if self._donate else ())
        return self._prefills[key]

    def _draft_prefill_fn(self, padded_len: int):
        """Draft-model bucketed pad-prefill into a FIXED-length dense
        cache (max_total_len + spec_k), so every speculative request
        shares one program per prompt bucket."""
        key = ("draft", padded_len)
        if key not in self._prefills:
            jax, draft = self._jax, self.draft_model
            cache_len = self._draft_cache_len

            def fn(dparams, tokens, last_index):
                _, cache = draft._prefill(dparams, tokens, cache_len,
                                          last_index=last_index)
                return cache

            self._prefills[key] = jax.jit(fn)  # graftlint: ok(retrace) — memoized per bucket
        return self._prefills[key]

    def _kv_gather_fn(self, cap: int):
        """KV-handoff export gather, one program per wave width: read a
        fixed-width wave of block ids out of the pool.  The pool is NOT
        donated (the source keeps serving from it); ids short of ``cap``
        are padded with the garbage block 0 and sliced off host-side, so
        every wave of a handoff — and every later handoff with the same
        wave bound — reuses this one program (zero steady-state
        recompiles, compile-guard pinned in the tests)."""
        key = ("kv_gather", cap)
        if key not in self._prefills:
            jax, model = self._jax, self.model

            def fn(pool, ids):
                return model.paged_blocks_gather(pool, ids)

            self._prefills[key] = jax.jit(fn)  # graftlint: ok(retrace) — memoized per wave width
        return self._prefills[key]

    def _kv_scatter_fn(self, cap: int):
        """KV-handoff import scatter (the block-id remap made real):
        write a fixed-width wave of shipped block payloads into freshly
        allocated local ids.  Pad entries target the garbage block 0.
        Pool donated where donation is real — the hot-loop reassignment
        argument from the decode step applies unchanged."""
        key = ("kv_scatter", cap)
        if key not in self._prefills:
            jax, model = self._jax, self.model

            def fn(pool, ids, k, v):
                return model.paged_blocks_scatter(pool, ids, k, v)

            self._prefills[key] = jax.jit(  # graftlint: ok(retrace) — memoized per wave width
                fn, donate_argnums=(0,) if self._donate else ())
        return self._prefills[key]

    # -- block bookkeeping ---------------------------------------------- #
    def _prefix_keys(self, prompt: np.ndarray) -> List[str]:
        """Chain hashes of the prompt's FULL blocks: key j commits to
        tokens [0, (j+1)*block_len) — a hit therefore guarantees the
        whole prefix matches, which is what makes the cached k/v exact
        for the new request."""
        return chain_prefix_keys(prompt, self.block_len)

    def _release_request(self, req: ServeRequest,
                         blocks: List[int]) -> None:
        """Return a request's blocks (refcounted) and its admission-time
        reservation; exactly once per placed request."""
        if self.paged:
            for b in blocks:
                self.allocator.release(b)
        self.batcher.release_blocks(req)

    def _observe_pool(self) -> None:
        if self.paged:
            st = self.allocator.stats()
            active = sum(1 for s in self._slots if s is not None) \
                + sum(1 for c in self._cursors if c is not None) \
                + self._spec_active
            self.metrics.observe_pool(st["used"], active)

    def _place_blocks(self, req: ServeRequest
                      ) -> Optional[Tuple[List[int], List[int],
                                          List[str]]]:
        """Prefix-lookup + allocate a request's remaining blocks.
        Returns (blocks, shared, keys) or None when the pool cannot
        place it right now (caller pushes the request back)."""
        s0 = int(req.prompt.size)
        needed = req.blocks_reserved or blocks_for_request(
            s0, req.max_new_tokens, self.block_len,
            self.spec_k if req.speculative else 0)
        shared: List[int] = []
        keys: List[str] = []
        if self.prefix_cache:
            keys = self._prefix_keys(req.prompt)
            if keys:
                self.metrics.inc("prefix_lookups")
            # keep >= 1 suffix token: the last prompt position's hidden
            # state must actually be computed to produce token 0
            shared = self.allocator.lookup_run(keys,
                                               (s0 - 1) // self.block_len)
            if shared:
                self.metrics.inc("prefix_hits")
                self.metrics.inc("prefix_hit_blocks", len(shared))
        fresh = self.allocator.alloc(needed - len(shared))
        if fresh is None:
            for b in shared:
                self.allocator.release(b)
            return None
        return shared + fresh, shared, keys

    def _register_prompt_blocks(self, req: ServeRequest,
                                blocks: List[int], shared: List[int],
                                keys: List[str]) -> None:
        """Publish this prompt's newly computed FULL blocks for future
        prefix hits (partial/pad blocks never register)."""
        if not self.prefix_cache:
            return
        for j in range(len(shared), int(req.prompt.size)
                       // self.block_len):
            self.allocator.register(keys[j], blocks[j])

    # -- admission ------------------------------------------------------ #
    def _pop_admittable(self) -> Optional[Tuple[ServeRequest,
                                                ServeResponse]]:
        """Next queued request still worth serving.  With an SLO policy
        attached, a request whose deadline passed while it queued is
        shed typed (``DeadlineExceeded``) RIGHT HERE — before any
        prefill compute is spent on a response the client already
        abandoned — its admission block reservation returns to the
        budget, and the pop retries the next request."""
        while True:
            item = self.batcher.pop()
            if item is None:
                return None
            req, resp = item
            if self._slo is not None and req.deadline is not None \
                    and time.monotonic() > req.deadline:
                exc = self._slo.shed(req,
                                     time.monotonic() - req.t_submit)
                if resp._fail(exc):
                    self.metrics.inc("failed")
                self.batcher.release_blocks(req)
                continue
            # NOTE: the deadline-MET observation is recorded at prefill
            # (the one-per-request point), not here — a pool-full head
            # request is re-popped via push_front every loop iteration,
            # and per-pop observations would flood the window with
            # non-violations exactly when overload matters
            return item

    def _admit(self) -> int:
        """Fill free slots from the queue: prefill each request into its
        cache (dense row-join or paged blocks), record TTFT (the first
        token exists the moment prefill returns)."""
        jnp = self._jax.numpy
        admitted = 0
        for i in range(self.max_slots):
            if self._slots[i] is not None or self._cursors[i] is not None:
                continue
            item = self._pop_admittable()
            if item is None:
                break
            req, resp = item
            if self.paged and self.chunked_prefill \
                    and req.import_handoff is None and not req.speculative \
                    and int(req.prompt.size) \
                    > self._chunk_blocks * self.block_len:
                # long prompt: stream it through a prefill cursor the
                # loop advances between decode waves (no upfront block
                # placement — a paused prefill holds only its
                # blocks-so-far, allocated chunk by chunk)
                self._start_cursor(i, req, resp)
                admitted += 1
                continue
            if self.paged and req.import_handoff is not None:
                # decode-lane entry: no prefill, just a block remap
                if not self._admit_import(i, req, resp):
                    break  # pool cannot place it now; request pushed back
                admitted += 1
                continue
            if self.paged and req.speculative \
                    and self.draft_model is not None \
                    and all(s is None for s in self._slots):
                # idle engine: the single-stream latency lane
                if not self._run_speculative(req, resp):
                    break  # pool cannot place it now; request pushed back
                admitted += 1
                continue
            if self.paged:
                placed = self._place_blocks(req)
                if placed is None:
                    # pool exhausted right now: FIFO head waits (no
                    # starvation; retires free blocks every step)
                    self.batcher.push_front(item)
                    break
                blocks, shared, keys = placed
            else:
                blocks, shared, keys = None, (), ()
            try:
                self._admit_one(i, req, resp, blocks, shared, keys)
            except BaseException as e:
                # the popped request is in neither the queue nor a slot:
                # its future must fail HERE or the client hangs until
                # timeout while the loop dies loudly
                if resp._fail(e):
                    self.metrics.inc("failed")
                if self.paged:
                    self._release_request(req, blocks)
                raise
            admitted += 1
        return admitted

    def _paged_prefill(self, req: ServeRequest, resp: ServeResponse,
                       blocks: List[int], shared, keys,
                       slot: int, speculative: bool = False
                       ) -> Tuple[int, np.ndarray, float]:
        """The one paged prefill path (normal slots AND the speculative
        lane ride it, so they cannot drift): build the request's table,
        chunk-prefill the un-shared suffix into its blocks, register the
        new full prompt blocks, and record TTFT.  Returns (first token,
        table row, completion timestamp)."""
        jnp = self._jax.numpy
        t_a = time.monotonic()
        # queue wait = admission -> this slot-join moment; ttft below
        # is queue_wait + prefill by construction
        self.metrics.observe_queue_wait(t_a - req.t_submit)
        self.metrics.observe_long_prefill(int(req.prompt.size))
        start = len(shared) * self.block_len
        sfx = req.prompt[start:]
        P = -(-int(sfx.size) // self.block_len) * self.block_len
        padded = np.zeros((1, P), np.int32)
        padded[0, :sfx.size] = sfx
        table = np.zeros((self.table_blocks,), np.int32)
        table[:len(blocks)] = blocks
        tok0, self._cache = self._chunk_prefill_fn(P)(
            self.params, self._cache, jnp.asarray(table),
            jnp.asarray(padded), jnp.int32(start),
            jnp.int32(int(sfx.size) - 1))
        self.metrics.inc("prefill_chunks")
        self._register_prompt_blocks(req, blocks, shared, keys)
        # graftlint: ok(host-sync) — TTFT gate: the first token must
        first = int(np.asarray(tok0)[0])  # be real before it is timed
        now = time.monotonic()
        resp.ttft_s = now - req.t_submit
        self.metrics.observe_ttft(resp.ttft_s)
        if self._slo is not None:
            self._slo.observe_ttft(resp.ttft_s, req)
            self._slo.observe_deadline_met(req)
        self.metrics.observe_prefill(now - t_a)
        if self.perf_timeline is not None:
            self.perf_timeline.observe("prefill", now - t_a)
        telemetry.emit("serve_prefill", trace=req.trace_id,
                       request=req.request_id, bucket=P, slot=slot,
                       shared_blocks=len(shared),
                       speculative=speculative,
                       ttft_ms=round(resp.ttft_s * 1e3, 3))
        return first, table, now

    def _admit_one(self, i: int, req: ServeRequest, resp: ServeResponse,
                   blocks: Optional[List[int]], shared, keys) -> None:
        """Prefill one placed request into slot ``i`` (or finish it at
        prefill for single-token budgets)."""
        jnp = self._jax.numpy
        s0 = int(req.prompt.size)
        if self.paged:
            first, table, now = self._paged_prefill(req, resp, blocks,
                                                    shared, keys, slot=i)
            if req.export_handoff:
                # prefill lane: the request's lifecycle on THIS engine
                # ends here — ship the blocks, keep them pinned until
                # the decode side confirms (release_handoff)
                self._export_handoff(req, resp, blocks, keys, first)
                self._observe_pool()
                return
        else:
            t_a = time.monotonic()
            self.metrics.observe_queue_wait(t_a - req.t_submit)
            P = self._bucket(s0)
            padded = np.zeros((1, P), np.int32)
            padded[0, :s0] = req.prompt
            tok0, row_cache = self._prefill_fn(P)(
                self.params, jnp.asarray(padded), jnp.int32(s0 - 1))
            if req.max_new_tokens > 1:
                # single-token requests finish at prefill; joining
                # their row would copy the whole cache for nothing
                self._cache = self._join(self._cache, row_cache,
                                         jnp.int32(i))
            # graftlint: ok(host-sync) — TTFT gate: the first token must
            first = int(np.asarray(tok0)[0])  # be real before timing
            now = time.monotonic()
            resp.ttft_s = now - req.t_submit
            self.metrics.observe_ttft(resp.ttft_s)
            if self._slo is not None:
                self._slo.observe_ttft(resp.ttft_s, req)
                self._slo.observe_deadline_met(req)
            self.metrics.observe_prefill(now - t_a)
            if self.perf_timeline is not None:
                self.perf_timeline.observe("prefill", now - t_a)
            telemetry.emit("serve_prefill", trace=req.trace_id,
                           request=req.request_id, bucket=P, slot=i,
                           shared_blocks=0,
                           ttft_ms=round(resp.ttft_s * 1e3, 3))
        if req.max_new_tokens == 1:
            self._finish(req, resp, [first])
            if self.paged:
                self._release_request(req, blocks)
        else:
            slot = _Slot(req, resp, pos=s0, first_token=first,
                         t_now=now,
                         blocks=blocks if self.paged else None)
            self._slots[i] = slot
            if self.paged:
                self._tables[i, :] = table
        self._observe_pool()

    # -- chunked long-prompt prefill ------------------------------------- #
    def _start_cursor(self, i: int, req: ServeRequest,
                      resp: ServeResponse) -> None:
        """Begin streaming a long prompt into slot ``i``: the prefix
        lookup happens NOW (a hit's blocks are exact KV, so the cursor
        starts past them), but blocks are otherwise allocated chunk by
        chunk — a paused prefill holds only its blocks-so-far.  The
        slot's table row stays zeroed until completion (see
        :class:`_PrefillCursor`)."""
        s0 = int(req.prompt.size)
        t_a = time.monotonic()
        # queue wait = admission -> the moment prefill starts; ttft at
        # completion is queue_wait + (streamed) prefill by construction
        self.metrics.observe_queue_wait(t_a - req.t_submit)
        self.metrics.observe_long_prefill(s0)
        shared: List[int] = []
        keys: List[str] = []
        if self.prefix_cache:
            keys = self._prefix_keys(req.prompt)
            if keys:
                self.metrics.inc("prefix_lookups")
            # keep >= 1 suffix token (the last position's hidden state
            # must be computed to produce token 0)
            shared = self.allocator.lookup_run(keys,
                                               (s0 - 1) // self.block_len)
            if shared:
                self.metrics.inc("prefix_hits")
                self.metrics.inc("prefix_hit_blocks", len(shared))
        self._cursors[i] = _PrefillCursor(
            req, resp, shared, keys, pos=len(shared) * self.block_len,
            t_start=t_a)
        telemetry.emit("serve_prefill_start", trace=req.trace_id,
                       request=req.request_id, slot=i, prompt=s0,
                       shared_blocks=len(shared), streamed=True)

    def _advance_prefills(self, decode_active: bool) -> None:
        """Advance every streaming prefill by ONE chunk this loop
        iteration.  The chunk budget is cadence-aware: the big quantum
        (RLA_TPU_SERVE_CHUNK_BLOCKS) while no decode slot is live, the
        small one (RLA_TPU_SERVE_CHUNK_MIN_BLOCKS) between decode waves
        — decode cadence stays bounded by one small chunk's compute.
        Both quanta are fixed buckets of the existing chunk-prefill
        program family, so steady state compiles nothing new."""
        for i, cur in enumerate(self._cursors):
            if cur is not None:
                self._advance_cursor(i, cur, decode_active)

    def _advance_cursor(self, i: int, cur: _PrefillCursor,
                        decode_active: bool) -> None:
        jnp = self._jax.numpy
        C = (self._chunk_min_blocks if decode_active
             else self._chunk_blocks) * self.block_len
        s0 = int(cur.req.prompt.size)
        rem = s0 - cur.pos
        if rem <= C:
            self._complete_cursor(i, cur)
            return
        # intermediate chunk at the exact quantum (no pad): allocate the
        # blocks its real positions write, run it at its true positions
        # through the table, discard the greedy token (position
        # pos+C-1's continuation is recomputed exactly by later chunks'
        # attention over these same blocks)
        need = -(-(cur.pos + C) // self.block_len) - len(cur.blocks)
        if need > 0:
            fresh = self.allocator.alloc(need)
            if fresh is None:
                return  # pool full now; the cursor waits, holding
                        # blocks-so-far (decode retires free blocks)
            cur.blocks.extend(fresh)
        table = np.zeros((self.table_blocks,), np.int32)
        table[:len(cur.blocks)] = cur.blocks
        chunk = np.ascontiguousarray(
            cur.req.prompt[cur.pos:cur.pos + C].reshape(1, C))
        t0 = time.monotonic()
        _, self._cache = self._chunk_prefill_fn(C)(
            self.params, self._cache, jnp.asarray(table),
            jnp.asarray(chunk), jnp.int32(cur.pos), jnp.int32(C - 1))
        cur.pos += C
        cur.chunks += 1
        self.metrics.inc("prefill_chunks")
        if self.perf_timeline is not None:
            self.perf_timeline.observe("prefill", time.monotonic() - t0)

    def _complete_cursor(self, i: int, cur: _PrefillCursor) -> None:
        """Final chunk: allocate the request's remaining (decode)
        blocks, run the padded tail, surface the first token, and
        promote the cursor to a live slot (or hand off / finish).  Pad
        positions are safe exactly as in the whole-prompt path: writes
        past the allocated span route to the garbage block through the
        zeroed table tail, and in-span pads sit at positions >= s0 that
        decode rewrites before the causal mask exposes them."""
        jnp = self._jax.numpy
        req, resp = cur.req, cur.resp
        s0 = int(req.prompt.size)
        needed = req.blocks_reserved or blocks_for_request(
            s0, req.max_new_tokens, self.block_len)
        need = needed - len(cur.blocks)
        if need > 0:
            fresh = self.allocator.alloc(need)
            if fresh is None:
                return  # pool full now; retry next loop iteration
            cur.blocks.extend(fresh)
        rem = s0 - cur.pos
        P = -(-rem // self.block_len) * self.block_len
        padded = np.zeros((1, P), np.int32)
        padded[0, :rem] = req.prompt[cur.pos:]
        table = np.zeros((self.table_blocks,), np.int32)
        table[:len(cur.blocks)] = cur.blocks
        t0 = time.monotonic()
        tok0, self._cache = self._chunk_prefill_fn(P)(
            self.params, self._cache, jnp.asarray(table),
            jnp.asarray(padded), jnp.int32(cur.pos), jnp.int32(rem - 1))
        cur.chunks += 1
        self.metrics.inc("prefill_chunks")
        self._register_prompt_blocks(req, cur.blocks, cur.shared,
                                     cur.keys)
        # graftlint: ok(host-sync) — TTFT gate: the first token must
        first = int(np.asarray(tok0)[0])  # be real before it is timed
        now = time.monotonic()
        resp.ttft_s = now - req.t_submit
        self.metrics.observe_ttft(resp.ttft_s)
        if self._slo is not None:
            self._slo.observe_ttft(resp.ttft_s, req)
            self._slo.observe_deadline_met(req)
        self.metrics.observe_prefill(now - cur.t_start)
        if self.perf_timeline is not None:
            self.perf_timeline.observe("prefill", now - t0)
        telemetry.emit("serve_prefill", trace=req.trace_id,
                       request=req.request_id, bucket=P, slot=i,
                       shared_blocks=len(cur.shared), streamed=True,
                       chunks=cur.chunks,
                       ttft_ms=round(resp.ttft_s * 1e3, 3))
        self._cursors[i] = None
        if req.export_handoff:
            # the disaggregated prefill lane rides the same cursor: the
            # request's lifecycle on THIS engine ends here
            self._export_handoff(req, resp, cur.blocks, cur.keys, first)
            self._observe_pool()
            return
        if req.max_new_tokens == 1:
            self._finish(req, resp, [first])
            self._release_request(req, cur.blocks)
        else:
            self._slots[i] = _Slot(req, resp, pos=s0, first_token=first,
                                   t_now=now, blocks=cur.blocks)
            self._tables[i, :] = 0
            self._tables[i, :len(cur.blocks)] = cur.blocks
        self._observe_pool()

    # -- KV handoff (disaggregated lanes) -------------------------------- #
    def _export_handoff(self, req: ServeRequest, resp: ServeResponse,
                        blocks: List[int], keys: List[str],
                        first: int) -> None:
        """Ship a just-prefilled request's KV blocks to the object store
        in bounded waves and resolve its response with the handoff
        descriptor.  The blocks stay pinned (refcounted) on this engine
        until ``release_handoff`` — a decode-side crash mid-import can
        always re-prefill against the still-cached source."""
        jnp = self._jax.numpy
        from ..parallel.redistribute import wave_schedule
        from ..runtime import object_store
        s0 = int(req.prompt.size)
        # per-block payload bytes (k+v), measured from the real pool
        per_block = max(1, self._pool_bytes // self.n_blocks)
        waves = wave_schedule([per_block] * len(blocks),
                              self.handoff_wave_bytes)
        cap = max(len(w) for w in waves)
        gather = self._kv_gather_fn(cap)
        store = object_store.global_store()
        refs: List[Any] = []
        wave_out: List[Tuple[int, Any]] = []
        total_bytes = 0
        try:
            for w in waves:
                ids = np.zeros((cap,), np.int32)  # pad = garbage block 0
                ids[:len(w)] = [blocks[j] for j in w]
                k, v = gather(self._cache, jnp.asarray(ids))
                # graftlint: ok(host-sync) — the copy IS the handoff
                kk = np.asarray(k)[:, :len(w)]
                vv = np.asarray(v)[:, :len(w)]  # graftlint: ok(host-sync) — the copy IS the handoff
                ref = store.put({"k": kk, "v": vv})
                refs.append(ref)
                wave_out.append((len(w), ref))
                total_bytes += kk.nbytes + vv.nbytes
        except BaseException:
            for ref in refs:  # don't leak shm segments on a failed ship
                try:
                    store.delete(ref)
                except Exception:
                    pass
            raise
        hid = next(self._handoff_ids)
        desc = {
            "handoff_id": hid,
            "request_id": req.request_id,
            "prompt": req.prompt,
            "max_new_tokens": req.max_new_tokens,
            "first": first,
            "pos": s0,
            "keys": list(keys),
            "block_len": self.block_len,
            "wave_cap": cap,
            "waves": wave_out,
            "bytes": total_bytes,
            "t_submit": req.t_submit,
            "deadline": req.deadline,
            "trace_id": req.trace_id,
        }
        with self._handoff_lock:
            self._handoffs[hid] = (req, list(blocks), refs)
        self.metrics.inc("kv_handoffs")
        self.metrics.inc("kv_handoff_bytes", total_bytes)
        telemetry.emit("serve_kv_export", trace=req.trace_id,
                       request=req.request_id, handoff=hid,
                       blocks=len(blocks), waves=len(wave_out),
                       bytes=total_bytes)
        if resp._complete(desc):
            self.metrics.inc("completed")

    def _admit_import(self, i: int, req: ServeRequest,
                      resp: ServeResponse) -> bool:
        """Turn a handoff descriptor into a live decode slot: allocate
        this engine's own blocks (the remap — no prefix lookup, the
        shipped bytes ARE the prefix), replay the object-store waves
        into them, register the full prompt blocks under their chain
        keys (first-writer-wins), and join mid-decode.  Returns False
        when the pool cannot place it right now (request pushed back).
        A stale-ref failure (source died and unlinked its segments)
        fails THIS response typed without killing the loop — the driver
        requeues the original for a full re-prefill."""
        jnp = self._jax.numpy
        from ..runtime import object_store
        desc = req.import_handoff
        needed = req.blocks_reserved or blocks_for_request(
            int(req.prompt.size), req.max_new_tokens, self.block_len)
        blocks = self.allocator.alloc(needed)
        if blocks is None:
            self.batcher.push_front((req, resp))
            return False
        try:
            cap = int(desc["wave_cap"])
            scatter = self._kv_scatter_fn(cap)
            store = object_store.global_store()
            idx = 0
            for count, ref in desc["waves"]:
                payload = store.get(ref)
                ids = np.zeros((cap,), np.int32)  # pad = garbage block 0
                ids[:count] = blocks[idx:idx + count]
                idx += count
                kk, vv = payload["k"], payload["v"]
                if count < cap:
                    pad = [(0, 0)] * kk.ndim
                    pad[1] = (0, cap - count)
                    kk = np.pad(kk, pad)  # pad payloads land in block 0
                    vv = np.pad(vv, pad)
                self._cache = scatter(self._cache, jnp.asarray(ids),
                                      jnp.asarray(kk), jnp.asarray(vv))
        except object_store.ObjectStoreError as e:
            self._release_request(req, blocks)
            if resp._fail(e):
                self.metrics.inc("failed")
            return True  # consumed; the loop (and the tier) live on
        except BaseException as e:
            self._release_request(req, blocks)
            if resp._fail(e):
                self.metrics.inc("failed")
            raise
        # register only AFTER every wave landed: a partially imported
        # block must never be reachable from the prefix index
        if self.prefix_cache:
            for j, key in enumerate(desc.get("keys", ())):
                self.allocator.register(key, blocks[j])
        first = int(desc["first"])
        now = time.monotonic()
        # no TTFT/queue-wait observation here: the first token was timed
        # where it was produced (the prefill lane); this engine only
        # contributes decode cadence
        telemetry.emit("serve_kv_import", trace=req.trace_id,
                       request=req.request_id,
                       handoff=desc.get("handoff_id"),
                       blocks=len(blocks), waves=len(desc["waves"]))
        if req.max_new_tokens == 1:
            self._finish(req, resp, [first])
            self._release_request(req, blocks)
        else:
            self._slots[i] = _Slot(req, resp, pos=int(desc["pos"]),
                                   first_token=first, t_now=now,
                                   blocks=blocks)
            self._tables[i, :] = 0
            self._tables[i, :len(blocks)] = blocks
        self._observe_pool()
        return True

    # -- decode --------------------------------------------------------- #
    def _decode_step(self, active: List[int]) -> None:
        """One batched step over ALL slots (static shape); only active
        rows advance host-side.  Inactive rows feed token 0 at position
        0 — dense: their slot is rewritten by the next join before the
        causal mask can expose the garbage; paged: their all-zero table
        routes the write to the reserved garbage block."""
        jnp = self._jax.numpy
        toks = np.zeros((self.max_slots,), np.int32)
        poss = np.zeros((self.max_slots,), np.int32)
        for i in active:
            s = self._slots[i]
            toks[i] = s.last
            poss[i] = s.pos
        t0 = time.monotonic()
        if self.paged:
            toks_next, row_ok, self._cache = self._step(
                self.params, self._cache, jnp.asarray(self._tables),
                jnp.asarray(toks), jnp.asarray(poss))
        else:
            toks_next, row_ok, self._cache = self._step(
                self.params, self._cache, jnp.asarray(toks),
                jnp.asarray(poss))
        # deliberate: step k+1's input IS step k's output, so the loop
        # must materialize it — the one sync a greedy feed cannot avoid
        nxt = np.asarray(toks_next)  # graftlint: ok(host-sync) — feed gate
        # the numeric guard's health bits ride that same materialization
        okh = np.asarray(row_ok)  # graftlint: ok(host-sync) — feed gate
        now = time.monotonic()
        self.metrics.observe_step(now - t0, len(active))
        if self.perf_timeline is not None:
            self.perf_timeline.observe("decode", now - t0)
        # batched event (one per step, not per slot): slot-level identity
        # lives in the admit/prefill/respond events' traces
        telemetry.emit("serve_decode_step", active=len(active),
                       step_ms=round((now - t0) * 1e3, 3))
        retired = False
        for i in active:
            s = self._slots[i]
            if not bool(okh[i]):
                # non-finite logits for THIS row: fail the one request
                # typed (NumericAnomaly crosses the replica wire with its
                # postmortem intact) instead of streaming garbage tokens;
                # the slot's blocks go back to the pool and the other
                # rows of the batch are untouched
                from ..runtime.guardian import NumericAnomaly
                err = NumericAnomaly.for_trip(
                    step=s.pos, blame="unknown",
                    flags={"decode_logits_nonfinite": True},
                    detail="serve decode produced non-finite logits")
                self.metrics.inc("numeric_anomalies")
                if s.resp._fail(err):
                    self.metrics.inc("failed")
                telemetry.emit("anomaly_trip", tier="serve", slot=i,
                               pos=s.pos, request_id=id(s.req))
                if self.paged:
                    self._release_request(s.req, s.blocks)
                    self._tables[i, :] = 0
                self._slots[i] = None
                retired = True
                continue
            tok = int(nxt[i])
            s.generated.append(tok)
            s.pos += 1
            s.last = tok
            s.remaining -= 1
            gap = now - s.t_last
            self.metrics.observe_token_latency(gap)
            if self._slo is not None:
                self._slo.observe_token(gap, s.req)
            s.t_last = now
            if s.remaining <= 0:
                self._finish(s.req, s.resp, s.generated)
                if self.paged:
                    self._release_request(s.req, s.blocks)
                    self._tables[i, :] = 0
                self._slots[i] = None  # retire = host-side table write
                retired = True
        if retired:
            self._observe_pool()

    # -- speculative lane ------------------------------------------------ #
    def _run_speculative(self, req: ServeRequest,
                         resp: ServeResponse) -> bool:
        """Serve one single-stream request end-to-end through greedy
        speculative decode against the PAGED pool: paged chunk prefill
        (prefix hits included), then rounds of draft-propose / one-pass
        target verification whose chunk writes land in the request's
        pre-reserved scratch blocks.  Rejected positions are rewritten
        by later rounds before the mask can expose them (the linear-
        cache no-rollback argument).  Returns False when the pool cannot
        place the request right now (request pushed back, nothing
        consumed)."""
        jnp = self._jax.numpy
        placed = self._place_blocks(req)
        if placed is None:
            self.batcher.push_front((req, resp))
            return False
        blocks, shared, keys = placed
        self._spec_active = 1
        try:
            try:
                self._spec_decode(req, resp, blocks, shared, keys)
            except BaseException as e:
                # the request is in neither the queue nor a slot: fail
                # its future here or the client hangs until timeout
                if resp._fail(e):
                    self.metrics.inc("failed")
                raise
        finally:
            self._spec_active = 0
            self._release_request(req, blocks)
            self._observe_pool()
        return True

    def _spec_decode(self, req: ServeRequest, resp: ServeResponse,
                     blocks: List[int], shared, keys) -> None:
        jnp = self._jax.numpy
        s0 = int(req.prompt.size)
        first, table, now = self._paged_prefill(req, resp, blocks,
                                                shared, keys, slot=-1,
                                                speculative=True)
        table_j = jnp.asarray(table)
        self.metrics.inc("speculative_requests")
        self._observe_pool()
        out = [first]
        if req.max_new_tokens > 1:
            # draft prefill: full padded prompt, fixed cache length.
            # Bucket WITHOUT the dense max_total_len clamp: block
            # rounding may admit prompts past W (the table span covers
            # them; the admission hard cap bounds them by max_seq_len)
            PB = -(-s0 // self.block_len) * self.block_len
            dpad = np.zeros((1, PB), np.int32)
            dpad[0, :s0] = req.prompt
            d_cache = self._draft_prefill_fn(PB)(
                self.draft_params, jnp.asarray(dpad),
                jnp.int32(s0 - 1))
            score = self._spec_score_fn()
            k = self.spec_k
            mx = req.max_new_tokens
            t_last_tok = now
            while len(out) < mx:
                if self._stop.is_set() and self._cancel_active:
                    # fast teardown must be able to interrupt the lane
                    # mid-request, exactly like _cancel_slots does for
                    # slot decodes
                    if resp._fail(ServeCancelled(
                            f"request {req.request_id} cancelled "
                            "mid-speculative-decode: engine stopped "
                            "with cancel_active")):
                        self.metrics.inc("cancelled")
                    return
                pos = s0 + len(out) - 1  # newest real token's slot
                last = jnp.asarray([out[-1]], jnp.int32)
                d_cache, draft_toks = self._d_propose(
                    d_cache, last, jnp.asarray(pos))
                # the next round's feed depends on these tokens
                # graftlint: ok(host-sync) — accept gate
                drafts = [int(t) for t in np.asarray(draft_toks)]
                chunk = jnp.asarray([[out[-1]] + drafts[:-1]],
                                    jnp.int32)
                t0 = time.monotonic()
                greedy_arr, self._cache = score(
                    self.params, self._cache, table_j, chunk,
                    jnp.int32(pos))
                # graftlint: ok(host-sync) — accept gate
                greedy = np.asarray(greedy_arr)
                accept = 0
                while accept < k and greedy[accept] == drafts[accept] \
                        and len(out) + accept + 1 < mx:
                    accept += 1
                self.metrics.inc("speculative_tokens_accepted",
                                 accept)
                new = drafts[:accept] + [int(greedy[accept])] \
                    if accept < k else drafts[:accept]
                new = new[:mx - len(out)]
                now = time.monotonic()
                self.metrics.observe_spec_round(now - t0, len(new))
                # per-token latency: the round produced len(new)
                # tokens in one target pass — amortize honestly
                dt_tok = (now - t_last_tok) / max(1, len(new))
                for _ in new:
                    self.metrics.observe_token_latency(dt_tok)
                    if self._slo is not None:
                        self._slo.observe_token(dt_tok, req)
                t_last_tok = now
                out.extend(new)
        self._finish(req, resp, out)

    def _finish(self, req: ServeRequest, resp: ServeResponse,
                generated: List[int]) -> None:
        tokens = np.concatenate(  # graftlint: ok(host-sync) — host list,
            [req.prompt, np.asarray(generated, np.int32)])  # no device value
        if resp._complete(tokens):
            self.metrics.inc("completed")
            telemetry.emit("serve_respond", trace=req.trace_id,
                           request=req.request_id,
                           tokens=len(generated))

    def _cancel_slots(self) -> None:
        for i, s in enumerate(self._slots):
            if s is None:
                continue
            if s.resp._fail(ServeCancelled(
                    f"request {s.req.request_id} cancelled mid-decode: "
                    "engine stopped with cancel_active")):
                self.metrics.inc("cancelled")
            self._release_request(s.req, s.blocks)
            if self.paged:
                self._tables[i, :] = 0
            self._slots[i] = None
        for i, cur in enumerate(self._cursors):
            if cur is None:
                continue
            if cur.resp._fail(ServeCancelled(
                    f"request {cur.req.request_id} cancelled "
                    "mid-prefill: engine stopped with cancel_active")):
                self.metrics.inc("cancelled")
            self._release_request(cur.req, cur.blocks)
            self._cursors[i] = None
