"""Continuous-batching serve engine over the static-shaped decode loop.

The decode cache is allocated ``[L, max_slots, H, max_total_len, D]`` up
front, so the engine's whole lifecycle is THREE compiled programs, all
static-shaped, none ever retraced per request:

- **prefill** (one per prompt-length bucket): run a right-padded prompt,
  return the first greedy token and a single-row cache;
- **join**: dynamic_update_slice the row cache into a free slot (slot
  index is traced — admitting never recompiles);
- **step**: one ``decode_step_rows`` over ALL slots at per-row positions,
  argmax per row.

Joining and retiring sequences mid-flight is therefore a slot write and a
host-side slot free — the veScale-style per-replica eager model: one
process, one fixed mesh (decode runs replicated, like ``generate()``),
requests streaming through fixed-shape programs.

**Exactness contract**: greedy only; every response is token-identical to
a standalone ``GPT.generate(prompt, max_new_tokens)`` of that prompt.
This holds because prefill/step reuse the same ``_decode_attn_block``
arithmetic, pad positions are causally masked (prefill) or rewritten
before the mask exposes them (decode), and softmax over the wider shared
cache adds only exactly-zero terms.  The CPU test suite asserts it
token-for-token.

Single-stream note: a batch-1 request could equally be routed through
``models.speculative.speculative_generate`` (its linear-cache chunk
scoring is join-compatible); the engine keeps greedy slots for
simplicity, but the speculative path enforces the same exactness
contract, so a router may mix them per request.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

import numpy as np

from ..telemetry import recorder as telemetry
from ..utils.logging import log
from .batcher import (AdmissionController, ServeCancelled, ServeRequest,
                      ServeResponse)
from .metrics import ServeMetrics


class _Slot:
    """Host-side state of one active decode slot."""

    __slots__ = ("req", "resp", "pos", "last", "generated", "remaining",
                 "t_last")

    def __init__(self, req: ServeRequest, resp: ServeResponse, pos: int,
                 first_token: int, t_now: float):
        self.req = req
        self.resp = resp
        self.pos = pos                    # position of the token to feed
        self.last = first_token           # token to feed next step
        self.generated = [first_token]
        self.remaining = req.max_new_tokens - 1
        self.t_last = t_now               # per-token latency anchor


class ServeEngine:
    """Continuous-batching greedy inference over one model replica.

    ``max_slots``: fixed decode batch (the cache's B).  ``queue_depth``:
    admission cap beyond the slots (backpressure).  ``max_total_len``:
    per-slot cache length; prompt + max_new_tokens of every request must
    fit (defaults to the model's max_seq_len).  ``prompt_block``: prompts
    are right-padded to multiples of this, bounding prefill compile count
    without unbounded padding waste.
    """

    def __init__(self, model: Any, params: Any, *, max_slots: int = 4,
                 queue_depth: int = 64,
                 max_total_len: Optional[int] = None,
                 max_new_tokens_cap: Optional[int] = None,
                 prompt_block: int = 8,
                 metrics: Optional[ServeMetrics] = None,
                 idle_poll_s: float = 0.05):
        import jax

        if model.cfg.sliding_window is not None:
            raise ValueError(
                "the serve engine needs linear cache slots; "
                "sliding_window models are unsupported (their rolling "
                "ring cache cannot slot-join)")
        if max_slots < 1:
            raise ValueError("max_slots must be >= 1")
        W = (max_total_len if max_total_len is not None
             else model.cfg.max_seq_len)
        if W > model.cfg.max_seq_len:
            raise ValueError(
                f"max_total_len {W} exceeds the model's max_seq_len "
                f"{model.cfg.max_seq_len}")
        self.model = model
        # decode replicated, exactly like generate(): a training-time mesh
        # must not carve up step-sized activations
        self._mesh_saved, model.mesh = model.mesh, None
        self.params = jax.tree.map(jax.numpy.asarray, params)
        self.max_slots = max_slots
        self.max_total_len = W
        self.prompt_block = max(1, prompt_block)
        self.metrics = metrics or ServeMetrics()
        self.batcher = AdmissionController(
            queue_depth=queue_depth, max_total_len=W,
            max_new_tokens_cap=max_new_tokens_cap)
        self.metrics.bind_queue(lambda: self.batcher.depth)
        self._idle_poll_s = idle_poll_s
        self._jax = jax
        # donate the cache operand where donation is real (TPU/GPU): the
        # hot loop reassigns self._cache every call, so without donation
        # each step/join copies the whole [L,B,H,W,D] pair and doubles
        # peak cache memory.  CPU ignores donation with a warning per
        # call site -- skip it there to keep test logs quiet.
        donate = jax.default_backend() != "cpu"
        self._join = jax.jit(type(model).cache_join,
                             donate_argnums=(0,) if donate else ())

        def step_tokens(p, c, t, pos):
            # argmax INSIDE the compiled step: the engine's lifecycle
            # stays exactly three programs (compile-guard asserts it),
            # and the per-step device->host transfer is [B] tokens
            # instead of [B, vocab] logits
            logits, cache = model.decode_step_rows(p, c, t, pos)
            return jax.numpy.argmax(logits, -1).astype(jax.numpy.int32), \
                cache

        self._step = jax.jit(step_tokens,
                             donate_argnums=(1,) if donate else ())
        self._prefills: Dict[int, Any] = {}
        self._cache = None
        self._slots: List[Optional[_Slot]] = [None] * max_slots
        self._stop = threading.Event()
        self._cancel_active = False
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------------ #
    # Lifecycle                                                          #
    # ------------------------------------------------------------------ #
    def start(self) -> "ServeEngine":
        if self._thread is not None:
            raise RuntimeError("engine already started")
        self._cache = self.model.decode_cache_alloc(self.max_slots,
                                                    self.max_total_len)
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="rla-tpu-serve-engine")
        self._thread.start()
        return self

    def stop(self, cancel_active: bool = False,
             timeout: float = 60.0) -> None:
        """Stop admitting; by default FINISH the in-flight slots (their
        budgets bound the wait), cancel everything still queued with
        ``ServeCancelled``, then join the loop.  ``cancel_active=True``
        cancels in-flight requests too (fast teardown)."""
        self._cancel_active = cancel_active
        self._stop.set()
        self.batcher.kick()
        if self._thread is not None:
            self._thread.join(timeout=timeout)
            self._thread = None
        n = self.batcher.shutdown()
        if n:
            self.metrics.inc("cancelled", n)
        self.model.mesh = self._mesh_saved

    def __enter__(self) -> "ServeEngine":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # ------------------------------------------------------------------ #
    # Client surface                                                     #
    # ------------------------------------------------------------------ #
    def submit(self, prompt: Any, max_new_tokens: int) -> ServeResponse:
        """Admit a request (typed QueueFull/RequestRejected backpressure);
        the response resolves to prompt + greedily generated tokens,
        token-identical to ``generate()``."""
        from .batcher import QueueFull, RequestRejected
        try:
            resp = self.batcher.submit(prompt, max_new_tokens)
        except (QueueFull, RequestRejected):
            # admission rejections only: a ServeCancelled from a stopping
            # engine must not read as overload in the counters
            self.metrics.inc("rejected")
            raise
        self.metrics.inc("submitted")
        # per-request trace (minted at admission): the whole
        # admit -> prefill -> respond lifecycle shares it
        telemetry.emit("serve_admit", trace=resp.request.trace_id,
                       request=resp.request.request_id,
                       prompt_len=int(resp.request.prompt.size))
        return resp

    def stats(self) -> Dict[str, Any]:
        return self.metrics.snapshot()

    # ------------------------------------------------------------------ #
    # Driver loop                                                        #
    # ------------------------------------------------------------------ #
    def _run(self) -> None:
        try:
            while True:
                if not self._stop.is_set():
                    self._admit()
                active = [i for i, s in enumerate(self._slots)
                          if s is not None]
                if active:
                    if self._stop.is_set() and self._cancel_active:
                        self._cancel_slots()
                        continue
                    self._decode_step(active)
                elif self._stop.is_set():
                    return
                else:
                    self.batcher.wait_for_work(self._idle_poll_s)
        except BaseException as e:  # engine death must fail loudly, typed
            log.error("serve engine loop died: %s", e)
            for i, s in enumerate(self._slots):
                if s is not None and s.resp._fail(e):
                    self.metrics.inc("failed")
                self._slots[i] = None
            n = self.batcher.shutdown()
            if n:  # keep completed+failed+cancelled == submitted honest
                self.metrics.inc("cancelled", n)
            raise

    def _bucket(self, s0: int) -> int:
        b = self.prompt_block
        return min(-(-s0 // b) * b, self.max_total_len)

    def _prefill_fn(self, padded_len: int):
        if padded_len not in self._prefills:
            jax, model = self._jax, self.model
            jnp = jax.numpy

            def fn(params, tokens, last_index):
                h_last, cache = model._prefill(params, tokens, padded_len,
                                               last_index=last_index)
                logits = model._unembed_matmul(h_last, params,
                                               model.compute_dtype)
                return jnp.argmax(logits, -1).astype(jnp.int32), cache

            # memoized per prompt bucket: each padded length compiles
            # exactly once for the engine's lifetime, bounded by
            # max_total_len / prompt_block buckets
            self._prefills[padded_len] = jax.jit(fn)  # graftlint: ok(retrace) — memoized per bucket
        return self._prefills[padded_len]

    def _admit(self) -> int:
        """Fill free slots from the queue: pad-prefill each request, slot-
        join its cache, record TTFT (the first token exists the moment
        prefill returns)."""
        jnp = self._jax.numpy
        admitted = 0
        for i in range(self.max_slots):
            if self._slots[i] is not None:
                continue
            item = self.batcher.pop()
            if item is None:
                break
            req, resp = item
            t_a = time.monotonic()
            s0 = int(req.prompt.size)
            P = self._bucket(s0)
            padded = np.zeros((1, P), np.int32)
            padded[0, :s0] = req.prompt
            tok0, row_cache = self._prefill_fn(P)(
                self.params, jnp.asarray(padded), jnp.int32(s0 - 1))
            if req.max_new_tokens > 1:
                # single-token requests finish at prefill; joining their
                # row would copy the whole multi-slot cache for nothing
                self._cache = self._join(self._cache, row_cache,
                                         jnp.int32(i))
            # graftlint: ok(host-sync) — TTFT gate: the first token must
            first = int(np.asarray(tok0)[0])  # be real before it is timed
            now = time.monotonic()
            resp.ttft_s = now - req.t_submit
            self.metrics.observe_ttft(resp.ttft_s)
            self.metrics.observe_prefill(now - t_a)
            telemetry.emit("serve_prefill", trace=req.trace_id,
                           request=req.request_id, bucket=P, slot=i,
                           ttft_ms=round(resp.ttft_s * 1e3, 3))
            if req.max_new_tokens == 1:
                self._finish(req, resp, [first])
            else:
                self._slots[i] = _Slot(req, resp, pos=s0,
                                       first_token=first, t_now=now)
            admitted += 1
        return admitted

    def _decode_step(self, active: List[int]) -> None:
        """One batched step over ALL slots (static shape); only active
        rows advance host-side.  Inactive rows feed token 0 at position 0
        — their slot is rewritten by the next join before the causal mask
        can expose the garbage."""
        jnp = self._jax.numpy
        toks = np.zeros((self.max_slots,), np.int32)
        poss = np.zeros((self.max_slots,), np.int32)
        for i in active:
            s = self._slots[i]
            toks[i] = s.last
            poss[i] = s.pos
        t0 = time.monotonic()
        toks_next, self._cache = self._step(self.params, self._cache,
                                            jnp.asarray(toks),
                                            jnp.asarray(poss))
        # deliberate: step k+1's input IS step k's output, so the loop
        # must materialize it — the one sync a greedy feed cannot avoid
        nxt = np.asarray(toks_next)  # graftlint: ok(host-sync) — feed gate
        now = time.monotonic()
        self.metrics.observe_step(now - t0, len(active))
        # batched event (one per step, not per slot): slot-level identity
        # lives in the admit/prefill/respond events' traces
        telemetry.emit("serve_decode_step", active=len(active),
                       step_ms=round((now - t0) * 1e3, 3))
        for i in active:
            s = self._slots[i]
            tok = int(nxt[i])
            s.generated.append(tok)
            s.pos += 1
            s.last = tok
            s.remaining -= 1
            self.metrics.observe_token_latency(now - s.t_last)
            s.t_last = now
            if s.remaining <= 0:
                self._finish(s.req, s.resp, s.generated)
                self._slots[i] = None  # retire = host-side slot free

    def _finish(self, req: ServeRequest, resp: ServeResponse,
                generated: List[int]) -> None:
        tokens = np.concatenate(  # graftlint: ok(host-sync) — host list,
            [req.prompt, np.asarray(generated, np.int32)])  # no device value
        if resp._complete(tokens):
            self.metrics.inc("completed")
            telemetry.emit("serve_respond", trace=req.trace_id,
                           request=req.request_id,
                           tokens=len(generated))

    def _cancel_slots(self) -> None:
        for i, s in enumerate(self._slots):
            if s is None:
                continue
            if s.resp._fail(ServeCancelled(
                    f"request {s.req.request_id} cancelled mid-decode: "
                    "engine stopped with cancel_active")):
                self.metrics.inc("cancelled")
            self._slots[i] = None
