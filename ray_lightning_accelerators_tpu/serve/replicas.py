"""N serve-engine replicas on the actor runtime, watchdog-supervised,
with a self-healing controller (serve/controller.py).

Each replica is a ``runtime.actors.Worker`` subprocess owning a full
engine (weights + cache + driver loop) — the per-replica eager execution
model of veScale-style runtimes: the driver here is a thin router, not a
participant in the math.  Requests flow driver -> replica as CHUNKS (one
dispatch carries several requests, submitted to the replica's engine
together so it continuous-batches them); responses flow back on the
worker future, along with the engine's own metrics snapshot — the
load/SLO signal the controller routes and scales on.

Failure model (the reason this layer exists):

- a replica that DIES fails its chunk future with "worker died";
- a replica that WEDGES (hung XLA dispatch, frozen process) never fails
  anything on its own — the pool's ``Watchdog`` reaps it from heartbeat
  staleness and the chunk future fails ``WorkerWedged``;
- either way the chunk's unanswered requests are RE-QUEUED head-of-line
  (with an exponential-backoff ``not_before`` stamp, bounded by a per-
  request retry budget) and complete on a surviving replica.  Responses
  are exactly-once by the ``ServeResponse`` first-completion-wins
  contract, so a request is never lost and never answered twice
  (``metrics`` proves the accounting) — the same contract that makes
  HEDGED re-dispatch of a slow replica's oldest chunk safe;
- a worker-side ``RemoteError`` (the engine itself raised) is an
  APPLICATION failure: re-running it elsewhere would fail again, so it
  fails the requests typed instead of poisoning every replica in turn.

A replica that went down no longer stays down: its circuit breaker
opens, backs off, half-open-probes and rejoins rotation
(``ReplicaController.maybe_revive``); ``revive(rank)`` remains the
manual path.  Sustained SLO burn / queue occupancy scales the tier up
(``max_replicas``), sustained idle drains it back down, and a saturated
tier with no headroom sheds typed (``BrownoutShed``).
"""

from __future__ import annotations

import itertools
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ..runtime.actors import ActorPool, RemoteError
from ..runtime.object_store import ObjectStoreError
from ..runtime.watchdog import WorkerWedged
from ..utils.logging import log
from .batcher import (AdmissionController, BrownoutShed, ServeCancelled,
                      ServeRequest, ServeResponse, chain_prefix_keys)
from .controller import (LANE_DECODE, LANE_PREFILL, ControllerConfig,
                         ReplicaController)
from .metrics import ServeMetrics

# affinity routing hashes at most this many chain keys per prompt: the
# router only needs enough of the chain to discriminate prefixes, not a
# digest of the whole prompt
_AFFINITY_KEY_LIMIT = 32

# live-plane labels for groups sharing one process (telemetry/live.py)
_GROUP_SEQ = itertools.count()

# worker-process side: one engine per replica process, installed by
# _replica_init (module-global so chunk dispatches find it); the chaos
# injector for replica-layer faults resolves lazily on the first chunk
_ENGINE = None
_CHAOS: Any = None  # None = unresolved, False = no replica faults


def _replica_init(engine_factory: Callable[[], Any]) -> bool:
    """Build and start this replica's engine (runs IN the worker).
    Installs the compile-guard listener first, so every chunk's stats
    snapshot can carry an honest backend-compile count (the acceptance
    tests pin zero steady-state recompiles per replica)."""
    global _ENGINE
    try:
        from ..analysis import compile_guard
        compile_guard.install()
    except Exception:
        pass
    if _ENGINE is not None:
        _ENGINE.stop(cancel_active=True)
    _ENGINE = engine_factory()
    _ENGINE.start()
    return True


def _replica_chaos(rank: int):
    """Replica-layer chaos injector (testing/chaos.py), resolved once
    per worker process.  ``hang`` freezes this process's heartbeat so
    the pool watchdog sees a frozen process, exactly like worker-layer
    hangs."""
    global _CHAOS
    if _CHAOS is None:
        from ..analysis import knobs
        inj = False
        if knobs.get_raw("RLA_TPU_CHAOS"):
            from ..runtime.actors import freeze_current_heartbeat
            from ..testing.chaos import ChaosInjector
            inj = ChaosInjector.from_env(
                rank, freeze_heartbeat=freeze_current_heartbeat,
                layer="replica") or False
        _CHAOS = inj
    return _CHAOS or None


def _engine_stats_snapshot() -> Dict[str, Any]:
    snap = _ENGINE.stats()
    try:
        from ..analysis import compile_guard
        snap["compile_count"] = compile_guard.compile_count()
    except Exception:
        pass
    return snap


def _replica_serve(rank: int, items: List[Tuple[int, Any, int]]
                   ) -> Tuple[List[Tuple[int, Any]], Dict[str, Any]]:
    """Serve one chunk (runs IN the worker).  Submit EVERY request before
    waiting on any, so the engine joins them into shared decode steps —
    this is where driver-level chunking becomes replica-level continuous
    batching.  Returns ``(results, engine stats snapshot)`` — the stats
    ride every chunk home so the controller's routing/autoscale signals
    stay fresh without extra dispatches (which would also shift the
    worker's chaos dispatch numbering)."""
    if _ENGINE is None:
        raise RuntimeError("replica engine not initialized")
    chaos = _replica_chaos(rank)
    if chaos is not None:
        chaos.on_dispatch()  # may crash/hang/slow THIS chunk
    handles = [(rid, _ENGINE.submit(np.asarray(prompt, np.int32), n))
               for rid, prompt, n in items]
    results = [(rid, np.asarray(h.result())) for rid, h in handles]
    return results, _engine_stats_snapshot()


def _replica_prefill(rank: int,
                     items: List[Tuple[int, Any, int, float, Any, Any]]
                     ) -> Tuple[List[Tuple[int, Any]], Dict[str, Any]]:
    """Prefill-lane chunk (runs IN the worker): each request resolves to
    a KV handoff DESCRIPTOR, not tokens — the engine pins the prefilled
    blocks until the driver confirms the decode side took ownership
    (``_replica_release``).  Items carry the client's original
    ``t_submit``/``deadline``/``trace_id`` stamps; monotonic clocks are
    system-wide on this host, so the absolute deadline survives the
    process hop and an expired request still sheds typed at the lane."""
    if _ENGINE is None:
        raise RuntimeError("replica engine not initialized")
    chaos = _replica_chaos(rank)
    if chaos is not None:
        chaos.on_dispatch()  # may crash/hang/slow THIS chunk
    handles = [(rid, _ENGINE.submit_handoff(
        np.asarray(prompt, np.int32), n, t_submit=t_submit,
        deadline=deadline, trace_id=trace_id))
        for rid, prompt, n, t_submit, deadline, trace_id in items]
    results = [(rid, h.result()) for rid, h in handles]
    return results, _engine_stats_snapshot()


def _replica_import(rank: int, descs: List[Tuple[int, Dict[str, Any]]]
                    ) -> Tuple[List[Tuple[int, Any]], Dict[str, Any]]:
    """Decode-lane chunk (runs IN the worker): turn each handoff
    descriptor into a live mid-decode slot and wait out the generation.
    A stale object-store ref (the source died and its segments were
    unlinked) surfaces typed — the driver requeues the originals for a
    full re-prefill instead of failing them."""
    if _ENGINE is None:
        raise RuntimeError("replica engine not initialized")
    chaos = _replica_chaos(rank)
    if chaos is not None:
        chaos.on_dispatch()  # may crash/hang/slow THIS chunk
    handles = [(rid, _ENGINE.submit_import(desc)) for rid, desc in descs]
    results = [(rid, np.asarray(h.result())) for rid, h in handles]
    return results, _engine_stats_snapshot()


def _replica_release(rank: int, handoff_ids: List[int]) -> int:
    """Drop export holds on this prefill replica (runs IN the worker):
    the decode side owns the KV now — the source's copies stay only as
    LRU-evictable prefix cache.  Deliberately NOT a chaos dispatch:
    release is cleanup bookkeeping, and letting it consume chaos
    dispatch numbers would make crash-at-chunk-N scripts misfire."""
    if _ENGINE is None:
        return 0
    n = 0
    for hid in handoff_ids:
        n += bool(_ENGINE.release_handoff(hid))
    return n


def _replica_stats() -> Dict[str, Any]:
    """Engine metrics snapshot (runs IN the worker) — also the circuit
    breaker's half-open probe dispatch."""
    if _ENGINE is None:
        raise RuntimeError("replica engine not initialized")
    return _engine_stats_snapshot()


def _replica_stop() -> bool:
    """Graceful engine stop (runs IN the worker): the scale-down drain
    path — admission is already fenced driver-side, in-flight slots
    finish on the engine's own retire path."""
    global _ENGINE
    if _ENGINE is not None:
        _ENGINE.stop(cancel_active=False)
        _ENGINE = None
    return True


def _is_application_failure(exc: BaseException) -> bool:
    """Failure triage for a chunk dispatch: True when the DISPATCHED
    CODE failed deterministically (fail those requests, keep the replica
    serving), False for infrastructure death (open the replica's
    circuit, requeue onto survivors).

    Application = a ``RemoteError`` payload, or a typed exception
    ``runtime/wire.py`` rebuilt from a worker-raised payload
    (``remote_typed`` — e.g. an ``ObjectStoreError`` from a stale ref:
    deterministic per request, and requeueing it would cascade a
    poisoned request through every replica).  A ``WorkerWedged`` stays
    infrastructure even when the worker itself raised it."""
    if isinstance(exc, RemoteError):
        return True
    return (getattr(exc, "remote_typed", False)
            and not isinstance(exc, WorkerWedged))


class ServeReplicas:
    """Self-healing router over ``num_replicas`` engine replicas.

    ``engine_factory``: zero-arg callable building a STARTABLE
    ``ServeEngine`` — it executes inside each worker process (ship numpy
    params in the closure; the factory runs after the worker's jax
    initializes).  ``chunk_size``: max requests per dispatch (the
    replica's engine batches the chunk).  ``wedge_timeout_s`` /
    ``heartbeat_s``: watchdog knobs, see runtime/watchdog.py.
    ``max_requeues``: infra-failure retries per request before failing
    it typed (None = the ``RLA_TPU_SERVE_MAX_RETRIES`` knob, default 2).

    ``controller``: a :class:`~.controller.ControllerConfig` (or None
    for the knob-backed default) configuring routing, hedging, the
    circuit breaker, autoscaling and brownout — see serve/controller.py.
    ``scale_env``: env overlay for autoscaled replicas (defaults to the
    heartbeat knob only — chaos/port overlays of the initial replicas
    are deliberately NOT inherited)."""

    def __init__(self, engine_factory: Callable[[], Any],
                 num_replicas: int = 2, *, queue_depth: int = 256,
                 max_total_len: Optional[int] = None,
                 chunk_size: int = 4,
                 max_requeues: Optional[int] = None,
                 heartbeat_s: Optional[float] = None,
                 wedge_timeout_s: Optional[float] = None,
                 supervise: bool = True,
                 env_per_worker: Optional[List[Dict[str, str]]] = None,
                 idle_poll_s: float = 0.02,
                 controller: Optional[ControllerConfig] = None,
                 scale_env: Optional[Dict[str, str]] = None,
                 affinity_block_len: int = 16):
        envs = [dict(e) for e in (env_per_worker
                                  or [{} for _ in range(num_replicas)])]
        if heartbeat_s is not None:
            for e in envs:
                e.setdefault("RLA_TPU_WORKER_HEARTBEAT_S",
                             str(heartbeat_s))
        self.chunk_size = max(1, chunk_size)
        self.queue_depth = queue_depth
        # affinity + lane routing hash prompts block-wise DRIVER-side;
        # this MUST equal the engines' block_len or the router's chain
        # keys never match what the replicas' prefix indexes register
        self.affinity_block_len = max(1, affinity_block_len)
        # handoff descriptors awaiting a decode-lane dispatch, appended
        # by prefill-done callbacks (collector threads) and drained by
        # the dispatch loop; deque append/popleft are atomic
        self._pending_imports: deque = deque()
        self.metrics = ServeMetrics()
        self.batcher = AdmissionController(queue_depth=queue_depth,
                                           max_total_len=max_total_len)
        self.metrics.bind_queue(lambda: self.batcher.depth)
        self._idle_poll_s = idle_poll_s
        self._stop = threading.Event()
        self._engine_factory = engine_factory
        self._scale_env = dict(scale_env or {})
        if heartbeat_s is not None:
            self._scale_env.setdefault("RLA_TPU_WORKER_HEARTBEAT_S",
                                       str(heartbeat_s))
        self._live_label: Optional[str] = None
        self.pool = ActorPool(num_replicas, env_per_worker=envs)
        try:
            for f in self.pool.execute_all(_replica_init, engine_factory):
                f.result()
            self.watchdog = (self.pool.watch(
                wedge_timeout_s=wedge_timeout_s) if supervise else None)
        except BaseException:
            self.pool.kill()
            raise
        cfg = controller or ControllerConfig.from_env()
        self.controller = ReplicaController(self, cfg)
        self.max_requeues = (max_requeues if max_requeues is not None
                             else cfg.max_retries)
        # per-lane occupancy gauges ride every tier snapshot; the merge
        # happens outside the metrics lock (ServeMetrics.snapshot), so
        # taking the controller lock inside lane_gauges cannot invert
        self.metrics.bind_lanes(self.controller.lane_gauges)
        self._dispatcher = threading.Thread(
            target=self._dispatch_loop, daemon=True,
            name="rla-tpu-serve-dispatch")
        self._dispatcher.start()
        self.controller.start()
        # live telemetry plane (telemetry/live.py): with
        # RLA_TPU_METRICS_PORT configured, the group's tier metrics and
        # the controller's per-replica table join the driver process's
        # /metrics + /statusz while the tier serves
        from ..telemetry import live as live_lib
        srv = live_lib.maybe_start_from_env()
        if srv is not None:
            self._live_label = f"replicas{next(_GROUP_SEQ)}"
            srv.sources.add_serve(self._live_label, self.metrics)
            srv.sources.bind_replica_controller(self.controller)

    # ------------------------------------------------------------------ #
    # Client surface                                                     #
    # ------------------------------------------------------------------ #
    def submit(self, prompt: Any, max_new_tokens: int) -> ServeResponse:
        from .batcher import QueueFull, RequestRejected
        shed = self.controller.should_shed()
        if shed is not None:
            depth, watermark, cap = shed
            self.metrics.inc("rejected")
            self.metrics.inc("brownout_shed")
            from ..telemetry import recorder as telemetry
            telemetry.emit("serve_brownout_shed", depth=depth,
                           watermark=watermark)
            raise BrownoutShed(depth, watermark, cap)
        try:
            resp = self.batcher.submit(prompt, max_new_tokens)
        except (QueueFull, RequestRejected):
            # admission rejections only -- shutdown's ServeCancelled must
            # not read as overload
            self.metrics.inc("rejected")
            raise
        self.metrics.inc("submitted")
        return resp

    def stats(self) -> Dict[str, Any]:
        out = self.metrics.snapshot()
        out["replicas"] = len(self.pool)
        out["replicas_down"] = self.controller.down_ranks()
        out["controller"] = self.controller.snapshot()
        if self.watchdog is not None:
            out["supervision"] = self.watchdog.report()
        return out

    def replica_stats(self, rank: int) -> Dict[str, Any]:
        """A live replica's own engine metrics (proves in-replica
        batching: its ``steps_batch_gt1`` counts shared decode steps;
        carries ``compile_count`` for steady-state recompile pins)."""
        w = self._worker(rank)
        if w is None:
            raise RuntimeError(
                f"replica {rank} is not in the pool (retired by a "
                "scale-down, or never existed)")
        return w.execute(_replica_stats).result()

    def revive(self, rank: int) -> None:
        """Restart a downed replica and re-initialize its engine NOW —
        the manual path; the controller's circuit breaker does the same
        automatically after its backoff."""
        self._revive_replica(rank)
        self.controller.note_revived(rank)

    def shutdown(self) -> None:
        self._stop.set()
        self.controller.stop()
        self.batcher.kick()
        self._dispatcher.join(timeout=30)
        n = self.batcher.shutdown()
        if n:
            self.metrics.inc("cancelled", n)
        # handoffs prefixed but never imported: cancel typed (their
        # source holds die with the replica engines at pool shutdown)
        while self._pending_imports:
            _src, req, resp, _desc = self._pending_imports.popleft()
            if resp._fail(ServeCancelled(
                    f"request {req.request_id} cancelled: tier shut "
                    "down with its KV handoff awaiting a decode "
                    "replica")):
                self.metrics.inc("cancelled")
        if self.watchdog is not None:
            self.watchdog.stop()
        if self._live_label is not None:
            from ..telemetry import live as live_lib
            srv = live_lib.get_server()
            if srv is not None:
                srv.sources.remove_serve(self._live_label)
                # only OUR controller: a sibling group that bound after
                # us must keep its table on the export
                srv.sources.unbind_replica_controller(self.controller)
            self._live_label = None
        self.pool.shutdown()

    def __enter__(self) -> "ServeReplicas":
        return self

    def __exit__(self, *exc) -> None:
        self.shutdown()

    # ------------------------------------------------------------------ #
    # Replica mechanics (the controller's hands)                         #
    # ------------------------------------------------------------------ #
    def _worker(self, rank: int) -> Any:
        """Rank-keyed lookup: after scale-downs the workers list is no
        longer index-aligned with ranks."""
        for w in self.pool.workers:
            if w.rank == rank:
                return w
        return None

    def _revive_replica(self, rank: int) -> Dict[str, Any]:
        """Restart + re-init one replica and PROBE it (one stats round
        trip) before it may rejoin rotation; raises on any failure.
        Each worker generation re-publishes its telemetry portfile and
        heartbeat channel from worker boot (runtime/actors._worker_main
        + telemetry/live.py), so a revived replica reappears in
        ClusterView/rla_top without extra plumbing."""
        w = self._worker(rank)
        if w is None:
            raise RuntimeError(f"replica {rank} is not in the pool")
        w.restart()
        w.execute(_replica_init, self._engine_factory).result(
            timeout=self.controller.cfg.probe_timeout_s)
        return w.execute(_replica_stats).result(
            timeout=self.controller.cfg.probe_timeout_s)

    def _add_replica(self) -> int:
        """Scale-up: spawn one more replica worker and init its engine
        (blocking; runs in the controller tick thread)."""
        w = self.pool.add_worker(env=dict(self._scale_env))
        try:
            w.execute(_replica_init, self._engine_factory).result()
        except BaseException:
            try:
                self.pool.drop([w.rank])
            except BaseException:
                pass
            raise
        return w.rank

    def _retire_replica(self, rank: int) -> None:
        """Scale-down of a DRAINED replica: stop its engine gracefully,
        then the worker, then forget the rank (survivors keep their
        rank identity — ``ActorPool.drop`` semantics)."""
        w = self._worker(rank)
        if w is None:
            return
        try:
            w.execute(_replica_stop).result(timeout=30)
        except BaseException as e:
            log.warning("graceful engine stop of replica %d failed: %s",
                        rank, e)
        self.pool.drop([rank])

    # ------------------------------------------------------------------ #
    # Dispatch                                                           #
    # ------------------------------------------------------------------ #
    def _dispatch_loop(self) -> None:
        while not self._stop.is_set():
            try:
                self._dispatch_once()
            except Exception as e:  # a policy bug must not kill dispatch
                log.error("serve dispatch iteration failed: %s", e)
                time.sleep(self._idle_poll_s)

    def _dispatch_once(self) -> None:
        # handoff descriptors first: their prefill already happened, so
        # every tick they wait is pure added TTFB on a finished prefill
        self._dispatch_imports()
        if not self.batcher.wait_for_work(self._idle_poll_s):
            return
        if not self.controller.serving_possible():
            # no capacity will ever come back on its own (every
            # circuit open and auto-revive disabled): fail the
            # queue typed rather than hang every caller forever
            for req, resp in iter(self.batcher.pop, None):
                if resp._fail(ServeCancelled(
                        f"request {req.request_id}: every replica is "
                        "down and auto-revive is disabled")):
                    self.metrics.inc("failed")
            while self._pending_imports:
                _src, req, resp, _desc = self._pending_imports.popleft()
                if resp._fail(ServeCancelled(
                        f"request {req.request_id}: every replica is "
                        "down and auto-revive is disabled")):
                    self.metrics.inc("failed")
            time.sleep(self._idle_poll_s)
            return
        batch: List[Tuple[ServeRequest, ServeResponse]] = []
        while len(batch) < self.chunk_size:
            item = self.batcher.pop()
            if item is None:
                break
            if item[1].done():
                # a requeued request a hedge copy already answered:
                # nothing left to serve — dropping it here saves a
                # whole wasted prefill+decode on a replica
                continue
            batch.append(item)
        if not batch:
            # nothing dispatchable right now (empty queue race or a
            # requeue-lane head still inside its retry backoff)
            time.sleep(self._idle_poll_s / 2)
            return
        # route PER REQUEST (prefix affinity is a property of the
        # prompt, not the chunk), then regroup by destination so one
        # dispatch still carries everything the replica can batch
        cfg = self.controller.cfg
        lanes_on = cfg.prefill_replicas > 0
        bl = self.affinity_block_len
        groups: Dict[Tuple[int, bool], List[
            Tuple[ServeRequest, ServeResponse]]] = {}
        unrouted: List[Tuple[ServeRequest, ServeResponse]] = []
        for req, resp in batch:
            keys = (chain_prefix_keys(req.prompt, bl,
                                      limit=_AFFINITY_KEY_LIMIT)
                    if cfg.affinity else None) or None
            handoff = (lanes_on and req.max_new_tokens > 1
                       and int(req.prompt.size) // bl
                       >= cfg.handoff_min_blocks)
            lane = ((LANE_PREFILL if handoff else LANE_DECODE)
                    if lanes_on else None)
            rank = self.controller.route(prefix_keys=keys, lane=lane)
            if rank is None:
                unrouted.append((req, resp))
                continue
            groups.setdefault((rank, handoff), []).append((req, resp))
        for item in reversed(unrouted):  # keep FIFO order at the head
            self.batcher.push_front(item)
        if not groups:
            time.sleep(self._idle_poll_s)
            return
        for (rank, handoff), chunk in groups.items():
            if handoff:
                self._dispatch_prefill(rank, chunk)
            else:
                self._dispatch(rank, chunk)

    def _dispatch(self, rank: int,
                  chunk: List[Tuple[ServeRequest, ServeResponse]],
                  hedge_of: Optional[Tuple[int, int]] = None) -> None:
        """Ship one chunk to ``rank`` (primary dispatch, or a HEDGE
        copy when ``hedge_of`` names the slow original)."""
        chunk_id = self.controller.on_dispatch(rank, chunk,
                                               hedge_of=hedge_of)
        items = [(req.request_id, req.prompt, req.max_new_tokens)
                 for req, _ in chunk]
        w = self._worker(rank)
        if w is None:
            fut = None
        else:
            fut = w.execute(_replica_serve, rank, items)
        if fut is None:
            exc = RuntimeError(f"replica {rank} left the pool before "
                               "dispatch")
            self.controller.note_infra_failure(rank, chunk_id, exc)
            for req, resp in chunk:
                self._requeue_or_fail(req, resp, exc, rank)
            return
        fut.add_done_callback(
            lambda f, r=rank, cid=chunk_id, c=chunk, h=hedge_of:
            self._on_chunk_done(r, cid, c, h, f))

    def _on_chunk_done(self, rank: int, chunk_id: int,
                       chunk: List[Tuple[ServeRequest, ServeResponse]],
                       hedge_of: Optional[Tuple[int, int]],
                       fut) -> None:
        """Runs on the worker's collector thread: settle or re-queue."""
        exc = fut.exception()
        if exc is None:
            results, stats = fut.result()
            self.controller.note_success(rank, chunk_id, stats)
            results = dict(results)
            now = time.monotonic()
            hedge_won = False
            for req, resp in chunk:
                tokens = results.get(req.request_id)
                if tokens is None:
                    self._requeue_or_fail(req, resp, RuntimeError(
                        f"replica {rank} returned no result for request "
                        f"{req.request_id}"), rank)
                elif resp._complete(tokens):
                    self.metrics.inc("completed")
                    # tier-level TTFT: a chunk returns the FULL
                    # sequence, so submit -> response is the finest
                    # first-token signal the driver can observe (it
                    # upper-bounds the replica's own TTFT and is what
                    # a tier client actually waits)
                    if resp.ttft_s is None:
                        resp.ttft_s = now - req.t_submit
                        self.metrics.observe_ttft(resp.ttft_s)
                    hedge_won = True
            if hedge_of is not None and hedge_won:
                # the hedge copy answered before the slow original —
                # first-completion-wins proves each response still
                # resolved exactly once.  Counted per hedge CHUNK, the
                # same unit as "hedged", so hedge_wins/hedged is a rate
                self.metrics.inc("hedge_wins")
            return
        if _is_application_failure(exc):
            # application failure: deterministic, don't poison survivors
            self.controller.note_app_failure(rank, chunk_id)
            log.error("replica %d failed a chunk application-side: %s",
                      rank, exc)
            for req, resp in chunk:
                if resp._fail(exc):
                    self.metrics.inc("failed")
            return
        # infra failure: wedged (watchdog reap) or died — open the
        # circuit and requeue; the breaker revives it later
        self.controller.note_infra_failure(rank, chunk_id, exc)
        if isinstance(exc, WorkerWedged):
            self.metrics.inc("wedge_events")
        log.warning("replica %d lost mid-chunk (%s); recovering %d "
                    "request(s) (requeue unless a hedge already "
                    "answered)", rank, type(exc).__name__, len(chunk))
        for req, resp in chunk:
            self._requeue_or_fail(req, resp, exc, rank)

    def _requeue_or_fail(self, req: ServeRequest, resp: ServeResponse,
                         exc: BaseException,
                         rank: Optional[int] = None) -> None:
        if resp.done():
            return
        if req.requeues >= self.max_requeues:
            if resp._fail(exc):
                self.metrics.inc("failed")
            return
        delay = self.controller.charge_retry(rank, req)
        if self.batcher.requeue(req, resp, delay_s=delay):
            self.metrics.inc("requeued")

    # ------------------------------------------------------------------ #
    # Disaggregated prefill/decode lanes (KV handoff)                    #
    # ------------------------------------------------------------------ #
    def _dispatch_prefill(self, rank: int,
                          chunk: List[Tuple[ServeRequest,
                                            ServeResponse]]) -> None:
        """Ship one prefill-lane chunk: the replica prefills and returns
        handoff DESCRIPTORS; `_on_prefill_done` queues them for a
        decode-lane import.  The chunk stays a first-class controller
        chunk — hedging sees it age like any other, and a hedge fires
        the normal full-serve path (first-completion-wins keeps that
        race exactly-once)."""
        chunk_id = self.controller.on_dispatch(rank, chunk)
        items = [(req.request_id, req.prompt, req.max_new_tokens,
                  req.t_submit, req.deadline, req.trace_id)
                 for req, _ in chunk]
        w = self._worker(rank)
        if w is None:
            exc = RuntimeError(f"replica {rank} left the pool before "
                               "prefill dispatch")
            self.controller.note_infra_failure(rank, chunk_id, exc)
            for req, resp in chunk:
                self._requeue_or_fail(req, resp, exc, rank)
            return
        fut = w.execute(_replica_prefill, rank, items)
        fut.add_done_callback(
            lambda f, r=rank, cid=chunk_id, c=chunk:
            self._on_prefill_done(r, cid, c, f))

    def _on_prefill_done(self, rank: int, chunk_id: int,
                         chunk: List[Tuple[ServeRequest, ServeResponse]],
                         fut) -> None:
        """Collector-thread callback for a prefill-lane chunk: hand each
        descriptor to the import queue (or clean up after a hedge that
        answered first)."""
        exc = fut.exception()
        if exc is not None:
            if _is_application_failure(exc):
                self.controller.note_app_failure(rank, chunk_id)
                log.error("replica %d failed a prefill chunk "
                          "application-side: %s", rank, exc)
                for req, resp in chunk:
                    if resp._fail(exc):
                        self.metrics.inc("failed")
                return
            self.controller.note_infra_failure(rank, chunk_id, exc)
            if isinstance(exc, WorkerWedged):
                self.metrics.inc("wedge_events")
            log.warning("prefill replica %d lost mid-chunk (%s); "
                        "recovering %d request(s)", rank,
                        type(exc).__name__, len(chunk))
            for req, resp in chunk:
                self._requeue_or_fail(req, resp, exc, rank)
            return
        results, stats = fut.result()
        self.controller.note_success(rank, chunk_id, stats)
        results = dict(results)
        now = time.monotonic()
        queued = False
        for req, resp in chunk:
            desc = results.get(req.request_id)
            if desc is None:
                self._requeue_or_fail(req, resp, RuntimeError(
                    f"replica {rank} returned no handoff for request "
                    f"{req.request_id}"), rank)
                continue
            if resp.done():
                # a hedge (full serve) answered while the lane worked:
                # nothing to import, just drop the source hold
                self._release_source(rank, [desc["handoff_id"]])
                continue
            self.metrics.inc("kv_handoffs")
            self.metrics.inc("kv_handoff_bytes",
                             int(desc.get("bytes", 0)))
            # tier-level TTFT: the first token exists the moment the
            # prefill lane returns, not when decode finishes
            if resp.ttft_s is None:
                resp.ttft_s = now - req.t_submit
                self.metrics.observe_ttft(resp.ttft_s)
            self._pending_imports.append((rank, req, resp, desc))
            queued = True
        if queued:
            self.batcher.kick()  # wake the dispatcher for the imports

    def _dispatch_imports(self) -> None:
        """Drain queued handoff descriptors onto decode-lane replicas
        (runs at the top of every dispatch iteration)."""
        batch = []
        while self._pending_imports and len(batch) < self.chunk_size:
            batch.append(self._pending_imports.popleft())
        if not batch:
            return
        groups: Dict[int, List[Tuple[int, ServeRequest, ServeResponse,
                                     Dict[str, Any]]]] = {}
        back = []
        for entry in batch:
            src_rank, req, resp, desc = entry
            if resp.done():
                # hedge/requeue answered while the descriptor queued
                self._release_source(src_rank, [desc["handoff_id"]])
                continue
            rank = self.controller.route(lane=LANE_DECODE)
            if rank is None:
                back.append(entry)
                continue
            groups.setdefault(rank, []).append(entry)
        for entry in reversed(back):
            self._pending_imports.appendleft(entry)
        for rank, entries in groups.items():
            self._dispatch_import(rank, entries)

    def _dispatch_import(self, rank: int,
                         entries: List[Tuple[int, ServeRequest,
                                             ServeResponse,
                                             Dict[str, Any]]]) -> None:
        chunk = [(req, resp) for _src, req, resp, _d in entries]
        chunk_id = self.controller.on_dispatch(rank, chunk)
        descs = [(req.request_id, desc)
                 for _src, req, _resp, desc in entries]
        w = self._worker(rank)
        if w is None:
            exc = RuntimeError(f"replica {rank} left the pool before "
                               "import dispatch")
            self.controller.note_infra_failure(rank, chunk_id, exc)
            self._recover_import_entries(entries, exc, rank)
            return
        fut = w.execute(_replica_import, rank, descs)
        fut.add_done_callback(
            lambda f, r=rank, cid=chunk_id, e=entries:
            self._on_import_done(r, cid, e, f))

    def _on_import_done(self, rank: int, chunk_id: int,
                        entries: List[Tuple[int, ServeRequest,
                                            ServeResponse,
                                            Dict[str, Any]]],
                        fut) -> None:
        """Settle a decode-lane import chunk.  Every terminal path
        releases the source holds exactly once: a released source keeps
        the prompt blocks LRU-cached in its prefix index, so even the
        requeue-for-re-prefill path lands back on a warm cache."""
        exc = fut.exception()
        if exc is None:
            results, stats = fut.result()
            self.controller.note_success(rank, chunk_id, stats)
            results = dict(results)
            for _src, req, resp, desc in entries:
                tokens = results.get(req.request_id)
                if tokens is None:
                    self._requeue_or_fail(req, resp, RuntimeError(
                        f"replica {rank} returned no result for "
                        f"imported request {req.request_id}"), rank)
                elif resp._complete(tokens):
                    self.metrics.inc("completed")
                    # residency truth: the KV now lives on the decode
                    # replica — future same-prefix routes go there
                    self.controller.note_import(rank,
                                                desc.get("keys"))
            self._release_entries(entries)
            return
        if _is_application_failure(exc):
            self.controller.note_app_failure(rank, chunk_id)
            if isinstance(exc, ObjectStoreError):
                # the shipped payload is gone (source died and its
                # segments were unlinked): deterministic for THIS
                # descriptor but not for the request — requeue it for
                # a full re-prefill instead of failing typed
                log.warning("import on replica %d hit a stale handoff "
                            "payload (%s); re-queueing %d request(s) "
                            "for full re-prefill", rank, exc,
                            len(entries))
                for _src, req, resp, _d in entries:
                    self._requeue_or_fail(req, resp, exc, rank)
            else:
                log.error("replica %d failed an import chunk "
                          "application-side: %s", rank, exc)
                for _src, req, resp, _d in entries:
                    if resp._fail(exc):
                        self.metrics.inc("failed")
            self._release_entries(entries)
            return
        self.controller.note_infra_failure(rank, chunk_id, exc)
        if isinstance(exc, WorkerWedged):
            self.metrics.inc("wedge_events")
        log.warning("decode replica %d lost mid-import (%s); "
                    "recovering %d request(s)", rank,
                    type(exc).__name__, len(entries))
        self._recover_import_entries(entries, exc, rank)

    def _recover_import_entries(self, entries, exc,
                                rank: Optional[int]) -> None:
        """Requeue an import chunk's originals (full re-prefill on a
        survivor) and release their source holds — the sources' prefix
        caches make the retry's prefill a block-table hit, not a
        recompute."""
        for _src, req, resp, _d in entries:
            self._requeue_or_fail(req, resp, exc, rank)
        self._release_entries(entries)

    def _release_entries(self, entries) -> None:
        by_src: Dict[int, List[int]] = {}
        for src, _req, _resp, desc in entries:
            by_src.setdefault(src, []).append(desc["handoff_id"])
        for src, hids in by_src.items():
            self._release_source(src, hids)

    def _release_source(self, src_rank: int,
                        handoff_ids: List[int]) -> None:
        """Fire-and-forget release of export holds on the prefill
        replica.  Best-effort by design: if the source is gone, its
        engine (and shm segments) died with it — there is nothing left
        to release."""
        w = self._worker(src_rank)
        if w is None or not w.is_alive:
            return
        try:
            fut = w.execute(_replica_release, src_rank,
                            list(handoff_ids))
            fut.add_done_callback(lambda f: f.exception())  # swallow
        except Exception:
            pass
