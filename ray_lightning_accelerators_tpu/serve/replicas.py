"""N serve-engine replicas on the actor runtime, watchdog-supervised.

Each replica is a ``runtime.actors.Worker`` subprocess owning a full
engine (weights + cache + driver loop) — the per-replica eager execution
model of veScale-style runtimes: the driver here is a thin router, not a
participant in the math.  Requests flow driver -> replica as CHUNKS (one
dispatch carries several requests, submitted to the replica's engine
together so it continuous-batches them); responses flow back on the
worker future.

Failure model (the reason this layer exists):

- a replica that DIES fails its chunk future with "worker died";
- a replica that WEDGES (hung XLA dispatch, frozen process) never fails
  anything on its own — the pool's ``Watchdog`` reaps it from heartbeat
  staleness and the chunk future fails ``WorkerWedged``;
- either way the chunk's unanswered requests are RE-QUEUED head-of-line
  and complete on a surviving replica.  Responses are exactly-once by the
  ``ServeResponse`` first-completion-wins contract, so a request is never
  lost and never answered twice (``metrics`` proves the accounting).
- a worker-side ``RemoteError`` (the engine itself raised) is an
  APPLICATION failure: re-running it elsewhere would fail again, so it
  fails the requests typed instead of poisoning every replica in turn.

Replicas that went down stay down (capacity degrades, correctness does
not); ``revive(rank)`` restarts and re-initializes one explicitly.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ..runtime.actors import ActorPool, RemoteError
from ..runtime.watchdog import WorkerWedged
from ..utils.logging import log
from .batcher import (AdmissionController, ServeCancelled, ServeRequest,
                      ServeResponse)
from .metrics import ServeMetrics

# worker-process side: one engine per replica process, installed by
# _replica_init (module-global so chunk dispatches find it)
_ENGINE = None


def _replica_init(engine_factory: Callable[[], Any]) -> bool:
    """Build and start this replica's engine (runs IN the worker)."""
    global _ENGINE
    if _ENGINE is not None:
        _ENGINE.stop(cancel_active=True)
    _ENGINE = engine_factory()
    _ENGINE.start()
    return True


def _replica_serve(items: List[Tuple[int, Any, int]]) -> List[
        Tuple[int, Any]]:
    """Serve one chunk (runs IN the worker).  Submit EVERY request before
    waiting on any, so the engine joins them into shared decode steps —
    this is where driver-level chunking becomes replica-level continuous
    batching."""
    if _ENGINE is None:
        raise RuntimeError("replica engine not initialized")
    handles = [(rid, _ENGINE.submit(np.asarray(prompt, np.int32), n))
               for rid, prompt, n in items]
    return [(rid, np.asarray(h.result())) for rid, h in handles]


def _is_application_failure(exc: BaseException) -> bool:
    """Failure triage for a chunk dispatch: True when the DISPATCHED
    CODE failed deterministically (fail those requests, keep the replica
    serving), False for infrastructure death (mark the replica down,
    requeue onto survivors).

    Application = a ``RemoteError`` payload, or a typed exception
    ``runtime/wire.py`` rebuilt from a worker-raised payload
    (``remote_typed`` — e.g. an ``ObjectStoreError`` from a stale ref:
    deterministic per request, and requeueing it would cascade a
    poisoned request through every replica).  A ``WorkerWedged`` stays
    infrastructure even when the worker itself raised it."""
    if isinstance(exc, RemoteError):
        return True
    return (getattr(exc, "remote_typed", False)
            and not isinstance(exc, WorkerWedged))


def _replica_stats() -> Dict[str, Any]:
    """Engine metrics snapshot (runs IN the worker)."""
    if _ENGINE is None:
        raise RuntimeError("replica engine not initialized")
    return _ENGINE.stats()


class ServeReplicas:
    """Router over ``num_replicas`` engine replicas with supervision.

    ``engine_factory``: zero-arg callable building a STARTABLE
    ``ServeEngine`` — it executes inside each worker process (ship numpy
    params in the closure; the factory runs after the worker's jax
    initializes).  ``chunk_size``: max requests per dispatch (the
    replica's engine batches the chunk).  ``wedge_timeout_s`` /
    ``heartbeat_s``: watchdog knobs, see runtime/watchdog.py.
    ``max_requeues``: infra-failure retries per request before failing it
    typed.
    """

    def __init__(self, engine_factory: Callable[[], Any],
                 num_replicas: int = 2, *, queue_depth: int = 256,
                 max_total_len: Optional[int] = None,
                 chunk_size: int = 4, max_requeues: int = 2,
                 heartbeat_s: Optional[float] = None,
                 wedge_timeout_s: Optional[float] = None,
                 supervise: bool = True,
                 env_per_worker: Optional[List[Dict[str, str]]] = None,
                 idle_poll_s: float = 0.02):
        envs = [dict(e) for e in (env_per_worker
                                  or [{} for _ in range(num_replicas)])]
        if heartbeat_s is not None:
            for e in envs:
                e.setdefault("RLA_TPU_WORKER_HEARTBEAT_S",
                             str(heartbeat_s))
        self.chunk_size = max(1, chunk_size)
        self.max_requeues = max_requeues
        self.metrics = ServeMetrics()
        self.batcher = AdmissionController(queue_depth=queue_depth,
                                           max_total_len=max_total_len)
        self.metrics.bind_queue(lambda: self.batcher.depth)
        self._idle_poll_s = idle_poll_s
        self._lock = threading.Lock()
        self._down: set = set()
        self._busy: set = set()
        self._next_rank = 0
        self._stop = threading.Event()
        self._engine_factory = engine_factory
        self.pool = ActorPool(num_replicas, env_per_worker=envs)
        try:
            for f in self.pool.execute_all(_replica_init, engine_factory):
                f.result()
            self.watchdog = (self.pool.watch(
                wedge_timeout_s=wedge_timeout_s) if supervise else None)
        except BaseException:
            self.pool.kill()
            raise
        self._dispatcher = threading.Thread(
            target=self._dispatch_loop, daemon=True,
            name="rla-tpu-serve-dispatch")
        self._dispatcher.start()

    # ------------------------------------------------------------------ #
    # Client surface                                                     #
    # ------------------------------------------------------------------ #
    def submit(self, prompt: Any, max_new_tokens: int) -> ServeResponse:
        from .batcher import QueueFull, RequestRejected
        try:
            resp = self.batcher.submit(prompt, max_new_tokens)
        except (QueueFull, RequestRejected):
            # admission rejections only -- shutdown's ServeCancelled must
            # not read as overload
            self.metrics.inc("rejected")
            raise
        self.metrics.inc("submitted")
        return resp

    def stats(self) -> Dict[str, Any]:
        out = self.metrics.snapshot()
        out["replicas"] = len(self.pool)
        with self._lock:
            out["replicas_down"] = sorted(self._down)
        if self.watchdog is not None:
            out["supervision"] = self.watchdog.report()
        return out

    def replica_stats(self, rank: int) -> Dict[str, Any]:
        """A live replica's own engine metrics (proves in-replica
        batching: its ``steps_batch_gt1`` counts shared decode steps)."""
        return self.pool.workers[rank].execute(_replica_stats).result()

    def revive(self, rank: int) -> None:
        """Restart a downed replica and re-initialize its engine."""
        w = self.pool.workers[rank]
        w.restart()
        w.execute(_replica_init, self._engine_factory).result()
        with self._lock:
            self._down.discard(rank)
            self._busy.discard(rank)

    def shutdown(self) -> None:
        self._stop.set()
        self.batcher.kick()
        self._dispatcher.join(timeout=30)
        n = self.batcher.shutdown()
        if n:
            self.metrics.inc("cancelled", n)
        if self.watchdog is not None:
            self.watchdog.stop()
        self.pool.shutdown()

    def __enter__(self) -> "ServeReplicas":
        return self

    def __exit__(self, *exc) -> None:
        self.shutdown()

    # ------------------------------------------------------------------ #
    # Dispatch                                                           #
    # ------------------------------------------------------------------ #
    def _pick_replica(self) -> Optional[int]:
        """Round-robin over live, idle replicas (round-robin spreads load
        so a hang anywhere is actually exercised, not avoided)."""
        n = len(self.pool)
        with self._lock:
            for off in range(n):
                rank = (self._next_rank + off) % n
                if rank in self._down or rank in self._busy:
                    continue
                if not self.pool.workers[rank].is_alive:
                    self._down.add(rank)
                    continue
                self._busy.add(rank)
                self._next_rank = (rank + 1) % n
                return rank
        return None

    def _dispatch_loop(self) -> None:
        while not self._stop.is_set():
            if not self.batcher.wait_for_work(self._idle_poll_s):
                continue
            with self._lock:
                all_down = len(self._down) >= len(self.pool)
            if all_down:
                # no capacity will ever come back on its own: fail the
                # queue typed rather than hang every caller forever
                for req, resp in iter(self.batcher.pop, None):
                    if resp._fail(ServeCancelled(
                            f"request {req.request_id}: every replica is "
                            "down")):
                        self.metrics.inc("failed")
                time.sleep(self._idle_poll_s)
                continue
            rank = self._pick_replica()
            if rank is None:
                time.sleep(self._idle_poll_s)
                continue
            chunk: List[Tuple[ServeRequest, ServeResponse]] = []
            while len(chunk) < self.chunk_size:
                item = self.batcher.pop()
                if item is None:
                    break
                chunk.append(item)
            if not chunk:
                with self._lock:
                    self._busy.discard(rank)
                continue
            items = [(req.request_id, req.prompt, req.max_new_tokens)
                     for req, _ in chunk]
            fut = self.pool.workers[rank].execute(_replica_serve, items)
            fut.add_done_callback(
                lambda f, r=rank, c=chunk: self._on_chunk_done(r, c, f))

    def _on_chunk_done(self, rank: int,
                       chunk: List[Tuple[ServeRequest, ServeResponse]],
                       fut) -> None:
        """Runs on the worker's collector thread: settle or re-queue."""
        with self._lock:
            self._busy.discard(rank)
        exc = fut.exception()
        if exc is None:
            results = dict(fut.result())
            for req, resp in chunk:
                tokens = results.get(req.request_id)
                if tokens is None:
                    self._requeue_or_fail(req, resp, RuntimeError(
                        f"replica {rank} returned no result for request "
                        f"{req.request_id}"))
                elif resp._complete(tokens):
                    self.metrics.inc("completed")
            return
        if _is_application_failure(exc):
            # application failure: deterministic, don't poison survivors
            log.error("replica %d failed a chunk application-side: %s",
                      rank, exc)
            for req, resp in chunk:
                if resp._fail(exc):
                    self.metrics.inc("failed")
            return
        # infra failure: wedged (watchdog reap) or died -- requeue
        with self._lock:
            self._down.add(rank)
        if isinstance(exc, WorkerWedged):
            self.metrics.inc("wedge_events")
        log.warning("replica %d lost mid-chunk (%s); re-queuing %d "
                    "request(s)", rank, type(exc).__name__, len(chunk))
        for req, resp in chunk:
            self._requeue_or_fail(req, resp, exc)

    def _requeue_or_fail(self, req: ServeRequest, resp: ServeResponse,
                         exc: BaseException) -> None:
        if resp.done():
            return
        if req.requeues >= self.max_requeues:
            if resp._fail(exc):
                self.metrics.inc("failed")
            return
        if self.batcher.requeue(req, resp):
            self.metrics.inc("requeued")
