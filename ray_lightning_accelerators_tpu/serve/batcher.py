"""Admission control and backpressure for the serve engine.

A serving system's failure surface must be TYPED: a client that gets a
generic exception cannot tell "shed load and retry later" (``QueueFull``)
from "this request can never be served" (``RequestRejected``) from "the
engine is going away" (``ServeCancelled``).  The admission queue is
bounded — unbounded queues turn overload into unbounded tail latency and
OOM instead of fast rejection.

Storage reuses the runtime's ``TrampolineQueue`` so shutdown rides its
idempotent drain path (runtime/queue.py): ``shutdown()`` drains whatever
is still enqueued and fails each request with ``ServeCancelled`` instead
of executing or silently dropping it.  A requeue lane sits IN FRONT of
the main queue for requests that already cost prefill work on a replica
that wedged — they re-enter at the head, bypass the depth check (they
were admitted once; bouncing them on a full queue would turn an infra
failure into a client-visible loss), and carry a requeue count so retry
loops are bounded.
"""

from __future__ import annotations

import dataclasses
import itertools
import threading
import time
from collections import deque
from concurrent.futures import Future
from typing import Any, List, Optional, Tuple

from ..telemetry.recorder import mint_trace_id

import numpy as np

from ..runtime.queue import TrampolineQueue


class QueueFull(RuntimeError):
    """Admission queue at capacity — backpressure.  Retryable: the caller
    sheds load (the HTTP 429 analog)."""

    def __init__(self, depth: int, limit: int):
        super().__init__(
            f"serve queue full: {depth} queued >= depth cap {limit}; "
            "retry after responses drain")
        self.depth = depth
        self.limit = limit


class PoolExhausted(QueueFull):
    """Block-pool backpressure: admitting this request would overcommit
    the paged KV pool — the admitted-but-unfinished set's worst-case
    block demand already covers the pool.  Retryable after responses
    drain (the 429 analog for cache MEMORY rather than queue slots);
    distinct from ``RequestRejected``, which means the request could
    NEVER fit."""

    def __init__(self, needed: int, outstanding: int, total: int,
                 overcommit: float):
        RuntimeError.__init__(
            self,
            f"serve block pool exhausted: request needs {needed} KV "
            f"blocks but {outstanding} are already committed to admitted "
            f"requests against a pool of {total} blocks "
            f"(overcommit {overcommit:g}); retry after responses drain")
        self.needed = needed
        self.outstanding = outstanding
        self.total = total


class BrownoutShed(QueueFull):
    """Typed brownout: the replica tier is saturated — the queue is past
    the controller's shed watermark and no scale-up headroom remains —
    so the request is shed BEFORE the queue grows to its hard cap
    (serve/controller.py).  Subclasses ``QueueFull`` because the client
    contract is the same 429 analog: shed load, retry after responses
    drain; the distinct type says the tier chose to degrade early
    rather than queue into unbounded tail latency."""

    def __init__(self, depth: int, watermark: int, limit: int):
        RuntimeError.__init__(
            self,
            f"serve tier brownout: {depth} queued >= shed watermark "
            f"{watermark} (hard cap {limit}) with no scale-up headroom; "
            "retry after load drains")
        self.depth = depth
        self.watermark = watermark
        self.limit = limit


def blocks_for_request(prompt_len: int, max_new_tokens: int,
                       block_len: int, headroom: int = 0) -> int:
    """Worst-case KV blocks a request pins: enough to cover every
    position its lifecycle writes — the right-padded prompt bucket
    (``ceil(prompt_len / block_len) * block_len`` positions) and the
    decode feeds up to position ``prompt_len + max_new_tokens - 2``
    (the final token is sampled, never fed).  ``headroom`` extends the
    top position for speculative chunk scoring, which drafts up to
    ``spec_k`` positions past the newest real token."""
    top = max(prompt_len + max_new_tokens - 1 + headroom,
              -(-prompt_len // block_len) * block_len)
    return -(-top // block_len)


def chain_prefix_keys(prompt: Any, block_len: int,
                      limit: Optional[int] = None) -> List[str]:
    """Chain-hashed prefix keys for every FULL block of ``prompt`` —
    key ``j`` commits to tokens ``[0, (j+1)*block_len)``, so equal keys
    imply equal prefixes and a shared block is reusable only when every
    earlier block matched too.

    This is the single definition both sides of prefix routing use: the
    engine's ``BlockAllocator`` prefix index registers these keys
    (serve/engine.py) and the replica tier's affinity router hashes the
    SAME keys to pick the replica whose cache already holds the run
    (serve/controller.py) — computed independently in different
    processes, they must agree byte-for-byte.  ``limit`` caps the number
    of keys for the routing side, which only needs enough of the chain
    to discriminate prefixes, not a digest of a 512k-token prompt."""
    import hashlib

    prompt = np.asarray(prompt, np.int32).reshape(-1)
    n_full = int(prompt.size) // block_len
    if limit is not None:
        n_full = min(n_full, limit)
    h = hashlib.blake2b(digest_size=16)
    keys: List[str] = []
    for j in range(n_full):
        h.update(prompt[j * block_len:(j + 1) * block_len].tobytes())
        keys.append(h.hexdigest())
    return keys


class RequestRejected(ValueError):
    """The request can never be served by this engine (empty prompt, non
    positive budget, prompt + budget past the cache length).  Not
    retryable as-is: the client must change the request."""


class ServeCancelled(RuntimeError):
    """Typed cancellation: the engine shut down (or lost every replica)
    with the request still queued or in flight.  The request was NOT
    served; re-submission to a live engine is safe."""


@dataclasses.dataclass
class ServeRequest:
    """One admitted generation request."""

    request_id: int
    prompt: np.ndarray          # [prompt_len] int32
    max_new_tokens: int
    t_submit: float             # monotonic, stamped at admission
    requeues: int = 0           # infra-failure re-admissions so far
    # retry backoff (serve/controller.py): a requeued request is not
    # dispatchable before this monotonic instant.  The requeue LANE
    # holds its head until then — a retried request keeps its place in
    # front of new admissions instead of losing it to the backoff
    not_before: float = 0.0
    # absolute SLO deadline (monotonic; serve/slo.py), stamped ONCE at
    # admission when the controller carries a policy with deadline_s.
    # It rides the request object through requeue and replica
    # re-dispatch, so an infra retry never resets the client's clock;
    # the engine sheds expired requests typed BEFORE prefill
    deadline: Optional[float] = None
    # per-request trace id (telemetry/recorder.py): stamped at admission
    # so every flight-recorder event of this request's lifecycle
    # (admit -> prefill -> decode -> respond) correlates — across
    # replicas too, since the id travels with the request on requeue
    trace_id: Optional[str] = None
    # speculative-lane HINT: an idle engine with a draft model routes
    # this request through greedy speculative decode (same exactness
    # contract, so the response is indistinguishable); a busy engine
    # decodes it in a normal slot
    speculative: bool = False
    # paged admission: worst-case KV blocks this request pins, stamped by
    # the controller so engine placement and controller accounting can
    # never disagree (0 = dense engine, no pool accounting)
    blocks_reserved: int = 0
    # disaggregated lanes (serve/replicas.py): an export request runs
    # prefill ONLY and resolves with a KV handoff descriptor instead of
    # tokens; an import request carries the descriptor of a prefill done
    # elsewhere and starts life mid-decode.  Both preserve the original
    # t_submit/deadline/trace_id stamps, so the client's SLO clock and
    # trace survive the lane hop
    export_handoff: bool = False
    import_handoff: Optional[Any] = None


class ServeResponse:
    """Caller-side handle for a submitted request.

    ``result(timeout)`` blocks for the full token sequence
    (prompt + generated, [total] int32 numpy) or raises the typed
    failure.  ``ttft_s`` is filled when the first token is produced.
    Completion is exactly-once: the first ``_complete``/``_fail`` wins,
    later ones report False — the replicas layer relies on this to
    guarantee a re-queued request is never answered twice."""

    def __init__(self, request: ServeRequest):
        self.request = request
        self.ttft_s: Optional[float] = None
        self._fut: Future = Future()

    def result(self, timeout: Optional[float] = None) -> np.ndarray:
        return self._fut.result(timeout)

    def done(self) -> bool:
        return self._fut.done()

    def exception(self, timeout: Optional[float] = None):
        return self._fut.exception(timeout)

    # -- engine side ---------------------------------------------------- #
    def _complete(self, tokens: np.ndarray) -> bool:
        if self._fut.done():
            return False
        self._fut.set_result(tokens)
        return True

    def _fail(self, exc: BaseException) -> bool:
        if self._fut.done():
            return False
        self._fut.set_exception(exc)
        return True


class AdmissionController:
    """Bounded, typed admission in front of an engine (or replica group).

    ``queue_depth``: cap on requests queued but not yet decoding — the
    backpressure knob.  ``max_total_len``: per-request budget check
    (prompt + max_new_tokens must fit the dense decode cache).
    ``max_new_tokens_cap``: optional per-request generation budget cap.

    **Paged mode** (``block_len`` set): admission is judged against the
    BLOCK POOL, not ``max_total_len`` — a request is rejected typed only
    when its worst-case block demand can never fit (more blocks than the
    per-slot table or the whole pool holds, both named in the error),
    and ``PoolExhausted`` backpressure fires when the admitted-but-
    unfinished set's demand would overcommit the pool past
    ``pool_overcommit`` (prefix sharing makes real usage lower than the
    worst case, which is what the overcommit knob trades on).
    """

    def __init__(self, queue_depth: int = 64,
                 max_total_len: Optional[int] = None,
                 max_new_tokens_cap: Optional[int] = None,
                 block_len: Optional[int] = None,
                 pool_blocks: Optional[int] = None,
                 max_blocks_per_slot: Optional[int] = None,
                 spec_headroom: int = 0,
                 pool_overcommit: float = 1.0,
                 hard_total_cap: Optional[int] = None,
                 slo_policy: Any = None):
        if queue_depth < 1:
            raise ValueError("queue_depth must be >= 1")
        if block_len is not None and (pool_blocks is None
                                      or max_blocks_per_slot is None):
            raise ValueError("paged admission needs block_len, "
                             "pool_blocks AND max_blocks_per_slot")
        self.queue_depth = queue_depth
        self.max_total_len = max_total_len
        self.max_new_tokens_cap = max_new_tokens_cap
        self.block_len = block_len
        self.pool_blocks = pool_blocks
        self.max_blocks_per_slot = max_blocks_per_slot
        self.spec_headroom = spec_headroom
        self.pool_overcommit = pool_overcommit
        # the MODEL's physical ceiling (max_seq_len): block rounding may
        # grant a table more positions than max_total_len, but no cache
        # layout can serve positions the model was never shaped for —
        # and generate() refuses them, so the exactness contract
        # requires the engine to refuse them too
        self.hard_total_cap = hard_total_cap
        # serve/slo.py SloPolicy: admission stamps each request's
        # absolute deadline from it (None = no SLO attached)
        self.slo_policy = slo_policy
        self._q = TrampolineQueue()
        self._requeue: deque = deque()
        self._cond = threading.Condition()
        self._depth = 0
        self._outstanding_blocks = 0
        self._closed = False
        self._ids = itertools.count()

    # ------------------------------------------------------------------ #
    @property
    def depth(self) -> int:
        return self._depth

    @property
    def closed(self) -> bool:
        return self._closed

    def submit(self, prompt: Any, max_new_tokens: int,
               speculative: bool = False, *,
               export_handoff: bool = False,
               import_handoff: Optional[Any] = None,
               t_submit: Optional[float] = None,
               deadline: Optional[float] = None,
               trace_id: Optional[str] = None) -> ServeResponse:
        """Admit a request or raise typed: ``RequestRejected`` (can never
        be served), ``QueueFull``/``PoolExhausted`` (backpressure),
        ``ServeCancelled`` (controller shut down).

        Lane handoff (serve/replicas.py): ``export_handoff`` admits a
        prefill-only request — its block reservation covers the PROMPT
        bucket alone, never decode growth this engine will not run.  An
        ``import_handoff`` request bypasses the depth cap like a requeue
        (it was admitted once at the tier and already cost a prefill);
        its pool check still applies, it is real memory here.  The
        ``t_submit``/``deadline``/``trace_id`` overrides carry the
        ORIGINAL stamps across the hop so a handoff never resets the
        client's SLO clock or breaks its trace."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size < 1:
            raise RequestRejected("empty prompt")
        if max_new_tokens < 1:
            raise RequestRejected(
                f"max_new_tokens must be >= 1, got {max_new_tokens}")
        if self.max_new_tokens_cap is not None \
                and max_new_tokens > self.max_new_tokens_cap:
            raise RequestRejected(
                f"max_new_tokens {max_new_tokens} exceeds the engine cap "
                f"{self.max_new_tokens_cap}")
        needed = 0
        if self.block_len is not None:
            if self.hard_total_cap is not None \
                    and prompt.size + max_new_tokens > self.hard_total_cap:
                raise RequestRejected(
                    f"prompt ({prompt.size}) + max_new_tokens "
                    f"({max_new_tokens}) exceeds the model's max_seq_len "
                    f"{self.hard_total_cap} (generate() refuses the same "
                    "request; block rounding cannot grant positions the "
                    "model was never shaped for)")
            # paged admission: judge against the pool's budgets, never a
            # dense per-slot length the paging indirection made obsolete.
            # A chunked-prefill engine passes a max_blocks_per_slot that
            # spans the MODEL's max_seq_len (its cursor streams prompts
            # longer than any single prefill bucket), so only the hard
            # cap above and the pool below can refuse a long prompt.
            needed = blocks_for_request(
                int(prompt.size),
                1 if export_handoff else int(max_new_tokens),
                self.block_len,
                self.spec_headroom if speculative else 0)
            if needed > self.max_blocks_per_slot \
                    or needed > self.pool_blocks:
                raise RequestRejected(
                    f"prompt ({prompt.size}) + max_new_tokens "
                    f"({max_new_tokens}) needs {needed} KV blocks of "
                    f"{self.block_len} tokens, exceeding the per-slot "
                    f"block-table budget ({self.max_blocks_per_slot} "
                    f"blocks = {self.max_blocks_per_slot * self.block_len}"
                    f" tokens) or the whole pool "
                    f"({self.pool_blocks} blocks)")
        elif self.max_total_len is not None \
                and prompt.size + max_new_tokens > self.max_total_len:
            raise RequestRejected(
                f"prompt ({prompt.size}) + max_new_tokens "
                f"({max_new_tokens}) exceeds the decode budget "
                f"{self.max_total_len}")
        with self._cond:
            if self._closed:
                raise ServeCancelled("serve queue is shut down")
            if self._depth >= self.queue_depth \
                    and import_handoff is None:
                raise QueueFull(self._depth, self.queue_depth)
            if self.block_len is not None and \
                    self._outstanding_blocks + needed > \
                    self.pool_overcommit * self.pool_blocks:
                raise PoolExhausted(needed, self._outstanding_blocks,
                                    self.pool_blocks,
                                    self.pool_overcommit)
            req = ServeRequest(next(self._ids), prompt,
                               int(max_new_tokens),
                               (time.monotonic() if t_submit is None
                                else float(t_submit)),
                               trace_id=(trace_id if trace_id is not None
                                         else mint_trace_id()),
                               speculative=bool(speculative),
                               blocks_reserved=needed,
                               export_handoff=bool(export_handoff),
                               import_handoff=import_handoff)
            if deadline is not None:
                req.deadline = float(deadline)
            elif self.slo_policy is not None \
                    and self.slo_policy.deadline_s is not None:
                req.deadline = req.t_submit + self.slo_policy.deadline_s
            self._outstanding_blocks += needed
            resp = ServeResponse(req)
            self._q.put((req, resp))
            self._depth += 1
            self._cond.notify_all()
        return resp

    def release_blocks(self, req: ServeRequest) -> None:
        """Return a finished/failed request's worst-case block
        reservation to the admission budget (exactly once per admitted
        request; the engine calls this wherever the response resolves).
        No-op for dense controllers."""
        if req.blocks_reserved <= 0:
            return
        with self._cond:
            self._outstanding_blocks = max(
                0, self._outstanding_blocks - req.blocks_reserved)
            req.blocks_reserved = 0
            self._cond.notify_all()

    def push_front(self, item: Tuple[ServeRequest, ServeResponse]) -> None:
        """Head-of-line put-back for FLOW CONTROL (the pool cannot place
        the popped request right now).  Unlike ``requeue`` this is not an
        infra failure: no requeue count, FIFO order preserved."""
        with self._cond:
            if self._closed:
                item[1]._fail(ServeCancelled(
                    f"request {item[0].request_id} cancelled: engine "
                    "shut down while it awaited pool capacity"))
                return
            self._requeue.appendleft(item)
            self._depth += 1
            self._cond.notify_all()

    def requeue(self, req: ServeRequest, resp: ServeResponse,
                delay_s: float = 0.0) -> bool:
        """Head-of-line re-admission after an infra failure (replica
        wedged/died mid-chunk).  Bypasses the depth cap — the request was
        already admitted once.  ``delay_s`` stamps a retry backoff
        (``not_before``): the lane holds until it expires, so the retry
        keeps its head-of-line position while still backing off.
        Returns False (and fails the response typed) when the controller
        is already closed."""
        with self._cond:
            if not self._closed:
                req.requeues += 1
                req.not_before = (time.monotonic() + delay_s
                                  if delay_s > 0 else 0.0)
                self._requeue.append((req, resp))
                self._depth += 1
                self._cond.notify_all()
                return True
        resp._fail(ServeCancelled(
            f"request {req.request_id} cancelled: engine shut down while "
            "it awaited re-dispatch"))
        return False

    def pop(self) -> Optional[Tuple[ServeRequest, ServeResponse]]:
        """Next request or None.  The requeue lane drains first; a lane
        head still inside its retry backoff HOLDS the lane (returns
        None) — a requeued request must re-dispatch before anything
        newly admitted, so the backoff must not let later arrivals
        overtake it."""
        with self._cond:
            if self._requeue:
                if self._requeue[0][0].not_before > time.monotonic():
                    return None
                self._depth -= 1
                return self._requeue.popleft()
            item = self._q.get_nowait()
            if item is not None:
                self._depth -= 1
            return item

    def wait_for_work(self, timeout: float) -> bool:
        """Block up to ``timeout`` for queued work (or closure); True when
        work is available.  Event-driven idle — the engine loop must not
        spin."""
        with self._cond:
            if self._depth == 0 and not self._closed:
                self._cond.wait(timeout)
            return self._depth > 0

    def kick(self) -> None:
        """Wake anything blocked in ``wait_for_work`` (engine stop path)."""
        with self._cond:
            self._cond.notify_all()

    def shutdown(self) -> int:
        """Idempotent: close admission and cancel everything still queued
        with ``ServeCancelled`` (riding ``TrampolineQueue.shutdown``'s
        drain).  Returns the number of cancelled requests."""
        with self._cond:
            self._closed = True
            drained: List[Tuple[ServeRequest, ServeResponse]] = \
                list(self._q.shutdown())
            drained.extend(self._requeue)
            self._requeue.clear()
            self._depth = 0
            self._outstanding_blocks = 0
            self._cond.notify_all()
        n = 0
        for req, resp in drained:
            if resp._fail(ServeCancelled(
                    f"request {req.request_id} cancelled: engine shut "
                    "down with it still queued")):
                n += 1
        return n
