"""Serving metrics: throughput, queue depth, and tail latency.

Serving is judged on its tail — a p50 dashboard hides the requests users
actually complain about — so every latency family reports
p50/p95/p99/max from ``utils.profiler``'s reservoir percentiles (the
exact max survives reservoir eviction).  Three latency families:

- **ttft** (time to first token): submit -> first token produced.  In a
  continuous-batching engine this includes queue wait, so it IS the
  admission/backpressure signal.
- **queue_wait**: submit -> slot-join (the moment prefill starts).
  ``ttft = queue_wait + prefill`` by construction, so the timeline
  splits queueing from compute — a fat queue_wait p99 says "add
  replicas / shed load" where a fat prefill p99 says "the model is
  slow", which is the serve-tier autoscaling signal.
- **token_latency**: gap between a request's consecutive tokens.  Under
  continuous batching this tracks the shared step time — it degrades
  gracefully as the batch fills, which is the throughput/latency trade
  the engine exists to make.
- **decode_step** / **prefill**: engine-internal phase timings.

Counters are exactly-once by construction (incremented where the
corresponding transition happens, guarded by the response's
first-completion-wins contract), so ``completed + failed + cancelled``
accounts for every admitted request — the no-lost-no-duplicated
invariant the replica layer is tested against.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, Optional

from ..utils.profiler import Profiler


class ServeMetrics:
    """Counters + latency reservoirs for one engine (or replica group)."""

    TTFT = "serve/ttft"
    TOKEN = "serve/token_latency"
    STEP = "serve/decode_step"
    PREFILL = "serve/prefill"
    QUEUE = "serve/queue_wait"

    _COUNTERS = ("submitted", "completed", "failed", "cancelled",
                 "rejected", "requeued", "prefills", "tokens_generated",
                 "steps", "steps_batch_gt1", "wedge_events",
                 "pool_exhausted", "prefix_lookups", "prefix_hits",
                 "prefix_hit_blocks", "speculative_requests",
                 "speculative_rounds", "speculative_tokens_accepted",
                 "slo_violations", "slo_deadline_shed",
                 # replica-tier resilience (serve/controller.py):
                 # hedged = speculative re-dispatches of a slow
                 # replica's oldest in-flight chunk; hedge_wins = the
                 # hedge copy answered first; brownout_shed = typed
                 # BrownoutShed rejections at the saturation watermark;
                 # revived = circuit-breaker replica revivals;
                 # scale_ups/scale_downs = autoscale replica count moves
                 "hedged", "hedge_wins", "brownout_shed", "revived",
                 "scale_ups", "scale_downs",
                 # prefix-affinity routing + disaggregated lanes
                 # (serve/controller.py, serve/replicas.py):
                 # prefix_route_hits/misses = tier route decisions that
                 # did/didn't land a request on a replica with its
                 # prefix run resident (hedges count as misses);
                 # kv_handoffs = prefill->decode block handoffs
                 # completed; kv_handoff_bytes = KV bytes those
                 # handoffs shipped through the object store
                 "prefix_route_hits", "prefix_route_misses",
                 "kv_handoffs", "kv_handoff_bytes",
                 # chunked long-prompt prefill (serve/engine.py): one
                 # increment per decode_chunk_paged call a streaming
                 # prefill cursor advances (whole-prompt prefills count 1)
                 "prefill_chunks",
                 # numeric guard (runtime/guardian.py): decode steps
                 # whose logits came back non-finite for a slot — that
                 # request fails typed (NumericAnomaly) and also counts
                 # under "failed"
                 "numeric_anomalies")

    # pool/HBM fields are GAUGES (live values, not monotone counters);
    # telemetry/registry.py keys its Prometheus type choice off this set
    POOL_GAUGES = ("block_pool_total", "block_pool_used",
                   "block_pool_cached", "block_pool_free",
                   "block_pool_occupancy", "block_len",
                   "hbm_cache_bytes", "hbm_used_bytes",
                   "dense_equivalent_bytes", "cache_waste_ratio",
                   "peak_used_blocks", "peak_concurrent")

    # SLO fields (serve/slo.py SloTracker.gauges) are gauges too: the
    # burn rate is a live level an autoscaler reads, never a counter
    SLO_GAUGES = ("slo_burn_rate", "slo_window_observations")

    # disaggregated-lane occupancy (serve/controller.py lane_gauges):
    # live per-lane replica counts and in-flight requests — levels,
    # not tallies, so the registry must type them gauge
    LANE_GAUGES = ("lane_prefill_replicas", "lane_decode_replicas",
                   "lane_prefill_inflight", "lane_decode_inflight")

    # chunked-prefill occupancy: active_long_prefills is the live count
    # of slots whose prompt is still streaming in (engine bind), and
    # longest_prefill_tokens is the high-watermark prompt length ever
    # admitted — levels, not tallies, so the registry types them gauge
    CHUNK_GAUGES = ("active_long_prefills", "longest_prefill_tokens")

    def __init__(self, profiler: Optional[Profiler] = None):
        self.profiler = profiler or Profiler()
        self._lock = threading.Lock()
        self._c: Dict[str, int] = {k: 0 for k in self._COUNTERS}
        self._max_batch = 0
        self._peak_used_blocks = 0
        self._peak_concurrent = 0
        self._t_first: Optional[float] = None
        self._t_last: Optional[float] = None
        self._longest_prefill = 0
        self._queue_depth: Callable[[], int] = lambda: 0
        self._pool_gauges: Optional[Callable[[], Dict[str, Any]]] = None
        self._slo_gauges: Optional[Callable[[], Dict[str, Any]]] = None
        self._lane_gauges: Optional[Callable[[], Dict[str, Any]]] = None
        self._chunk_gauges: Optional[Callable[[], Dict[str, Any]]] = None

    # ------------------------------------------------------------------ #
    def bind_queue(self, depth_fn: Callable[[], int]) -> None:
        """Wire the live queue-depth gauge (the batcher owns the number)."""
        self._queue_depth = depth_fn

    def bind_pool(self, gauges_fn: Callable[[], Dict[str, Any]]) -> None:
        """Wire the paged engine's live block-pool gauges: a callable
        returning flat numeric fields (``block_pool_*`` occupancy,
        ``hbm_cache_bytes``, ``dense_equivalent_bytes``,
        ``cache_waste_ratio``) merged into every snapshot.  Dense
        engines never bind, and the fields stay absent."""
        self._pool_gauges = gauges_fn

    def bind_slo(self, gauges_fn: Callable[[], Dict[str, Any]]) -> None:
        """Wire the live SLO gauges (serve/slo.py
        ``SloTracker.gauges``): ``slo_burn_rate`` + window size merged
        into every snapshot.  Engines without an SLO policy never bind,
        and the fields stay absent."""
        self._slo_gauges = gauges_fn

    def bind_lanes(self, gauges_fn: Callable[[], Dict[str, Any]]) -> None:
        """Wire the live per-lane occupancy gauges
        (serve/controller.py ``ReplicaController.lane_gauges``).
        Merged outside the metrics lock like every bound gauge source,
        so the controller's own lock never nests inside this one."""
        self._lane_gauges = gauges_fn

    def bind_chunks(self, gauges_fn: Callable[[], Dict[str, Any]]) -> None:
        """Wire the live chunked-prefill occupancy gauge
        (``active_long_prefills`` — the engine owns the cursor list).
        Merged outside the metrics lock like every bound gauge source."""
        self._chunk_gauges = gauges_fn

    def observe_long_prefill(self, prompt_tokens: int) -> None:
        """Record an admitted prompt length; the snapshot keeps the
        high-watermark (``longest_prefill_tokens``) so probes can prove
        a long-context request actually streamed through."""
        with self._lock:
            self._longest_prefill = max(self._longest_prefill,
                                        int(prompt_tokens))

    def observe_pool(self, used_blocks: int, concurrent: int) -> None:
        """Record a pool-occupancy observation (engine calls at every
        admit/retire): high-watermarks survive in the snapshot so probes
        can report PEAK placed sequences/blocks, not just the final
        drained state."""
        with self._lock:
            self._peak_used_blocks = max(self._peak_used_blocks,
                                         used_blocks)
            self._peak_concurrent = max(self._peak_concurrent, concurrent)

    # Lock discipline (live-scrape consistency): every observe_* holds
    # self._lock around BOTH its reservoir write (profiler.observe) and
    # its counter/busy-window updates, and snapshot() reads the
    # profiler summary under the SAME lock — so a concurrent scrape can
    # never see a reservoir that advanced without its counter (or vice
    # versa).  Ordering is always ServeMetrics._lock -> Profiler._lock,
    # never the reverse, so the nesting cannot deadlock.
    def inc(self, name: str, n: int = 1) -> None:
        with self._lock:
            self._c[name] = self._c.get(name, 0) + n

    def observe_ttft(self, dt_s: float) -> None:
        with self._lock:
            self.profiler.observe(self.TTFT, dt_s)

    def observe_queue_wait(self, dt_s: float) -> None:
        """Admission -> slot-join wait (recorded the moment the engine
        starts the request's prefill)."""
        with self._lock:
            self.profiler.observe(self.QUEUE, dt_s)

    def observe_token_latency(self, dt_s: float) -> None:
        with self._lock:
            self.profiler.observe(self.TOKEN, dt_s)

    def observe_prefill(self, dt_s: float) -> None:
        """One admission prefill.  Counts the request's FIRST served token
        (prefill produces it) and extends the busy window, so
        throughput/tokens stay honest even for max_new_tokens=1 loads."""
        now = time.monotonic()
        with self._lock:
            self.profiler.observe(self.PREFILL, dt_s)
            self._c["prefills"] += 1
            self._c["tokens_generated"] += 1
            if self._t_first is None:
                self._t_first = now - dt_s
            self._t_last = now

    def observe_spec_round(self, dt_s: float, tokens: int) -> None:
        """One speculative draft/verify round that emitted ``tokens``
        accepted+corrected tokens in one target pass: extends the busy
        window and the token count (throughput stays honest), counted
        under ``speculative_rounds`` rather than ``steps``."""
        now = time.monotonic()
        with self._lock:
            self.profiler.observe(self.STEP, dt_s)
            self._c["speculative_rounds"] += 1
            self._c["tokens_generated"] += tokens
            if self._t_first is None:
                self._t_first = now - dt_s
            self._t_last = now

    def observe_step(self, dt_s: float, active: int) -> None:
        """One continuous-batching decode step over ``active`` live slots
        (inactive slots ride along at static shape; they are compute, not
        service)."""
        now = time.monotonic()
        with self._lock:
            self.profiler.observe(self.STEP, dt_s)
            self._c["steps"] += 1
            if active > 1:
                self._c["steps_batch_gt1"] += 1
            self._c["tokens_generated"] += active
            self._max_batch = max(self._max_batch, active)
            if self._t_first is None:
                self._t_first = now - dt_s
            self._t_last = now

    # ------------------------------------------------------------------ #
    def snapshot(self) -> Dict[str, Any]:
        """Machine-readable report (bench-honesty style: flat, JSON-able).

        ``throughput_tok_s`` divides generated tokens by the busy window
        (first step start -> last step end), not process lifetime — an
        idle engine must not look slow.

        The whole read happens under the metrics lock (see the lock
        discipline note above the observers): a live ``/metrics`` scrape
        racing concurrent ``observe_*`` calls gets ONE consistent view —
        reservoir counts and their paired counters can never tear."""

        def pct(name: str) -> Optional[Dict[str, float]]:
            row = s.get(name)
            if row is None:
                return None
            return {k: row[k] for k in ("count", "mean_s", "p50_s",
                                        "p95_s", "p99_s", "max_s")}

        with self._lock:
            s = self.profiler.summary()
            counters = dict(self._c)
            max_batch = self._max_batch
            peak_used = self._peak_used_blocks
            peak_conc = self._peak_concurrent
            longest_prefill = self._longest_prefill
            busy_s = ((self._t_last - self._t_first)
                      if self._t_first is not None
                      and self._t_last is not None else 0.0)
        out: Dict[str, Any] = dict(counters)
        out["max_batch"] = max_batch
        out["queue_depth"] = self._queue_depth()
        out["busy_s"] = busy_s
        if self._pool_gauges is not None:
            out.update(self._pool_gauges())
            out["peak_used_blocks"] = peak_used
            out["peak_concurrent"] = peak_conc
        if self._slo_gauges is not None:
            out.update(self._slo_gauges())
        if self._lane_gauges is not None:
            out.update(self._lane_gauges())
        if self._chunk_gauges is not None:
            out.update(self._chunk_gauges())
            out["longest_prefill_tokens"] = longest_prefill
        out["throughput_tok_s"] = (
            counters["tokens_generated"] / busy_s if busy_s > 0 else 0.0)
        out["ttft_s"] = pct(self.TTFT)
        out["queue_wait_s"] = pct(self.QUEUE)
        out["token_latency_s"] = pct(self.TOKEN)
        out["decode_step_s"] = pct(self.STEP)
        out["prefill_s"] = pct(self.PREFILL)
        return out

    def reset(self) -> None:
        """Clear EVERY accumulated structure — counters, max-batch
        watermark, busy window, and the latency reservoirs (the owned
        profiler resets too; callers sharing a profiler across engines
        accept that its other families clear with it).  Probes reset
        after warmup so the measured window starts from zero; the reset
        test pins that no field is missed (PR 3/PR 4 each shipped a
        reset that forgot one)."""
        with self._lock:
            self._c = {k: 0 for k in self._COUNTERS}
            self._max_batch = 0
            self._peak_used_blocks = 0
            self._peak_concurrent = 0
            self._longest_prefill = 0
            self._t_first = None
            self._t_last = None
            self.profiler.reset()

    def describe(self) -> str:
        """Human-readable snapshot + the profiler's latency table."""
        snap = self.snapshot()
        head = ", ".join(
            f"{k}={snap[k]}" for k in
            ("submitted", "completed", "failed", "cancelled", "rejected",
             "requeued", "steps", "steps_batch_gt1", "max_batch",
             "queue_depth"))
        tput = f"throughput={snap['throughput_tok_s']:.1f} tok/s"
        return f"{head}, {tput}\n{self.profiler.describe()}"
