"""Trainer: owns the fit/validate/test/predict loops, TPU-first.

The reference leaned on PTL 1.1.7's Trainer and only swapped the process
launcher (reference: ray_lightning/ray_ddp.py:218-219 calls
``super().ddp_train``).  Here the loop itself is part of the framework, and
it is designed around XLA's compilation model:

- the train step is **traced once** and jit-compiled with explicit
  in/out shardings over the accelerator's mesh; gradient all-reduce is
  emitted by XLA from the batch sharding (no DDP wrapper, no process group);
- the step donates its input state, so params/optimizer state live on-device
  for the whole run (no host round-trips per step);
- metrics stay device arrays; they are materialized only at log/validation
  boundaries (the discipline SURVEY.md flags at tune.py:85's ``.item()``);
- epoch/step bookkeeping is host-side Python *around* the jitted step --
  never inside it.

Observable behaviors pinned by the reference's tests and reproduced here:
weight re-hydration into the user's module after fit
(reference: ray_lightning/ray_ddp.py:185-189), `callback_metrics` bridging
(reference: ray_lightning/tune.py:82-95), sampler injection
(reference: ray_lightning/ray_ddp.py:280-295), checkpoint round-trips
(reference: ray_lightning/tests/utils.py:129-134), fit/test callable multiple
times from one script (reference: README.md:34-36).
"""

from __future__ import annotations

import copy
import dataclasses
import itertools
import os
import time
from contextlib import contextmanager
from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ..accelerators.base import Accelerator
from ..accelerators.tpu import RayTPUAccelerator
from ..analysis import knobs
from ..data import prefetch as prefetch_lib
from ..data.loader import DataLoader
from ..parallel import mesh as mesh_lib
from ..telemetry import live as live_lib
from ..telemetry import recorder as telemetry
from ..utils import checkpoint as ckpt_lib
from ..utils.logging import CSVLogger, InMemoryLogger, Logger, log
from ..utils.profiler import Profiler
from ..utils.seed import rng_from_seed, seed_everything
from .callbacks import Callback, ModelCheckpoint
from .module import TpuModule
from .state import TrainState

_PRECISION_DTYPES = {
    "bf16": jnp.bfloat16, "bf16-mixed": jnp.bfloat16,
    "f32": jnp.float32, "32": jnp.float32, 32: jnp.float32,
}


class Trainer:
    def __init__(self,
                 max_epochs: Optional[int] = None,
                 max_steps: Optional[int] = None,
                 max_time: Optional[float] = None,
                 accelerator: Optional[Accelerator] = None,
                 callbacks: Optional[Sequence[Callback]] = None,
                 logger: Optional[Logger] = None,
                 default_root_dir: Optional[str] = None,
                 limit_train_batches: Optional[int] = None,
                 limit_val_batches: Optional[int] = None,
                 check_val_every_n_epoch: int = 1,
                 val_check_interval: Optional[int] = None,
                 log_every_n_steps: int = 50,
                 precision: Any = "bf16",
                 accumulate_grad_batches: int = 1,
                 gradient_clip_val: Optional[float] = None,
                 log_grad_norm: bool = False,
                 ema_decay: Optional[float] = None,
                 ema_eval: bool = False,
                 enable_checkpointing: bool = True,
                 checkpoint_format: str = "pickle",
                 num_sanity_val_steps: int = 0,
                 enable_progress_bar: bool = False,
                 profiler: Optional["Profiler"] = None,
                 perf_observatory: Any = None,
                 cache_dataset_on_device: Any = "auto",
                 prefetch_batches: int = 2,
                 worker_deadline_s: Optional[float] = None,
                 grad_compression: Optional[str] = None,
                 shard_optimizer_state: bool = False,
                 gather_mode: str = "tree",
                 int8_matmul: bool = False,
                 pipeline_stages: int = 1,
                 pipeline_schedule: str = "1f1b",
                 pipeline_microbatches: int = 4,
                 seq_parallel: int = 1,
                 seq_parallel_mode: Optional[str] = None,
                 guard: Any = "auto",
                 seed: Optional[int] = None):
        if max_epochs is None and max_steps is None:
            max_epochs = 1000
        self.max_epochs = max_epochs
        self.max_steps = max_steps
        # wall-clock budget in seconds; checked at step boundaries so the
        # run ends on a clean step (preemptible/budgeted TPU reservations)
        self.max_time = max_time
        self.accelerator = accelerator or RayTPUAccelerator()
        self.callbacks: List[Callback] = list(callbacks or [])
        self.default_root_dir = default_root_dir or os.path.join(
            os.getcwd(), "rla_tpu_logs")
        self.logger = logger if logger is not None else InMemoryLogger()
        self.limit_train_batches = limit_train_batches
        self.limit_val_batches = limit_val_batches
        self.check_val_every_n_epoch = max(1, check_val_every_n_epoch)
        # mid-epoch validation every N optimizer steps (long-epoch/LM runs
        # where an epoch is too coarse a cadence); epoch-boundary validation
        # still runs per check_val_every_n_epoch
        self.val_check_interval = val_check_interval
        self.log_every_n_steps = log_every_n_steps
        self.precision = precision
        if precision not in _PRECISION_DTYPES:
            raise ValueError(
                f"unsupported precision {precision!r}; choose from "
                f"{sorted(str(k) for k in _PRECISION_DTYPES)}")
        self.compute_dtype = _PRECISION_DTYPES[precision]
        self.accumulate_grad_batches = max(1, accumulate_grad_batches)
        self.gradient_clip_val = gradient_clip_val
        # adds a "grad_norm" metric computed inside the jitted step (one
        # fused reduction, no host sync -- the XLA-honest way to watch for
        # divergence/clipping pressure).  Semantics under
        # accumulate_grad_batches > 1: the logged value is the
        # MICRO-BATCH gradient norm of each step (the grads handed to the
        # accumulator), NOT the accumulated-window norm -- per-step
        # divergence shows up immediately instead of once per window.
        # Under grad_compression the local grads never globalize outside
        # the exchange, so the metric is sqrt(mean over replicas of
        # ||local micro-grad||^2): an upper bound on the true global
        # micro-batch norm, equal to it when replicas agree.
        self.log_grad_norm = log_grad_norm
        # EMA of params, tracked inside the jitted step as optimizer state
        # (utils/ema.py); ema_eval runs validation/test on the averaged
        # weights (the deployment weights) instead of the raw ones
        if ema_decay is not None and not (0.0 < ema_decay < 1.0):
            raise ValueError(
                f"ema_decay must be in (0, 1), got {ema_decay}")
        self.ema_decay = ema_decay
        self.ema_eval = ema_eval
        if ema_eval and ema_decay is None:
            raise ValueError("ema_eval=True requires ema_decay")
        self.enable_checkpointing = enable_checkpointing
        # "pickle": single-file, rank-0 host gather (reference-shaped).
        # "sharded": every process writes its own shards (orbax; scales to
        # pods).  "sharded-async": same, committed by a background thread.
        if checkpoint_format not in ("pickle", "sharded", "sharded-async"):
            raise ValueError(f"unknown checkpoint_format {checkpoint_format!r}")
        self.checkpoint_format = checkpoint_format
        self.num_sanity_val_steps = num_sanity_val_steps
        self.enable_progress_bar = enable_progress_bar
        self.profiler = profiler
        # perf observatory (telemetry/perf.py): True builds one, or pass
        # a PerfObservatory.  The fit loop brackets every optimizer step
        # for the phase timeline (h2d / compile / compute / ckpt /
        # drain, remainder surfaced as `other`), registers the state's
        # HBM pools (params / opt_state / exchange buffers / device
        # cache) on the ledger, and samples watermarks off the hot path
        # (throttled by RLA_TPU_PERF_HBM_SAMPLE_S).  Exported through
        # build_metrics_registry() -> JSON + Prometheus + run_report.
        if perf_observatory is True:
            from ..telemetry.perf import PerfObservatory
            perf_observatory = PerfObservatory()
        self.perf = perf_observatory or None
        # device-resident dataset cache: "auto" caches array-backed datasets
        # up to _CACHE_MAX_BYTES; True forces (when eligible), False disables
        self.cache_dataset_on_device = cache_dataset_on_device
        # async input pipeline (data/prefetch.py): host iteration + collate
        # run on a background thread and the next N batches are eagerly
        # device-placed, so step k's dispatch never waits on batch k's
        # collate or H2D transfer.  0 = fully synchronous hot loop.  Batch
        # order, tail-batch semantics, and every early-stop break are
        # preserved exactly — the loss trajectory is bit-identical to
        # prefetch_batches=0 (test-asserted).  Composes with
        # grad_compression (host/H2D overlap is orthogonal to the gradient
        # wire format) and the watchdog (heartbeats come from the worker
        # dispatch loop, not the input thread); the device-cache scan path
        # has no per-step host work, so prefetch is a no-op there.
        if not isinstance(prefetch_batches, int) or prefetch_batches < 0:
            raise ValueError(
                f"prefetch_batches must be an int >= 0, got "
                f"{prefetch_batches!r}")
        self.prefetch_batches = prefetch_batches
        # per-attempt wall-clock budget for a fanned-out fit/eval body: a
        # rank busy past this is wedged -> reaped -> the attempt fails
        # retryably with WorkerWedged instead of hanging the driver (see
        # runtime/watchdog.py; stale-heartbeat detection additionally runs
        # whenever RLA_TPU_WEDGE_TIMEOUT_S is set, deadline or not)
        self.worker_deadline_s = worker_deadline_s
        # communication-efficient gradient exchange
        # (parallel/collectives.py): "int8" = block-quantized allreduce
        # with error-feedback residuals (LOSSY, ~4x less wire traffic),
        # "bf16" = half-precision exchange (~2x), None = the implicit
        # fp32 psum.  Requires a pure data-parallel mesh.
        from ..parallel import collectives as collectives_lib
        self.grad_compression = grad_compression
        self._exchange_cfg = collectives_lib.ExchangeConfig(
            mode=grad_compression)  # validates the mode string
        # ZeRO-1: each replica stores + updates a 1/N shard of the
        # optimizer state and params are all-gathered after the update —
        # BIT-IDENTICAL to replicated training (the gradient reduce is
        # unchanged; the update is elementwise), ~3x less optimizer
        # memory per device for Adam-family optimizers
        self.shard_optimizer_state = shard_optimizer_state
        # how the compressed-FSDP step assembles its bf16 compute view
        # (parallel/collectives.py GATHER_MODES): "tree" all-gathers the
        # whole param tree before the forward (PR 8); "scan" keeps the
        # module's declared layer stacks fsdp-sharded as scan operands
        # and all-gathers each layer INSIDE the layer scan — XLA
        # overlaps layer k+1's gather with layer k's matmuls, the
        # backward re-gathers per layer under the remat policy, and the
        # per-layer gradient reduce-scatter rides the gather's autodiff
        # transpose (exact bf16, overlapped).  Falls back to "tree"
        # (with a warning) for modules without a scanned layer stack.
        if gather_mode not in collectives_lib.GATHER_MODES:
            raise ValueError(
                f"gather_mode must be one of "
                f"{collectives_lib.GATHER_MODES}, got {gather_mode!r}")
        self.gather_mode = gather_mode
        # int8 forward matmuls inside the train step (models that
        # support it — GPT's MLP projections — read the module flag;
        # ops/quant.py kernels where shapes allow, int8-rounded XLA dots
        # otherwise, straight-through gradients either way)
        self.int8_matmul = int8_matmul
        # MPMD pipeline parallelism (parallel/mpmd/): pipeline_stages > 1
        # routes fit() to a PipelineRunner over the actor runtime — S
        # stage groups of separate processes, a 1F1B/GPipe microbatch
        # schedule with object-store activation handoff, per-stage fault
        # domains and checkpoint replay.  Orthogonal to the SPMD
        # `pipeline` mesh axis (one program, layer-stacked params); see
        # docs/API.md "Pipeline parallelism (MPMD)".
        if not isinstance(pipeline_stages, int) or pipeline_stages < 1:
            raise ValueError(
                f"pipeline_stages must be an int >= 1, got "
                f"{pipeline_stages!r}")
        self.pipeline_stages = pipeline_stages
        self.pipeline_schedule = pipeline_schedule
        self.pipeline_microbatches = pipeline_microbatches
        if pipeline_stages > 1:
            from ..parallel.mpmd import schedule as mpmd_schedule_lib
            if pipeline_schedule not in mpmd_schedule_lib.SCHEDULES:
                raise ValueError(
                    f"pipeline_schedule must be one of "
                    f"{mpmd_schedule_lib.SCHEDULES}, got "
                    f"{pipeline_schedule!r}")
            if not isinstance(pipeline_microbatches, int) or \
                    pipeline_microbatches < 1:
                raise ValueError(
                    f"pipeline_microbatches must be an int >= 1, got "
                    f"{pipeline_microbatches!r}")
            if grad_compression is not None:
                raise ValueError(
                    "grad_compression composes with the compiled SPMD "
                    "gradient exchange, not with pipeline_stages > 1: "
                    "MPMD lane gradients cross the object store in fp32 "
                    "by design (exact parity with the single-group "
                    "baseline)")
            if shard_optimizer_state:
                raise ValueError(
                    "shard_optimizer_state=True (ZeRO-1) is an SPMD-mesh "
                    "feature; under pipeline_stages > 1 each stage group "
                    "shards within its stage instead — pass fsdp>1 "
                    "through the pipeline runner")
            if accumulate_grad_batches > 1:
                raise ValueError(
                    "accumulate_grad_batches > 1 is redundant under "
                    "pipeline_stages > 1: the pipeline schedule already "
                    "accumulates pipeline_microbatches gradients per "
                    "optimizer step")
        # sequence parallelism (parallel/ulysses.py, ring_attention.py):
        # seq_parallel > 1 adds a `sequence` mesh axis composing with
        # data×fsdp — activations shard on the sequence dim, attention
        # routes through the Ulysses all_to_all head-scatter or the ring
        # KV rotation INSIDE the layer scan (XLA overlaps the collective
        # with per-layer compute, same placement argument as the scan
        # param gather).  Params stay on their data/fsdp layout.
        if not isinstance(seq_parallel, int) or seq_parallel < 1:
            raise ValueError(
                f"seq_parallel must be an int >= 1, got {seq_parallel!r}")
        self.seq_parallel = seq_parallel
        if seq_parallel_mode is None:
            seq_parallel_mode = knobs.get_str("RLA_TPU_SEQ_PARALLEL_MODE",
                                              "ulysses")
        if seq_parallel_mode not in ("ulysses", "ring"):
            raise ValueError(
                f"seq_parallel_mode must be 'ulysses' or 'ring', got "
                f"{seq_parallel_mode!r}")
        self.seq_parallel_mode = seq_parallel_mode
        if seq_parallel > 1:
            if pipeline_stages > 1:
                raise ValueError(
                    "seq_parallel > 1 composes with the SPMD data×fsdp "
                    "mesh, not with pipeline_stages > 1: the MPMD stage "
                    "groups split layers across processes while the "
                    "sequence axis splits activations within one program "
                    "— shard sequence inside a stage via the stage "
                    "group's own mesh instead")
            if grad_compression is not None:
                raise ValueError(
                    "grad_compression wraps the forward in a full-manual "
                    "shard_map (parallel/collectives.py "
                    "build_local_grads), which cannot nest the "
                    "ulysses/ring attention shard_map; run seq_parallel "
                    "with the implicit fp32 exchange")
            mesh_cfg = self.accelerator.mesh_config
            if mesh_cfg.sequence not in (1, seq_parallel):
                raise ValueError(
                    f"seq_parallel={seq_parallel} conflicts with the "
                    f"accelerator's mesh_config.sequence="
                    f"{mesh_cfg.sequence}; pass one or the other")
            if mesh_cfg.sequence != seq_parallel:
                # inject the sequence axis without mutating the caller's
                # accelerator (resize_in_memory idiom)
                accelerator = copy.copy(self.accelerator)
                accelerator.mesh_config = dataclasses.replace(
                    mesh_cfg, sequence=seq_parallel)
                accelerator._mesh = None
                self.accelerator = accelerator
        # numeric anomaly guardian (runtime/guardian.py): "auto" (default)
        # reads the guard knob family (on unless RLA_TPU_GUARD=0),
        # None disables — the step functions are then BIT-IDENTICAL to the
        # pre-guardian build (no guard state leaf, no guard math in the
        # trace); a GuardConfig uses it as-is.  Guarded steps fold the
        # health flags into the compiled program and ride the existing
        # metrics readback: zero extra device syncs, zero retraces.
        from ..runtime import guardian as guardian_lib
        if guard == "auto":
            guard = guardian_lib.GuardConfig.from_env()
        if guard is not None and not isinstance(
                guard, guardian_lib.GuardConfig):
            raise ValueError(
                f"guard must be 'auto', None, or a GuardConfig, got "
                f"{guard!r}")
        self.guard = guard
        # analytic bytes-on-wire record for the compiled gradient
        # exchange (collectives.wire_bytes_per_step); also mirrored onto
        # the profiler when one is attached
        self.comms_per_step: Optional[Dict[str, Any]] = None
        self.seed = seed_everything(seed)

        if enable_checkpointing and not any(
                isinstance(c, ModelCheckpoint) for c in self.callbacks):
            self.callbacks.append(ModelCheckpoint(monitor=None))

        # run state
        self.current_epoch = 0
        self.epochs_completed = 0
        self.global_step = 0
        self.should_stop = False
        self.sanity_checking = False
        self.fitting = False
        self.callback_metrics: Dict[str, float] = {}
        # machine-readable record of the last fan-out stall (bench.py
        # death-record shape, runtime/watchdog.stall_record); None while
        # no supervised run has failed
        self.last_stall_diagnosis: Optional[Dict[str, Any]] = None
        # telemetry (telemetry/): trace id minted per fit on the driver,
        # adopted from the ambient recorder inside fanned-out workers (the
        # pickled trainer carries it across the agent execute op, so one
        # fit is one trace on every process); per-rank telemetry snapshots
        # returned by a fan-out land in _rank_telemetry for
        # build_metrics_registry() to merge
        self.trace_id: Optional[str] = None
        self._rank_telemetry: Dict[Any, Optional[Dict[str, Any]]] = {}
        # live telemetry plane (telemetry/live.py): the per-process
        # /metrics+/statusz+/healthz server (started at fit when
        # RLA_TPU_METRICS_PORT is configured) and the driver-side
        # ClusterView aggregating every fan-out rank's live snapshot —
        # its last view is embedded in run_report.json on failure
        self._live_server = None
        self._cluster_view = None
        # preemption drain (runtime/preemption.py): bound at fit start
        # when RLA_TPU_PREEMPT_GRACE_S is configured (None otherwise —
        # zero per-step overhead); the step loop polls it and drains into
        # an emergency checkpoint + typed Preempted
        self._preempt_notice = None
        # (saved_dp, current_dp) when the last restore crossed world
        # sizes (elastic scale-down/up); None for same-world restores
        self._resumed_world_resize: Optional[tuple] = None
        # guardian host companion (runtime/guardian.py Guardian): bound at
        # fit start when guard is on; tracks the dispatched-batch ring for
        # blame attribution and owns the quarantine ledger
        self._guardian = None
        # chaos numeric faults (testing/chaos.py numeric layer) active for
        # this process; parsed once per fit from RLA_TPU_CHAOS
        self._chaos_numeric: tuple = ()
        self.module: Optional[TpuModule] = None
        self._state: Optional[TrainState] = None
        self._mesh = None
        self._tx = None
        self._train_step_fn = None
        self._eval_step_fn = None
        self._val_loader = None
        self._device_cache = None
        self._train_step_cached_fn = None
        self._epoch_scan_fn = None
        self._zero1_update_sh = None
        # param shardings when the compressed exchange runs in the FSDP
        # (reduce-scatter/all-gather) regime; None = replicated-DP regime
        self._fsdp_param_sh = None
        # the resolved ShardingPlan (parallel/plan.py) for the current
        # mesh — the layout value the elastic resize path diffs and
        # redistributes against; set by _resolve_state_shardings
        self._plan = None
        # first training batch of the last fit — the compile template a
        # live resize recompiles against (the loader is long gone then)
        self._example_batch = None
        # (effective gather mode, scanned top-level keys) resolved per
        # compile — "scan" only when the FSDP regime is live AND the
        # module declares a compatible layer stack
        self._gather_mode_eff = "tree"
        self._scanned_keys: tuple = ()
        # persistent fan-out world (spawned agent workers + formed
        # jax.distributed world), reused across entry points; see
        # _acquire_world / shutdown_workers
        self._world = None

    def __getstate__(self):
        """The fan-out ships this trainer to workers; the live world
        (processes, sockets, threads) stays driver-side.  The preemption
        notice holds thread primitives and is per-process by design —
        workers re-bind their own at fit start."""
        state = dict(self.__dict__)
        state["_world"] = None
        state["_preempt_notice"] = None
        # the resolved ShardingPlan holds live Device objects (meshes /
        # NamedShardings); workers re-resolve it at their own _compile
        state["_plan"] = None
        # the live server/cluster view hold sockets + threads; workers
        # start their own at boot (actors._worker_main) and bind their
        # copy of the trainer to it at fit
        state["_live_server"] = None
        state["_cluster_view"] = None
        return state

    # ------------------------------------------------------------------ #
    # Checkpoint plumbing                                                #
    # ------------------------------------------------------------------ #
    @property
    def checkpoint_callback(self) -> Optional[ModelCheckpoint]:
        for c in self.callbacks:
            if isinstance(c, ModelCheckpoint):
                return c
        return None

    def dump_checkpoint(self, include_state: bool = True) -> Dict[str, Any]:
        cb_states = {}
        for c in self.callbacks:
            st = c.state_dict()
            if st:
                cb_states[c.state_key] = st
        # the stored epoch counts COMPLETED epochs (maintained by the fit
        # loop; a max_steps-truncated epoch does not count), so a resumed run
        # neither repeats the epoch that produced the save nor skips ahead
        # world record: lets a resume at a DIFFERENT device count detect
        # the resize and reconcile world-shaped state (ZeRO-1 shards
        # redistribute via global shapes; per-replica residuals reset)
        world = {"dp": (mesh_lib.data_parallel_size(self._mesh)
                        if self._mesh is not None else None),
                 "fsdp": (mesh_lib.mesh_axis_size(self._mesh,
                                                  mesh_lib.FSDP_AXIS)
                          if self._mesh is not None else None),
                 "processes": jax.process_count()}
        extra = {"world": world}
        # compressed-exchange buffer shapes (world-dependent: stacked
        # replica dim / fsdp chunk sizes): lets a resumed run at a
        # DIFFERENT world size rebuild an exactly-shaped restore template
        # without re-deriving the saving mesh's layout heuristics
        if self._state is not None:
            for field in ("residual", "grad_accum"):
                tree = getattr(self._state, field, None)
                if tree is not None:
                    extra[f"{field}_leaf_shapes"] = [
                        list(map(int, leaf.shape))
                        for leaf in jax.tree.leaves(tree)]
        payload = ckpt_lib.build_checkpoint(
            self._state if include_state else None,
            self.epochs_completed, self.global_step,
            hparams=getattr(self.module, "hparams", {}), callbacks=cb_states,
            extra=extra)
        if self.module is not None:
            self.module.on_save_checkpoint(payload)
        for c in self.callbacks:
            c.on_save_checkpoint(self, self.module, payload)
        return payload

    def save_checkpoint(self, filepath: str) -> None:
        with self._perf_phase("ckpt"):  # timeline: save cost is a phase
            if self.checkpoint_format != "pickle":
                # every process participates (each writes its own shards)
                from ..utils import sharded_checkpoint as sharded_lib
                meta = self.dump_checkpoint(include_state=False)
                sharded_lib.save_sharded(
                    filepath, self._state, meta,
                    async_save=self.checkpoint_format == "sharded-async")
            elif jax.process_index() == 0:
                ckpt_lib.atomic_save(self.dump_checkpoint(), filepath)

    # ------------------------------------------------------------------ #
    # Preemption drain                                                   #
    # ------------------------------------------------------------------ #
    def _bind_preemption(self) -> None:
        """Activate the preemption drain for this fit when a grace budget
        is configured (``RLA_TPU_PREEMPT_GRACE_S``): install/attach the
        process notice with the run dir as the cross-rank flag dir, so
        one rank's SIGTERM drains every rank at the same step boundary.
        Unconfigured runs keep ``_preempt_notice`` None — the step loop
        pays nothing."""
        from ..runtime import preemption as preempt_lib
        notice = preempt_lib.get_notice()
        if preempt_lib.grace_from_env() is None and not notice.enabled():
            self._preempt_notice = None
            return
        notice.install(flag_dir=self.default_root_dir)
        # a flag file left by the PREVIOUS drain must not preempt this
        # (resumed) fit at its first step boundary
        notice.clear_stale_flag()
        # multi-process: the drain decision is a cross-host collective
        # (all ranks must stop at the same boundary), so it runs on a
        # deterministic every-N-steps schedule instead of per step --
        # a per-step allgather would serialize the async dispatch
        # pipeline for the run's whole lifetime.  Single process pays
        # nothing and checks every step.
        self._preempt_check_every = max(1, knobs.get_int(
            preempt_lib.PREEMPT_CONSENSUS_EVERY_ENV, 8))
        self._preempt_notice = notice

    def _maybe_drain_preemption(self, every_step: bool = False) -> None:
        """Step-boundary poll: on a (cross-rank-agreed) notice, force an
        emergency checkpoint inside the grace budget and raise the typed
        ``Preempted`` — ``ElasticRunner`` resumes it without charging the
        failure budget and ``fit(ckpt_path='last')`` lands on the exact
        saved step.  ``every_step=True`` bypasses the multi-process
        consensus schedule — used at call sites that are already rare
        AND SPMD-consistent (epoch boundaries on the scan path, whose
        steps would otherwise alias the modulo and defer the drain past
        the grace budget)."""
        notice = self._preempt_notice
        if notice is None:
            return
        from ..runtime import preemption as preempt_lib
        if not every_step and jax.process_count() > 1 \
                and self.global_step % self._preempt_check_every != 0:
            # off the consensus schedule: every rank skips the same
            # boundaries (global_step is SPMD-consistent), so the
            # collective below always has full participation
            return
        if not preempt_lib.consensus_requested(notice.requested()):
            return
        log.warning(
            "preemption notice (%s): draining at step %d (grace %.1fs, "
            "%.1fs remaining)", notice.source, self.global_step,
            notice.grace_s(), notice.remaining_s() or 0.0)
        telemetry.emit("preempt_drain", step=self.global_step,
                       source=notice.source)
        with self._perf_phase("drain"):  # drain incl. its emergency save
            path = self._emergency_checkpoint()
        telemetry.emit("emergency_checkpoint", step=self.global_step,
                       path=path)
        self.fitting = False
        raise preempt_lib.Preempted.at_step(
            self.global_step, path, source=notice.source or "notice")

    def _emergency_checkpoint(self) -> Optional[str]:
        """Synchronous save for the drain path: fence any in-flight async
        commit first (it must not straggle past the grace window), then
        write ``preempt-step{N}.ckpt`` under the checkpoint dir.  Always
        sync even under ``sharded-async`` — the process is about to
        exit, and an async commit racing interpreter teardown is exactly
        the torn checkpoint this PR exists to survive."""
        if self._state is None or not self.enable_checkpointing:
            return None
        cb = self.checkpoint_callback
        dirpath = (cb.dirpath if cb is not None and cb.dirpath
                   else os.path.join(self.default_root_dir, "checkpoints"))
        path = os.path.join(dirpath,
                            f"preempt-step{self.global_step}.ckpt")
        if self.checkpoint_format != "pickle":
            from ..utils import sharded_checkpoint as sharded_lib
            sharded_lib.wait_until_finished()
            meta = self.dump_checkpoint(include_state=False)
            sharded_lib.save_sharded(path, self._state, meta,
                                     async_save=False)
        elif jax.process_index() == 0:
            ckpt_lib.atomic_save(self.dump_checkpoint(), path)
        if jax.process_count() > 1:
            # no rank may raise Preempted before process 0's meta.json /
            # pickle rename is durable: the driver fails fast on the
            # FIRST resolved future and kills the world, and a SIGKILL
            # mid-meta-write would leave the emergency checkpoint torn
            # (invisible to latest_checkpoint) — losing the exact-step
            # resume this drain exists to guarantee
            from jax.experimental import multihost_utils
            multihost_utils.sync_global_devices("rla_emergency_ckpt")
        log.warning("emergency checkpoint written: %s", path)
        return path

    def _detect_resize(self, payload: Dict[str, Any]) -> Optional[tuple]:
        """(saved_dp, current_dp) when the checkpoint was written at a
        different data-parallel world size than this run's mesh (elastic
        scale-down after a lost host, or scale-up), else None.  Global
        array shapes are world-independent — only per-replica state
        (error-feedback residuals, local-grad accumulators) and the
        shard LAYOUT change, and the layout re-resolves from the current
        mesh in ``_compile``.  A dp-preserving mesh RE-SPLIT (data=1 x
        fsdp=8 -> data=2 x fsdp=4) counts too: the shard-local FSDP
        residual chunk sizes depend on the fsdp extent, so the run's own
        buffers cannot serve as the restore template."""
        world = payload.get("world") or {}
        saved_dp = world.get("dp")
        saved_fsdp = world.get("fsdp")
        cur_dp = mesh_lib.data_parallel_size(self._mesh)
        cur_fsdp = mesh_lib.mesh_axis_size(self._mesh, mesh_lib.FSDP_AXIS)
        if saved_dp is None or (saved_dp == cur_dp and
                                saved_fsdp in (None, cur_fsdp)):
            return None
        log.warning(
            "resuming a checkpoint saved at data-parallel world size %d "
            "(fsdp %s) onto %d (fsdp %d): ZeRO-1/optimizer shards "
            "redistribute via their global shapes; per-replica "
            "error-feedback residuals and gradient accumulators reset "
            "to zero (replica-local semantics cannot cross world "
            "layouts)", saved_dp, saved_fsdp, cur_dp, cur_fsdp)
        return (saved_dp, cur_dp)

    def _restore_sharded_state(self, ckpt_path: str, state: TrainState,
                               resized: Optional[tuple],
                               payload: Optional[Dict[str, Any]] = None
                               ) -> TrainState:
        """Orbax restore with template reconciliation.  Candidate
        templates, in order: the run's own state (skipped on a world
        resize — its per-replica buffers have the wrong leading dim);
        stripped of residual/grad_accum (checkpoint predates them, or
        carries none); carrying SAVED-world-shaped buffers (compression
        checkpoint restored onto a different world — restored buffers
        are then discarded for this run's fresh zeros).  Saved-world
        buffer shapes come from the shape record in ``meta.json`` when
        present (exact for the shard-local FSDP layout, whose chunk
        sizes depend on the saved fsdp size), else re-derived as the
        stacked-DP layout from ``saved_dp``."""
        from ..parallel import collectives as collectives_lib
        from ..utils import sharded_checkpoint as sharded_lib

        payload = payload or {}

        def recorded_tree(field):
            shapes = payload.get(f"{field}_leaf_shapes")
            flat, treedef = jax.tree.flatten(state.params)
            if not isinstance(shapes, list) or len(shapes) != len(flat):
                return None
            return treedef.unflatten(
                [jnp.zeros(tuple(s), jnp.float32) for s in shapes])

        carries = (state.residual is not None
                   or state.grad_accum is not None)
        candidates = []
        if not (resized and carries):
            candidates.append(("full", state))
        if carries:
            candidates.append(
                ("stripped",
                 state.replace(residual=None, grad_accum=None)))
            if resized:
                saved_dp = resized[0]
                # explicit None tests: recorded_tree returns a bare
                # array for single-leaf param trees, whose truthiness
                # raises
                res = acc = None
                if state.residual is not None:
                    res = recorded_tree("residual")
                    if res is None:
                        res = collectives_lib.residual_zeros(
                            state.params, saved_dp, self._exchange_cfg)
                if state.grad_accum is not None:
                    acc = recorded_tree("grad_accum")
                    if acc is None:
                        acc = collectives_lib.accum_zeros(state.params,
                                                          saved_dp)
                candidates.append(
                    ("saved-world",
                     state.replace(residual=res, grad_accum=acc)))
        last_exc = None
        for name, template in candidates:
            shardings = None
            if resized:
                # restore straight into THIS mesh's layout: abstract
                # arrays carry the re-resolved (ZeRO-1-aware) shardings,
                # so each process reads only the bytes its devices need
                # and the saved shards redistribute onto the new world —
                # never materializing through the SAVED mesh, whose
                # devices may no longer exist
                shardings = self._resolve_state_shardings(
                    self.module, template, report_fallbacks=False)
                if template.residual is not None \
                        or template.grad_accum is not None:
                    # saved-world-shaped buffers are discarded right
                    # after the restore; replicate them instead of
                    # assuming the old leading dim divides the new mesh
                    repl = jax.sharding.NamedSharding(
                        self._mesh, jax.sharding.PartitionSpec())
                    shardings = shardings.replace(
                        residual=jax.tree.map(lambda _: repl,
                                              template.residual),
                        grad_accum=jax.tree.map(lambda _: repl,
                                                template.grad_accum))
            try:
                restored = sharded_lib.restore_sharded(ckpt_path,
                                                       template=template,
                                                       shardings=shardings)
            except Exception as e:
                last_exc = e
                log.warning(
                    "sharded restore with the %s template failed "
                    "(%s: %s)%s", name, type(e).__name__, e,
                    "; retrying with the next reconciliation"
                    if template is not candidates[-1][1] else "")
                continue
            if name == "full":
                # orbax happily restores SAVED-shaped buffers over a
                # differently-shaped template; per-replica exchange
                # buffers whose layout changed between runs (a
                # gather_mode flip swaps real residuals for
                # placeholders and back) must reset to this run's fresh
                # zeros instead of silently adopting the saved layout
                return self._reset_mismatched_exchange_buffers(
                    restored, state)
            # non-full template: this run keeps its own fresh (zero)
            # residual/accumulator buffers -- error feedback loses at
            # most one step of history
            log.warning(
                "error-feedback residuals/gradient accumulators reset "
                "to zero (restored via the %s template)", name)
            return restored.replace(residual=state.residual,
                                    grad_accum=state.grad_accum)
        raise last_exc

    @staticmethod
    def _reset_mismatched_exchange_buffers(restored: TrainState,
                                           template: TrainState
                                           ) -> TrainState:
        """Per-replica exchange buffers (error-feedback residuals,
        gradient accumulators) restored with shapes this run's layout
        does not expect — a gather_mode flip swaps real residuals for
        placeholders and back, and neither orbax nor flax
        ``from_state_dict`` shape-checks — reset to the template's
        fresh zeros (error feedback loses at most one step of
        history)."""

        def mismatched(field) -> bool:
            t = getattr(template, field)
            r = getattr(restored, field)
            if t is None or r is None:
                return (t is None) != (r is None)
            tl, rl = jax.tree.leaves(t), jax.tree.leaves(r)
            return (len(tl) != len(rl) or any(
                tuple(np.shape(a)) != tuple(np.shape(b))
                for a, b in zip(tl, rl)))

        bad = [f for f in ("residual", "grad_accum") if mismatched(f)]
        if bad:
            log.warning(
                "restored %s buffers do not match this run's exchange "
                "layout (gather_mode or compression change); resetting "
                "them to zero — error feedback loses at most one step "
                "of history", "/".join(bad))
            restored = restored.replace(
                **{f: getattr(template, f) for f in bad})
        return restored

    def _restore(self, ckpt_path: str, state: TrainState) -> TrainState:
        from ..utils import sharded_checkpoint as sharded_lib
        self._resumed_world_resize = None
        if sharded_lib.is_sharded_checkpoint(ckpt_path):
            payload = sharded_lib.read_metadata(ckpt_path)
            resized = self._detect_resize(payload)
            self._resumed_world_resize = resized
            state = self._restore_sharded_state(ckpt_path, state, resized,
                                                payload=payload)
        else:
            payload = ckpt_lib.read_checkpoint(ckpt_path)
            resized = self._detect_resize(payload)
            self._resumed_world_resize = resized
            if resized and isinstance(payload.get("state"), dict):
                # per-replica buffers are [saved_dp, ...]-shaped;
                # flax.from_state_dict does not shape-check, so a silent
                # wrong-world restore must be cut off here -- dropping
                # them keeps the template's fresh zeros
                for k in ("residual", "grad_accum"):
                    if payload["state"].get(k) is not None:
                        payload["state"][k] = None
            state = self._reset_mismatched_exchange_buffers(
                ckpt_lib.restore_state(payload, state), state)
        self.current_epoch = payload["epoch"]
        self.epochs_completed = payload["epoch"]
        self.global_step = payload["global_step"]
        for c in self.callbacks:
            if c.state_key in payload.get("callbacks", {}):
                c.load_state_dict(payload["callbacks"][c.state_key])
            c.on_load_checkpoint(self, self.module, payload)
        if self.module is not None:
            self.module.on_load_checkpoint(payload)
        return state

    # ------------------------------------------------------------------ #
    # Compilation                                                        #
    # ------------------------------------------------------------------ #
    def _build_tx(self, module: TpuModule) -> optax.GradientTransformation:
        tx = module.configure_optimizers()
        if tx is None:
            tx = optax.adam(1e-3)
        if self.gradient_clip_val:
            tx = optax.chain(
                optax.clip_by_global_norm(self.gradient_clip_val), tx)
        if self.ema_decay is not None:
            from ..utils.ema import ema_tracker
            # inside MultiSteps so the shadow moves once per optimizer
            # update, not per accumulation micro-step
            tx = optax.chain(tx, ema_tracker(self.ema_decay))
        if self.accumulate_grad_batches > 1 and self.grad_compression is None:
            # with grad_compression the train step accumulates LOCAL
            # (pre-exchange) grads itself in TrainState.grad_accum so the
            # collective runs once per window; MultiSteps would force an
            # exchange every micro-step just to feed its accumulator
            tx = optax.MultiSteps(tx, self.accumulate_grad_batches)
        return tx

    def _resolve_state_shardings(self, module: TpuModule,
                                 state: TrainState,
                                 report_fallbacks: bool = True):
        """State shardings for THIS run's mesh (accelerator layout +
        ZeRO-1 re-sharding when enabled); sets ``_zero1_update_sh`` as a
        side effect.  Shared by ``_compile`` (the authoritative
        resolution — the one that reports fsdp_fallback telemetry) and
        the sharded restore path — an elastic resume re-resolves the
        layout against the NEW (possibly smaller) mesh, once per
        candidate template, and restores straight into it
        (``report_fallbacks=False`` there so one fallback leaf does not
        emit one event per template).

        The resolution itself lives in ``parallel/plan.build_plan`` (the
        declarative ShardingPlan the elastic resize path builds for
        meshes the run is not on yet); this wrapper binds the plan to
        the trainer's mesh and caches it on ``self._plan``."""
        from ..parallel import plan as plan_lib

        plan = plan_lib.build_plan(
            self._mesh, self.accelerator, module, state, self._tx,
            grad_compression=self.grad_compression,
            shard_optimizer_state=self.shard_optimizer_state,
            report_fallbacks=report_fallbacks)
        self._plan = plan
        self._fsdp_param_sh = plan.fsdp_param_shardings
        self._zero1_update_sh = plan.zero1_update_shardings
        return plan.state_shardings

    def _resolve_gather_mode(self, module, params, param_sh,
                             quiet: bool = False):
        """(effective gather mode, scanned top-level keys) for this
        run.  "scan" engages only when the user asked for it AND the
        module declares scanned param subtrees whose layout the in-scan
        gather can handle; anything else warns (once, from the
        authoritative _compile resolution) and falls back to the
        whole-tree gather — correct, just not overlapped."""
        from ..parallel import collectives as collectives_lib

        if self.gather_mode != "scan":
            return "tree", ()
        scanned = tuple(getattr(module, "scanned_param_subtrees",
                                lambda: ())())
        reason = None
        if not scanned:
            reason = ("module declares no scanned param subtrees "
                      "(scanned_param_subtrees)")
        elif not isinstance(params, dict) \
                or any(k not in params for k in scanned):
            reason = (f"scanned keys {scanned} are not top-level keys "
                      f"of the param tree")
        else:
            try:
                collectives_lib.validate_scan_gather(param_sh, scanned)
            except collectives_lib.TensorShardedParamsError as e:
                reason = str(e)
        if reason is None and self._mesh is not None and \
                mesh_lib.mesh_axis_size(
                    self._mesh, mesh_lib.SEQUENCE_AXIS) > 1:
            reason = ("mesh has a sequence axis: the in-scan gather's "
                      "full-manual shard_map cannot nest the "
                      "ulysses/ring attention shard_map")
        if reason is None and not any(
                collectives_lib.fsdp_shard_dim(s) is not None
                for k in scanned
                for s in jax.tree.leaves(param_sh[k])):
            reason = ("no scanned leaf is fsdp-sharded — nothing to "
                      "gather inside the scan")
        if reason is not None:
            if not quiet:
                log.warning("gather_mode='scan' falls back to 'tree': %s",
                            reason)
            return "tree", ()
        return "scan", scanned

    def _fresh_exchange_buffers(self, module: TpuModule, params,
                                mesh) -> tuple:
        """(residual, grad_accum) zero trees for ``mesh``'s world under
        grad_compression — per-replica state whose leading dim IS the
        world size, so fit init, the cross-world restore path and the
        in-memory resize all rebuild it identically from here.

        The exchange regime decides the buffer shapes, so the param
        layout is probed first (quiet: _compile's authoritative
        resolution emits the fallback telemetry once); fsdp-sharded
        params get shard-local (1/N) residuals and param-shaped
        (post-exchange) accumulators — model-parallel shardings refuse
        typed right here."""
        from ..parallel import collectives as collectives_lib
        n_dp = mesh_lib.data_parallel_size(mesh)
        param_sh = self.accelerator.param_shardings(
            mesh, params, module=module, report_fallbacks=False)
        fsdp_mode = any(
            collectives_lib.fsdp_shard_dim(s) is not None
            for s in jax.tree.leaves(param_sh))
        if fsdp_mode:
            # scan-gathered leaves never ride the quantized exchange
            # (their reduce-scatter is the in-scan gather's exact
            # transpose), so they get residual placeholders
            _, scanned = self._resolve_gather_mode(
                module, params, param_sh, quiet=True)
            residual = collectives_lib.fsdp_residual_zeros(
                params, param_sh, self._exchange_cfg, scanned=scanned)
            grad_accum = (jax.tree.map(
                lambda p: jnp.zeros(p.shape, jnp.float32), params)
                if self.accumulate_grad_batches > 1 else None)
        else:
            residual = collectives_lib.residual_zeros(
                params, n_dp, self._exchange_cfg)
            grad_accum = (collectives_lib.accum_zeros(params, n_dp)
                          if self.accumulate_grad_batches > 1 else None)
        return residual, grad_accum

    # ------------------------------------------------------------------ #
    # Live elastic resharding                                             #
    # ------------------------------------------------------------------ #
    def resize_in_memory(self, num_workers: int, *,
                         max_bytes: Optional[int] = None) -> Dict[str, Any]:
        """Re-plan the live state onto a ``num_workers``-wide mesh and
        redistribute the shards IN MEMORY — no checkpoint round-trip.

        Validation happens strictly before mutation: the new mesh, the
        new :class:`~..parallel.plan.ShardingPlan` and the batch
        divisibility are all resolved against temporaries, and any
        refusal raises :class:`~..runtime.elastic.ElasticResizeError`
        with the live state untouched (the dp=8→3 case).  Only then are
        params / opt_state / step / rng moved via
        ``parallel/redistribute.redistribute_tree`` (bounded waves,
        never a replicated intermediate) while the per-replica buffers
        (residual / grad_accum) are rebuilt as fresh zeros for the new
        world — exactly as the checkpoint-restore path does.

        Afterwards the trainer is compiled for the new mesh and a
        ``fit(..., ckpt_path="live")`` continues from the live state and
        counters.  Returns the redistribution stats (bytes moved,
        waves, seconds).  Emits ``resize_begin``/``resize_end`` and
        accounts the downtime as the goodput ledger's ``resize`` phase
        when a perf observatory is attached."""
        from ..parallel import plan as plan_lib
        from ..parallel import redistribute as redistribute_lib
        from ..runtime.elastic import ElasticResizeError

        if self._state is None or self.module is None \
                or self._example_batch is None:
            raise ElasticResizeError(
                "resize_in_memory needs a fitted trainer with live state "
                "(call fit() first)")
        module, state = self.module, self._state
        old_mesh = self._mesh
        old_dp = mesh_lib.data_parallel_size(old_mesh)
        t0 = time.perf_counter()

        # -- plan the new topology against temporaries (refusals here
        #    leave the run exactly as it was) -------------------------
        cfg = self.accelerator.mesh_config
        n_fsdp = cfg.fsdp if cfg.fsdp and cfg.fsdp > 0 else 1
        if num_workers < 1 or num_workers % n_fsdp:
            raise ElasticResizeError(
                f"cannot resize to {num_workers} batch shards: not "
                f"divisible by the mesh's fsdp={n_fsdp} axis")
        import copy
        import dataclasses as _dc
        accelerator = copy.copy(self.accelerator)
        accelerator.mesh_config = _dc.replace(cfg,
                                              data=num_workers // n_fsdp)
        accelerator._mesh = None
        if getattr(accelerator, "num_workers", None) is not None:
            accelerator.num_workers = num_workers
        try:
            new_mesh = accelerator.build_mesh()
            new_plan = plan_lib.build_plan(
                new_mesh, accelerator, module, state, self._tx,
                grad_compression=self.grad_compression,
                shard_optimizer_state=self.shard_optimizer_state,
                report_fallbacks=False)
        except ValueError as e:
            raise ElasticResizeError(
                f"cannot re-plan the live state onto a {num_workers}-wide "
                f"mesh: {e}") from e
        new_dp = mesh_lib.data_parallel_size(new_mesh)
        # the batch contract the next step must satisfy: same typed
        # refusal _check_batch raises on an elastic resume, but BEFORE
        # any state moved
        batch_leaves = jax.tree.leaves(self._example_batch)
        dp_local = max(1, new_dp // jax.process_count())
        for leaf in batch_leaves:
            n = leaf.shape[0] if getattr(leaf, "ndim", 0) else 0
            if n and n % dp_local:
                raise ElasticResizeError(
                    f"per-process batch dim {n} is not divisible by the "
                    f"resized local data-parallel size {dp_local} "
                    f"(dp {old_dp}→{new_dp}); this run cannot continue "
                    f"at that world size")

        telemetry.emit("resize_begin", old_world=old_dp,
                       new_world=new_dp, step=self.global_step)
        # -- commit the topology, rebuild buffers, recompile ----------
        old_state = state
        self.accelerator = accelerator
        self._mesh = new_mesh
        residual, grad_accum = (None, None)
        if self.grad_compression is not None:
            residual, grad_accum = self._fresh_exchange_buffers(
                module, state.params, new_mesh)
        template = state.replace(residual=residual, grad_accum=grad_accum)
        self._compile(module, template, self._example_batch)
        sh = self._state_shardings

        # -- redistribute the live core through bounded waves ---------
        kwargs = {} if max_bytes is None else {"max_bytes": max_bytes}
        (step, params, opt_state, rng), stats = \
            redistribute_lib.redistribute_tree(
                (old_state.step, old_state.params, old_state.opt_state,
                 old_state.rng),
                (sh.step, sh.params, sh.opt_state, sh.rng),
                donate=True, **kwargs)
        new_state = old_state.replace(
            step=step, params=params, opt_state=opt_state, rng=rng,
            residual=(None if residual is None
                      else jax.device_put(residual, sh.residual)),
            grad_accum=(None if grad_accum is None
                        else jax.device_put(grad_accum, sh.grad_accum)))
        self._state = new_state
        self.module.params = new_state.params
        self._resumed_world_resize = (old_dp, new_dp)
        # per-replica device caches sized for the old world are stale
        self._device_cache = None
        self._epoch_scan_fn = None

        seconds = time.perf_counter() - t0
        stats = dict(stats, old_world=old_dp, new_world=new_dp,
                     seconds=seconds)
        if self.perf is not None and getattr(self.perf, "goodput", None) \
                is not None:
            # priced against restart/ckpt in goodput_fraction: the
            # in-memory path's downtime is a first-class overhead phase
            self.perf.goodput.account("resize", seconds)
        telemetry.emit("resize_end", old_world=old_dp, new_world=new_dp,
                       bytes_moved=stats["bytes_moved"],
                       waves=stats["waves"], seconds=seconds)
        log.warning("in-memory resize dp %d→%d: %d bytes moved in %d "
                    "wave(s), %.3fs", old_dp, new_dp,
                    stats["bytes_moved"], stats["waves"], seconds)
        return stats

    def _apply_seq_parallel(self, module: TpuModule, seq: int) -> None:
        """Typed refusals + module routing for a ``sequence`` mesh axis.

        The module's attention must be context-parallel-aware (GPT's
        ``cfg.context_parallel`` dispatch); its declared sequence length
        must divide the axis, and the Ulysses head-scatter additionally
        needs the head count divisible (ring has no such constraint).
        The mode is written onto the module config so the dispatch in
        ``GPT._attention`` — which sits INSIDE the layer scan, where XLA
        overlaps the all_to_all/ppermute with per-layer compute — picks
        the requested strategy."""
        cfg = getattr(module, "cfg", None)
        if cfg is None or not hasattr(cfg, "context_parallel"):
            raise ValueError(
                f"seq_parallel={seq} needs a context-parallel-aware "
                f"module (one whose config carries `context_parallel`, "
                f"e.g. models.GPT); {type(module).__name__} cannot "
                f"shard its attention over a sequence axis")
        max_seq = getattr(cfg, "max_seq_len", None)
        if max_seq is not None and max_seq % seq != 0:
            raise ValueError(
                f"sequence length ({max_seq}) is not divisible by the "
                f"sequence axis size ({seq}); pad max_seq_len or change "
                f"seq_parallel")
        n_heads = getattr(cfg, "n_heads", None)
        if (self.seq_parallel_mode == "ulysses" and n_heads is not None
                and n_heads % seq != 0):
            raise ValueError(
                f"ulysses needs heads ({n_heads}) divisible by the "
                f"sequence axis size ({seq}); use "
                f"seq_parallel_mode='ring' instead")
        cfg.context_parallel = self.seq_parallel_mode

    def _claim_numeric_chaos(self) -> tuple:
        """Numeric chaos faults this build injects (testing/chaos.py):
        each is claimed through the chaos namespace at build time, so a
        post-rewind recompile replays the offending window clean."""
        from ..testing import chaos as chaos_lib
        faults = getattr(self, "_chaos_numeric", ()) or ()
        return tuple(f for f in faults
                     if f.kind in ("nanloss", "gradspike", "bitflip")
                     and chaos_lib.claim_numeric(f))

    def _guard_tail(self, st: TrainState, new_state: TrainState, metrics,
                    grads=None, stacked_local=None):
        """Guardian hook shared by every step builder: fold the traced
        health flags (runtime/guardian.py ``update``) into the state's
        guard vector and pack them into ``metrics["guard"]`` so they ride
        the readback the fit loop was doing anyway — no extra syncs, and
        a scalar-only trace addition (no retraces, compile_guard-pinned).
        A no-op returning its inputs untouched when the guard is off, so
        ``guard=None`` steps stay bit-identical to the pre-guardian
        build."""
        if self.guard is None or getattr(st, "guard_ema", None) is None:
            return new_state, metrics
        from ..runtime import guardian as guardian_lib
        loss = metrics.get("train_loss", jnp.float32(0.0))
        gnorm = metrics.get("grad_norm")
        if gnorm is None:
            if grads is not None:
                gnorm = optax.global_norm(grads)
            elif stacked_local is not None:
                # replica mean of the local micro-grads: the tensor the
                # exchange is about to reduce
                gnorm = optax.global_norm(jax.tree.map(
                    lambda x: jnp.mean(x.astype(jnp.float32), axis=0),
                    stacked_local))
            else:
                gnorm = jnp.float32(0.0)
        delta = jax.tree.map(
            lambda n, o: n.astype(jnp.float32) - o.astype(jnp.float32),
            new_state.params, st.params)
        ratio = optax.global_norm(delta) / (
            optax.global_norm(st.params) + 1e-12)
        rank_bad = None
        if stacked_local is not None:
            rank_bad = guardian_lib.per_replica_bad(
                stacked_local, self.guard.spike_factor)
        new_g, gvec = guardian_lib.update(
            self.guard, st.guard_ema, st.step, loss, gnorm, ratio,
            rank_bad)
        metrics = dict(metrics)
        metrics["guard"] = gvec
        return new_state.replace(guard_ema=new_g), metrics

    def _compile(self, module: TpuModule, state: TrainState, example_batch):
        from ..parallel import collectives as collectives_lib
        from ..parallel import plan as plan_lib
        from ..testing import chaos as chaos_lib

        mesh = self._mesh
        module.mesh = mesh  # models use this for sharding constraints
        seq = mesh_lib.mesh_axis_size(mesh, mesh_lib.SEQUENCE_AXIS)
        if seq > 1:
            if self.grad_compression is not None:
                # reachable only via an accelerator-supplied sequence
                # axis (Trainer(seq_parallel=..) refuses at __init__)
                raise ValueError(
                    "grad_compression wraps the forward in a full-manual "
                    "shard_map (parallel/collectives.py "
                    "build_local_grads), which cannot nest the "
                    "ulysses/ring attention shard_map; run the sequence "
                    "axis with the implicit fp32 exchange")
            self._apply_seq_parallel(module, seq)
            # per-leaf batch tree: sequence dim sharded where it divides
            batch_sh = plan_lib.batch_shardings(mesh, example_batch)
        else:
            batch_sh = self.accelerator.batch_sharding(mesh)
        state_sh = self._resolve_state_shardings(module, state)
        self._gather_mode_eff, self._scanned_keys = ("tree", ())
        if self._fsdp_param_sh is not None:
            self._gather_mode_eff, self._scanned_keys = \
                self._resolve_gather_mode(module, state.params,
                                          self._fsdp_param_sh)
        from ..parallel.sharding import validate_shardings
        validate_shardings(state.params, state_sh.params, mesh)
        if self.profiler is not None:
            # silent loss of FSDP savings, counted: leaves the accelerator
            # had to warn-and-replicate (telemetry event `fsdp_fallback`
            # fires at resolution; this mirrors it into the merged
            # MetricsRegistry counter export)
            n_fb = len(getattr(self.accelerator,
                               "last_fsdp_fallbacks", ()) or ())
            if n_fb:
                self.profiler.incr("fsdp_fallback", n_fb)
        tx = self._tx

        # batch_sh / repl act as pytree *prefixes*: one sharding covers
        # every leaf of the (arbitrary) batch / metrics subtree.
        repl = jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec())

        def apply_grads(grads, opt_state, params):
            """Optimizer update shared by both step variants.  Under
            ZeRO-1 the grads are pinned replicated (so the reduce is the
            SAME op as the replicated baseline -- the bit-identity
            guarantee) and the update tree is constrained to the
            optimizer-state layout, so XLA shards the elementwise update
            and all-gathers the params once."""
            if self._zero1_update_sh is not None:
                grads = jax.tree.map(
                    lambda g: jax.lax.with_sharding_constraint(g, repl),
                    grads)
            updates, new_opt = tx.update(grads, opt_state, params)
            if self._zero1_update_sh is not None:
                updates = jax.tree.map(jax.lax.with_sharding_constraint,
                                       updates, self._zero1_update_sh)
            return optax.apply_updates(params, updates), new_opt

        def step_metrics_lr(st, metrics):
            sched = getattr(module, "lr_schedule", None)
            if callable(sched):  # evaluated in-trace; no host sync
                # accumulation advances the inner schedule once per
                # window, so index by optimizer updates, not micro-steps
                metrics["lr"] = sched(st.step // self.accumulate_grad_batches)
            return metrics

        def loss_fn_of(batch, step_rng):
            def loss_fn(params):
                out = module.training_step(params, batch, step_rng)
                if isinstance(out, tuple):
                    loss, metrics = out
                    metrics = dict(metrics)
                else:
                    loss, metrics = out, {}
                metrics.setdefault("train_loss", loss)
                return loss, metrics
            return loss_fn

        # numeric chaos faults (testing/chaos.py numeric layer) are baked
        # into the TRACE at build time — claimed here so the recompile
        # after a guardian rewind builds a clean step
        chaos_numeric = self._claim_numeric_chaos()

        def train_step(st: TrainState, batch):
            step_rng = jax.random.fold_in(st.rng, st.step)

            (_, metrics), grads = jax.value_and_grad(
                loss_fn_of(batch, step_rng), has_aux=True)(st.params)
            for fault in chaos_numeric:
                metrics, grads, _ = chaos_lib.apply_traced_numeric(
                    fault, st.step, metrics, grads=grads)
            if self.log_grad_norm:
                # micro-batch norm (see the log_grad_norm init comment)
                metrics["grad_norm"] = optax.global_norm(grads)
            new_params, new_opt = apply_grads(grads, st.opt_state, st.params)
            new_state = st.replace(step=st.step + 1, params=new_params,
                                   opt_state=new_opt)
            new_state, metrics = self._guard_tail(st, new_state, metrics,
                                                  grads=grads)
            return new_state, step_metrics_lr(st, metrics)

        if self.grad_compression is not None:
            train_step = self._build_compressed_train_step(
                module, mesh, batch_sh, loss_fn_of, apply_grads,
                step_metrics_lr, chaos_numeric)

        def eval_step(params, batch):
            return module.validation_step(params, batch)

        def test_step(params, batch):
            return module.test_step(params, batch)

        def predict_step(params, batch):
            return module.predict_step(params, batch)

        self._train_step_fn = jax.jit(
            train_step,
            in_shardings=(state_sh, batch_sh),
            out_shardings=(state_sh, repl),
            donate_argnums=0)
        if self._device_cache is not None:
            self._compile_cached_step(train_step, state_sh, batch_sh, repl)
        self._eval_step_fn = jax.jit(
            eval_step, in_shardings=(state_sh.params, batch_sh))
        self._test_step_fn = jax.jit(
            test_step, in_shardings=(state_sh.params, batch_sh))
        self._predict_step_fn = jax.jit(
            predict_step, in_shardings=(state_sh.params, batch_sh))
        self._batch_sharding = batch_sh
        self._state_shardings = state_sh

        if self.grad_compression is not None:
            # the collective payloads of a compiled step are static, so
            # the bytes-on-wire claim is computed, not sampled (FSDP
            # regime: reduce-scatter + bf16 param all-gather accounting)
            report = collectives_lib.wire_bytes_per_step(
                state.params, collectives_lib.dp_size(mesh),
                self._exchange_cfg, param_shardings=self._fsdp_param_sh,
                gather_mode=self._gather_mode_eff,
                scanned=self._scanned_keys)
            self.comms_per_step = report
            if self.profiler is not None:
                self.profiler.record_comms(report)
            if self.perf is not None:
                # the timeline export states the analytic exposed/hidden
                # wire split next to the measured phase times
                self.perf.timeline.attach_comms(report)

    def _build_compressed_train_step(self, module, mesh, batch_sh,
                                     loss_fn_of, apply_grads,
                                     step_metrics_lr, chaos_numeric=()):
        """The grad_compression train step: gradients are computed
        per-replica inside a shard_map (no implicit fp32 psum), exchanged
        through the quantized two-phase collective
        (parallel/collectives.py), with error-feedback residuals carried
        in ``TrainState.residual``.  Under accumulate_grad_batches > 1
        the LOCAL grads accumulate in ``TrainState.grad_accum`` and the
        exchange -- the only communication -- runs once per window,
        gated by a ``lax.cond`` so off-boundary steps move zero gradient
        bytes."""
        from ..parallel import collectives as collectives_lib

        cfg = self._exchange_cfg
        collectives_lib.validate_mesh_for_compression(mesh)
        axes = collectives_lib.dp_axis_names(mesh)
        k = self.accumulate_grad_batches

        def vag(params, batch, step_rng):
            return jax.value_and_grad(
                loss_fn_of(batch, step_rng), has_aux=True)(params)

        extra = None
        if self.log_grad_norm:
            def extra(local_grads):
                # RMS over replicas of the local micro-grad norm (see the
                # log_grad_norm init comment): one scalar pmean, no
                # full-tensor exchange outside the compressed path
                sq = optax.global_norm(local_grads) ** 2
                return {"grad_norm": jnp.sqrt(jax.lax.pmean(sq, axes))}

        if self._fsdp_param_sh is not None:
            return self._build_fsdp_train_step(
                mesh, cfg, k, vag, extra, batch_sh, apply_grads,
                step_metrics_lr, chaos_numeric)
        local_grad_fn = collectives_lib.build_local_grads(
            mesh, vag, batch_sh.spec, extra_metrics=extra)
        exchange_fn = collectives_lib.build_exchange(mesh, cfg)
        from ..testing import chaos as chaos_lib

        def train_step(st: TrainState, batch):
            step_rng = jax.random.fold_in(st.rng, st.step)
            metrics, local = local_grad_fn(st.params, batch, step_rng)
            for fault in chaos_numeric:
                metrics, _, local = chaos_lib.apply_traced_numeric(
                    fault, st.step, metrics, stacked=local)
            if k == 1:
                grads, new_res = exchange_fn(local, st.residual)
                new_params, new_opt = apply_grads(grads, st.opt_state,
                                                  st.params)
                new_state = st.replace(step=st.step + 1, params=new_params,
                                       opt_state=new_opt, residual=new_res)
                new_state, metrics = self._guard_tail(
                    st, new_state, metrics, stacked_local=local)
                return new_state, step_metrics_lr(st, metrics)

            acc = jax.tree.map(lambda a, g: a + g.astype(a.dtype),
                               st.grad_accum, local)
            boundary = (st.step % k) == (k - 1)

            def at_boundary(args):
                acc, res, opt, params = args
                # match MultiSteps: the applied gradient is the window
                # MEAN of the micro-grads
                grads, new_res = exchange_fn(
                    jax.tree.map(lambda a: a / k, acc), res)
                grads = jax.tree.map(lambda g, p: g.astype(p.dtype),
                                     grads, params)
                new_params, new_opt = apply_grads(grads, opt, params)
                return (new_params, new_opt, new_res,
                        jax.tree.map(jnp.zeros_like, acc))

            def off_boundary(args):
                acc, res, opt, params = args
                return params, opt, res, acc

            new_params, new_opt, new_res, new_acc = jax.lax.cond(
                boundary, at_boundary, off_boundary,
                (acc, st.residual, st.opt_state, st.params))
            new_state = st.replace(step=st.step + 1, params=new_params,
                                   opt_state=new_opt, residual=new_res,
                                   grad_accum=new_acc)
            new_state, metrics = self._guard_tail(
                st, new_state, metrics, stacked_local=local)
            return new_state, step_metrics_lr(st, metrics)

        return train_step

    def _build_fsdp_train_step(self, mesh, cfg, k, vag, extra, batch_sh,
                               apply_grads, step_metrics_lr,
                               chaos_numeric=()):
        """The compressed-FSDP (ZeRO-2/3) train step: params live SHARDED
        over the fsdp axis (with their optimizer state — 1/N each), the
        compute view is a bf16 all-gather, per-replica grads land back
        INTO the shard owner, and the optimizer update runs shard-local —
        XLA partitions the elementwise update from the matching layouts.

        Two gather schedules (``Trainer(gather_mode=...)``):

        - ``tree`` (PR 8): the whole bf16 compute tree is all-gathered
          BEFORE the forward (``collectives.build_param_gather``) and the
          grads reduce-scatter quantized through
          ``collectives.build_fsdp_exchange`` afterwards — simple, but
          the gather latency serializes with compute and the replicated
          tree stays live through the backward.
        - ``scan``: the module's layer stacks stay fsdp-sharded as scan
          operands; each layer's bf16 shards are all-gathered INSIDE the
          layer scan (``collectives.build_scan_param_gather`` hooks,
          applied by the model's scan body), so XLA overlaps layer k+1's
          gather with layer k's matmuls, and the gather's autodiff
          transpose reduce-scatters each layer's gradient (exact bf16)
          into its owner inside the equally-overlapped backward — under
          a remat policy that drops gathered weights, the backward
          re-gathers per layer instead of holding the replicated tree
          live.  Non-stacked leaves (embeddings, final norm) keep the
          up-front gather + quantized exchange.

        ``accumulate_grad_batches > 1`` accumulates the POST-exchange
        owned shards in ``TrainState.grad_accum`` (param-shaped, so the
        accumulator is 1/N per device too — the ZeRO-2 trade: the
        reduce-scatter runs every micro-step instead of once per window,
        but no full-size buffer ever exists) and gates only the
        optimizer update on the window boundary."""
        from ..parallel import collectives as collectives_lib
        from ..testing import chaos as chaos_lib

        def finish(st, metrics, gshard, new_res, stacked_local=None):
            """Shared tail: apply now (k == 1) or accumulate the owned
            shards and update at the window boundary."""
            if k == 1:
                new_params, new_opt = apply_grads(gshard, st.opt_state,
                                                  st.params)
                new_state = st.replace(step=st.step + 1, params=new_params,
                                       opt_state=new_opt, residual=new_res)
                new_state, metrics = self._guard_tail(
                    st, new_state, metrics, grads=gshard,
                    stacked_local=stacked_local)
                return new_state, step_metrics_lr(st, metrics)

            acc = jax.tree.map(lambda a, g: a + g.astype(a.dtype),
                               st.grad_accum, gshard)
            boundary = (st.step % k) == (k - 1)

            def at_boundary(args):
                acc, opt, params = args
                # match MultiSteps: the applied gradient is the window
                # MEAN of the (already-exchanged) per-micro-step shards
                grads = jax.tree.map(lambda a, p: (a / k).astype(p.dtype),
                                     acc, params)
                new_params, new_opt = apply_grads(grads, opt, params)
                return (new_params, new_opt,
                        jax.tree.map(jnp.zeros_like, acc))

            def off_boundary(args):
                acc, opt, params = args
                return params, opt, acc

            new_params, new_opt, new_acc = jax.lax.cond(
                boundary, at_boundary, off_boundary,
                (acc, st.opt_state, st.params))
            new_state = st.replace(step=st.step + 1, params=new_params,
                                   opt_state=new_opt, residual=new_res,
                                   grad_accum=new_acc)
            new_state, metrics = self._guard_tail(
                st, new_state, metrics, grads=gshard,
                stacked_local=stacked_local)
            return new_state, step_metrics_lr(st, metrics)

        if self._gather_mode_eff == "scan":
            scanned = self._scanned_keys
            prelude, hooks = collectives_lib.build_scan_param_gather(
                mesh, self._fsdp_param_sh, scanned)
            local_scan_fn = collectives_lib.build_scan_local_grads(
                mesh, vag, batch_sh.spec, self._fsdp_param_sh, scanned,
                hooks, extra_metrics=extra)
            rest_sh = {kk: v for kk, v in self._fsdp_param_sh.items()
                       if kk not in scanned}
            exchange_rest = (collectives_lib.build_fsdp_exchange(
                mesh, cfg, rest_sh) if rest_sh else None)

            def train_step(st: TrainState, batch):
                step_rng = jax.random.fold_in(st.rng, st.step)
                compute_params = prelude(st.params)
                metrics, grads = local_scan_fn(compute_params, batch,
                                               step_rng)
                for fault in chaos_numeric:
                    metrics, grads, _ = chaos_lib.apply_traced_numeric(
                        fault, st.step, metrics, grads=grads)
                # scanned leaves came back finished (exact mean, owner
                # layout — the in-scan gather's transpose); only the
                # rest rides the quantized exchange
                if exchange_rest is not None:
                    rest_out, rest_res = exchange_rest(
                        {kk: v for kk, v in grads.items()
                         if kk not in scanned},
                        {kk: v for kk, v in st.residual.items()
                         if kk not in scanned})
                    gshard = dict(rest_out)
                    gshard.update({kk: grads[kk] for kk in scanned})
                    new_res = dict(rest_res)
                    new_res.update({kk: st.residual[kk]
                                    for kk in scanned})
                else:
                    gshard, new_res = grads, st.residual
                return finish(st, metrics, gshard, new_res)

            return train_step

        local_grad_fn = collectives_lib.build_local_grads(
            mesh, vag, batch_sh.spec, extra_metrics=extra)
        gather_fn = collectives_lib.build_param_gather(
            mesh, self._fsdp_param_sh)
        exchange_fn = collectives_lib.build_fsdp_exchange(
            mesh, cfg, self._fsdp_param_sh)

        def train_step(st: TrainState, batch):
            step_rng = jax.random.fold_in(st.rng, st.step)
            compute_params = gather_fn(st.params)
            metrics, local = local_grad_fn(compute_params, batch, step_rng)
            for fault in chaos_numeric:
                metrics, _, local = chaos_lib.apply_traced_numeric(
                    fault, st.step, metrics, stacked=local)
            gshard, new_res = exchange_fn(local, st.residual)
            return finish(st, metrics, gshard, new_res, stacked_local=local)

        return train_step

    # ------------------------------------------------------------------ #
    # Device-resident dataset cache                                      #
    # ------------------------------------------------------------------ #
    _CACHE_MAX_BYTES = 1 << 30  # "auto" ships datasets up to 1 GiB to HBM
    # "auto" engages only where per-batch h2d is expensive (TPU/GPU links);
    # on the CPU backend the replicated cache copies cost more than they save
    _CACHE_AUTO_ON_CPU = False

    def _build_device_cache(self, loader) -> bool:
        """Ship an array-backed dataset to HBM once; per-step input becomes a
        tiny int32 index row gathered ON device.

        The TPU-idiomatic answer to SURVEY.md §7.4 hard part 4 (input
        pipeline dominates small models): per-batch host->device transfer is
        the bottleneck — over a tunneled/remote PjRt link catastrophically so
        — and a dataset that fits HBM never needs to cross the link twice."""
        self._device_cache = None
        mode = self.cache_dataset_on_device
        if mode is False or not isinstance(loader, DataLoader):
            return False
        arrays = getattr(loader.dataset, "_native_arrays", lambda: None)()
        if not arrays or any(a.dtype.hasobject for a in arrays):
            return False
        from ..data.loader import default_collate
        if loader.collate_fn is not default_collate:
            return False
        total = sum(a.nbytes for a in arrays)
        if mode == "auto":
            if total > self._CACHE_MAX_BYTES:
                return False
            if (jax.default_backend() == "cpu"
                    and not self._CACHE_AUTO_ON_CPU):
                return False
        repl = jax.sharding.NamedSharding(self._mesh,
                                          jax.sharding.PartitionSpec())
        if jax.process_count() > 1:
            # every process holds the full host dataset (the sampler, not
            # the dataset, is what's sharded), so each can populate its
            # addressable shards of a globally-replicated cache -- the
            # per-process analog of the single-host device_put below
            self._device_cache = tuple(
                jax.make_array_from_callback(
                    a.shape, repl, lambda i, a=a: a[i])
                for a in (np.ascontiguousarray(x) for x in arrays))
        else:
            self._device_cache = tuple(
                jax.device_put(np.ascontiguousarray(a), repl)
                for a in arrays)
        self._cache_single = len(arrays) == 1
        return True

    def _compile_cached_step(self, train_step, state_sh, batch_sh, repl):
        # index rows ride the batch sharding: each process contributes ITS
        # sampler's (global dataset) indices to its own shard positions --
        # the same contract _put_batch uses for host-fed data, so the
        # gathered batch lands exactly where the host-fed batch would
        from ..parallel.mesh import BATCH_AXES
        idx_row_sh = batch_sh
        idx_mat_sh = jax.sharding.NamedSharding(
            self._mesh, jax.sharding.PartitionSpec(None, BATCH_AXES))
        self._idx_row_sharding = idx_row_sh
        self._idx_mat_sharding = idx_mat_sh

        def gather(cache, idx):
            batch = tuple(jnp.take(a, idx, axis=0) for a in cache)
            batch = batch[0] if self._cache_single else batch
            return jax.lax.with_sharding_constraint(
                batch, jax.tree.map(lambda _: batch_sh, batch))

        def cached_step(st, cache, idx):
            return train_step(st, gather(cache, idx))

        self._train_step_cached_fn = jax.jit(
            cached_step,
            in_shardings=(state_sh, repl, idx_row_sh),
            out_shardings=(state_sh, repl),
            donate_argnums=0)

        # whole-epoch fusion: ONE dispatch runs every step of an epoch as
        # a lax.scan over the index matrix.  Per-step dispatch overhead
        # (severe over a tunneled/remote PjRt link) leaves the hot loop
        # entirely; metrics come back stacked [n_steps, ...] for
        # after-the-fact logging
        def scanned_epoch(st, cache, idx_mat):
            def body(carry, idx):
                return cached_step(carry, cache, idx)
            return jax.lax.scan(body, st, idx_mat)

        self._epoch_scan_fn = jax.jit(
            scanned_epoch,
            in_shardings=(state_sh, repl, idx_mat_sh),
            out_shardings=(state_sh, repl),
            donate_argnums=0)

    def _can_scan_epoch(self) -> bool:
        """Whole-epoch fusion is eligible when nothing needs the host
        between steps: device cache active, no mid-epoch validation, no
        wall-clock budget (max_time resolves per step in loop mode), no
        per-step profiler spans, and no callback overriding
        on_train_batch_end (the scan cannot call back per step)."""
        if self._epoch_scan_fn is None or self._device_cache is None:
            return False
        if self.val_check_interval or self.max_time is not None:
            return False
        if self.profiler is not None:
            return False
        # an active quarantine (runtime/guardian.py) needs the per-batch
        # skip seam of the step loop; badbatch chaos needs the host path
        if self._guardian is not None and self._guardian.has_quarantine():
            return False
        if any(f.kind == "badbatch" for f in self._chaos_numeric):
            return False

        def overrides_batch_end(c) -> bool:
            fn = getattr(c, "on_train_batch_end", None)
            # __func__ comparison also catches instance-attribute hooks
            # (c.on_train_batch_end = my_fn), which plain functions lack
            return getattr(fn, "__func__", None) \
                is not Callback.on_train_batch_end

        return not any(overrides_batch_end(c) for c in self.callbacks)

    # -- shared epoch materialization (single source of truth for the    #
    #    step loop and the scanned path)                                 #
    @staticmethod
    def _epoch_index_plan(loader):
        """(sampler permutation, batch_size, number of FULL batches)."""
        perm = np.fromiter(loader.sampler, np.int64)
        bs = loader.batch_size
        return perm, bs, len(perm) // bs

    @staticmethod
    def _tail_host_batch(loader, perm, full_nb):
        """The trailing partial batch (drop_last=False), or None."""
        tail = perm[full_nb * loader.batch_size:]
        if not len(tail) or loader.drop_last:
            return None
        arrays = loader.dataset._native_arrays()
        batch = tuple(a[tail] for a in arrays)
        return batch[0] if len(batch) == 1 else batch

    def _run_scanned_epoch(self, state, loader):
        """One dispatch for the epoch's whole-batch steps; returns
        (state, last-step metrics dict, epoch_complete).  The trailing
        partial batch (drop_last=False) still runs through the host path.
        Guard conditions mirror the step loop exactly: a max_steps budget
        hit anywhere in the epoch marks it incomplete and stops."""
        perm, bs, full_nb = self._epoch_index_plan(loader)
        nb_epoch = full_nb
        if self.limit_train_batches is not None:
            nb_epoch = min(nb_epoch, self.limit_train_batches)
        nb = nb_epoch
        if self.max_steps:
            nb = min(nb, max(0, self.max_steps - self.global_step))
        budget_cut = nb < nb_epoch  # max_steps ends the epoch early
        train_metrics: Dict[str, Any] = {}
        if nb:
            idx_mat = self._put_index_matrix(
                perm[:nb * bs].astype(np.int32).reshape(nb, bs))
            t_scan = time.perf_counter()
            state, stacked = self._epoch_scan_fn(state, self._device_cache,
                                                 idx_mat)
            if self.perf is not None:
                # the scanned epoch is ONE async dispatch — per-step
                # phases don't exist, so the timeline gets one coarse
                # nb-step row (dispatch wall; device time lands at the
                # next sync) and the HBM ledger its throttled sample
                self.perf.timeline.observe_scan_epoch(
                    time.perf_counter() - t_scan, nb)
                self.perf.hbm.maybe_sample()
            first_step = self.global_step
            self.global_step += nb
            self._state = state
            train_metrics = {k: v[-1] for k, v in stacked.items()}
            # replay periodic logging from the stacked metrics
            cadence = self.log_every_n_steps
            hits = [i for i in range(nb)
                    if (first_step + i + 1) % cadence == 0]
            if hits:
                # graftlint: ok(host-sync) — one post-epoch readback of
                host = jax.device_get(stacked)  # the stacked metrics
                g_stack = host.pop("guard", None)
                for i in hits:
                    self._log_now({k: float(v[i])
                                   for k, v in host.items()},
                                  step=first_step + i + 1)
                if g_stack is not None:
                    # sticky flags: the last scanned row carries any trip
                    # graftlint: ok(host-sync) — already on host (the
                    self._guard_check(np.asarray(g_stack)[-1])  # get above)

        def budget_hit() -> bool:
            return bool(self.max_steps
                        and self.global_step >= self.max_steps)

        tail = self._tail_host_batch(loader, perm, full_nb)
        if (tail is not None and not budget_hit() and nb == full_nb
                and (self.limit_train_batches is None
                     or full_nb < self.limit_train_batches)):
            batch = self._put_batch(tail)
            state, train_metrics = self._train_step_fn(state, batch)
            self.global_step += 1
            self._state = state
        if budget_hit():
            # loop parity: the step loop breaks on the budget check after
            # the batch, leaving the epoch incomplete
            self.should_stop = True
        return state, train_metrics, not (budget_cut or budget_hit())

    def _put_index_matrix(self, idx_mat: np.ndarray):
        """Device-place a per-process (nb, local_bs) index matrix with the
        batch-dim sharding (multi-process: assembled into the global
        (nb, global_bs) matrix, the index analog of ``_put_batch``)."""
        if jax.process_count() > 1:
            return jax.make_array_from_process_local_data(
                self._idx_mat_sharding, idx_mat)
        return jax.device_put(idx_mat, self._idx_mat_sharding)

    def _cached_epoch_source(self, loader):
        """Yield per-step device index rows (plus a host-path trailing
        partial batch when drop_last=False), honoring the loader's sampler
        order exactly."""
        perm, bs, nb = self._epoch_index_plan(loader)
        if nb:
            rows = perm[:nb * bs].astype(np.int32).reshape(nb, bs)
            if jax.process_count() > 1:
                # a global (nb, bs) matrix is not eagerly row-indexable
                # across processes; each global row is assembled from the
                # local row at CONSUMPTION time (_put_index_row) -- under
                # prefetch this generator runs on the producer thread,
                # and placements must stay on the consumer thread so
                # every process issues them in the same sequence
                for i in range(nb):
                    yield ("cached_local", rows[i])
            else:
                idx_mat = jax.device_put(rows)
                for i in range(nb):
                    yield ("cached", idx_mat[i])
        tail = self._tail_host_batch(loader, perm, nb)
        if tail is not None:
            yield ("host", tail)

    def _put_index_row(self, row: np.ndarray):
        """Assemble one global device index row from this process's local
        row (the per-step analog of ``_put_index_matrix``)."""
        return jax.make_array_from_process_local_data(
            self._idx_row_sharding, row)

    def _place_train_item(self, item):
        """Device-place one fit-source item inside the prefetch pipeline
        (runs on the CONSUMER thread, in stream order): host batches get
        the batch sharding, local cached index rows are assembled into
        global device rows; single-process cached rows are already
        device-resident."""
        kind, payload = item
        if kind == "host":
            payload = self._put_batch(payload)
        elif kind == "cached_local":
            kind, payload = "cached", self._put_index_row(payload)
        return kind, payload

    def _put_batch(self, batch):
        """Ship one host batch to the mesh with the batch sharding.

        Single process: the host batch IS the global batch; device_put
        scatters it.  Multi-process: each process holds only its sampler's
        slice, so the global array is assembled from per-process shards
        (the SPMD analog of per-worker DistributedSampler loading,
        reference: ray_ddp.py:280-295).
        """
        if jax.process_count() > 1:
            return jax.tree.map(
                lambda x: jax.make_array_from_process_local_data(
                    # graftlint: ok(host-sync) — host->device placement
                    self._batch_sharding, np.asarray(x)), batch)
        return jax.device_put(batch, self._batch_sharding)

    # ------------------------------------------------------------------ #
    # fit                                                                #
    # ------------------------------------------------------------------ #
    # ------------------------------------------------------------------ #
    # Multi-machine fan-out (driver mode)                                #
    # ------------------------------------------------------------------ #
    # The reference's signature flow: the driver serializes the whole
    # Trainer into the object store, fans `train_remote` out to actors on
    # cluster nodes, pumps the trampoline queue while training runs, and
    # re-hydrates rank-0 results/weights into the driver's model
    # (reference: ray_lightning/ray_ddp.py:169-193).  Here the actors are
    # per-host agent workers and the collective substrate is a
    # jax.distributed world formed before fit runs in each process.

    def _launch_plan(self) -> Optional[Dict[str, Any]]:
        if knobs.get_bool("RLA_TPU_INSIDE_WORKER"):
            return None  # already a fanned-out worker process
        if jax.process_count() > 1:
            return None  # already inside a formed distributed world
        return self.accelerator.launch_spec()

    def _spawn_platform(self, spec):
        """(env, platform, cpu_devices_per_process) for the fan-out
        workers.  CPU fan-out (tests / CI): each worker gets its share of
        virtual devices and gloo collectives.  The env var is honored even
        when a device plugin overrode the driver's own backend through
        jax.config."""
        env = {"RLA_TPU_INSIDE_WORKER": "1"}
        platform = cpu_per = None
        worker_platform = knobs.get_raw("RLA_TPU_WORKER_PLATFORM")
        if worker_platform:
            # explicit split: workers claim this platform while the
            # driver keeps its own backend -- the single-chip layout,
            # where the DRIVER must stay off the TPU so the worker's
            # device claim doesn't deadlock against the driver's
            platform = worker_platform
            env["JAX_PLATFORMS"] = worker_platform
            # driver-only XLA_FLAGS (e.g. host-platform device-count
            # overrides keeping the driver CPU-side) must not leak into
            # ANY worker platform -- a tpu/axon worker inheriting them
            # would carry driver-side XLA configuration onto the chip
            env["XLA_FLAGS"] = ""
            if worker_platform == "cpu":
                cpu_per = spec.get("devices_per_host") or 1
            return env, platform, cpu_per
        env_platform = os.environ.get("JAX_PLATFORMS",
                                      "").split(",")[0].lower()
        if env_platform == "cpu" or jax.default_backend() == "cpu":
            platform = "cpu"
            cpu_per = spec.get("devices_per_host") or 1
            env.update({"JAX_PLATFORMS": "cpu", "XLA_FLAGS": ""})
        return env, platform, cpu_per

    def _strip_for_shipment(self, module) -> None:
        """The fan-out payload must be free of live device/compiled
        objects: ship existing params as numpy (refit continuation works
        through the fan-out), and clear meshes / jitted fns / device
        caches a prior in-process fit left on the trainer and module."""
        if module.params is not None:
            module.params = jax.tree.map(
                lambda x: np.asarray(jax.device_get(x)), module.params)
        module.trainer = None  # rebound worker-side and on return
        self._release_compiled_state()
        self._mesh = None
        self._val_loader = None
        if getattr(module, "mesh", None) is not None:
            module.mesh = None
        if hasattr(module, "_jit_predict"):
            del module._jit_predict

    def _acquire_world(self, spec):
        """The persistent fan-out world for ``spec``: reused across
        fit/validate/test/predict (workers spawn ONCE, the
        jax.distributed world forms once -- the reference's actors live
        for the whole setup->teardown span, ray_ddp.py:99-121); respawned
        only when the spec changed or a prior run poisoned it.  Acquired
        BEFORE ``_strip_for_shipment``, so an unreachable agent raises
        while the driver's module/trainer are still intact."""
        from ..runtime.bootstrap import DistributedWorld

        n = spec["num_processes"]
        env, platform, cpu_per = self._spawn_platform(spec)
        key = (n, platform, cpu_per, tuple(sorted(env.items())),
               tuple(spec.get("agents") or ()))
        world = self._world
        if world is not None and (world.spec != key or not world.alive()):
            world.shutdown()
            world = self._world = None
        if world is None:
            world = DistributedWorld(n, platform, cpu_per, env,
                                     spec.get("agents"))
            self._world = world
        return world

    def _run_in_world(self, world, module, body, queue, stage="fit"):
        """One entry-point run over the persistent world.  A failed run
        poisons the world's collectives (DistributedWorld kills itself);
        re-bind the stripped driver objects so the caller's trainer/module
        still work locally afterwards.  Runs under hang-aware supervision
        when a per-attempt deadline (``worker_deadline_s``) or
        ``RLA_TPU_WEDGE_TIMEOUT_S`` is configured; a stalled run surfaces
        a machine-readable diagnosis on ``last_stall_diagnosis`` (and the
        log) before re-raising."""
        from ..runtime.watchdog import (WorkerWedged, stall_record)
        from ..testing import spmd_sanitizer
        self.last_stall_diagnosis = None
        # opt-in SPMD sanitizer (RLA_TPU_SPMD_SANITIZER): this run must
        # only ever be diffed against sequences ITS workers trace — not
        # a previous run's (or a smaller world's leftover) spills
        spmd_sanitizer.reset_world_collectives()
        try:
            results = world.run(body, queue=queue,
                                deadline_s=self.worker_deadline_s)
        except BaseException as e:
            self._world = None
            module.trainer = self
            self.module = module
            self.fitting = False
            if isinstance(e, (WorkerWedged, TimeoutError)):
                import json
                record = stall_record(e, stage)
                # fold in the watchdog's reap records (per-rank beat/busy
                # ages at kill time) gathered by the world
                reaps = list(getattr(world, "last_stall", []))
                if reaps and record.get("rank") is None:
                    record["rank"] = reaps[0].get("rank")
                record["reaped"] = reaps
                self.last_stall_diagnosis = record
                log.error("stall diagnosis: %s",
                          json.dumps(record, sort_keys=True, default=str))
                # the worst SPMD failure mode decoded: when the wedge's
                # real cause is a rank-divergent collective, the spilled
                # sequences disagree — surface the typed mismatch naming
                # the first divergent call instead of the generic wedge
                mismatch = None
                try:
                    mismatch = spmd_sanitizer.check_world_collectives(
                        raise_on_mismatch=False)
                except Exception:  # the postmortem must not mask e
                    pass
                if mismatch is not None:
                    self._write_failure_report(mismatch)
                    raise mismatch from e
            # postmortem artifact: the pool is already gone (world.run
            # kills it on failure), so rank timelines come from the
            # telemetry-dir spill files — the channel built to survive
            # exactly this
            self._write_failure_report(e)
            raise
        # even a run that COMPLETED may have traced divergent collective
        # sequences (divergence hangs only when the mismatched
        # collective actually executes) — diff the rank spills and
        # refuse to call it a success.  Unlike the except path, the
        # world is still ALIVE here: its workers traced poison, so end
        # it explicitly before surfacing the typed mismatch.
        mismatch = spmd_sanitizer.check_world_collectives(
            raise_on_mismatch=False)
        if mismatch is not None:
            try:
                world.shutdown()
            except Exception:
                pass
            self._world = None
            module.trainer = self
            self.module = module
            self.fitting = False
            self._write_failure_report(mismatch)
            raise mismatch
        return results

    def shutdown_workers(self) -> None:
        """End the persistent fan-out world (spawned agent workers + their
        jax.distributed world).  The explicit end of the reference's
        actor lifecycle (ray_ddp.py:109-121); idle worlds otherwise live
        until the driver process exits."""
        if self._world is not None:
            self._world.shutdown()
            self._world = None

    def _fit_via_launcher(self, spec, module, train_dataloaders,
                          val_dataloaders, datamodule, ckpt_path) -> None:
        import functools

        from ..runtime.queue import TrampolineQueue

        n = spec["num_processes"]
        log.warning("fanning fit out to %d processes via agents %s",
                    n, spec.get("agents"))
        # the trace was minted at fit() entry, before the trainer ships:
        # the pickled trainer carries it through the agent execute op,
        # so every worker's events and the driver's share one id
        telemetry.emit("fit_start", fanout=n)
        world = self._acquire_world(spec)
        # live telemetry plane: driver server + ClusterView over the
        # fan-out ranks — each worker's live /snapshot (portfile scrape
        # locally, the agent `live` wire op remotely) merges rank-
        # labeled into the driver's /metrics while the fit runs, and
        # the last collected view is embedded in run_report.json if
        # the run dies
        self._live_server = live_lib.maybe_start_from_env()
        if self._live_server is not None:
            self._live_server.sources.bind_trainer(self)
            self._cluster_view = live_lib.ClusterView(
                workers=list(world.pool.workers)).start()
            self._live_server.sources.bind_cluster_view(
                self._cluster_view)
        self._strip_for_shipment(module)

        queue = TrampolineQueue()
        # datasets ship ONCE per world (content-addressed worker cache);
        # a later test/predict/refit over the same data sends a key, not
        # the bytes
        try:
            body = functools.partial(
                _remote_fit_worker, self, module,
                world.ship_value(train_dataloaders),
                world.ship_value(val_dataloaders),
                world.ship_value(datamodule), ckpt_path)
            results = self._run_in_world(world, module, body, queue,
                                         stage="fit")
            if self._cluster_view is not None:
                # one deliberate final sweep while the world is still
                # up: a fit shorter than the refresh cadence must not
                # finish with an empty view (failure paths skip this —
                # the pool is already gone, and the periodic thread's
                # last successful view is exactly what we keep)
                try:
                    self._cluster_view.refresh()
                except Exception:
                    pass
        finally:
            # stop the refresh thread; the LAST collected view stays on
            # self._cluster_view for the failure report / later scrapes
            if self._cluster_view is not None:
                self._cluster_view.stop()

        # per-rank telemetry (profiler exports + event tails) shipped
        # home by every rank — build_metrics_registry merges them
        self._rank_telemetry = {
            i: (r or {}).get("telemetry") for i, r in enumerate(results)}
        telemetry.emit("fit_end", fanout=n)

        # re-hydrate rank-0 state into the driver's trainer + module
        # (reference: ray_ddp.py:185-193)
        r0 = results[0]
        module.params = r0["params"]
        module.trainer = self
        self.module = module
        self.global_step = r0["global_step"]
        self.current_epoch = r0["current_epoch"]
        self.epochs_completed = r0["epochs_completed"]
        self.callback_metrics = dict(r0["metrics"])
        for c in self.callbacks:
            st = r0["callbacks"].get(c.state_key)
            if st:
                c.load_state_dict(st)
        cb = self.checkpoint_callback
        if cb is not None and r0.get("best_model_path"):
            # valid on the driver under the shared-FS assumption the
            # reference also makes (SURVEY.md §5.4)
            cb.best_model_path = r0["best_model_path"]
        self.fitting = False

    def _eval_via_launcher(self, spec, module, dataloaders, datamodule,
                           stage: str):
        """validate/test/predict fanned out over host agents, exactly like
        fit (the reference routes test through the same accelerator
        machinery -- fit/test multi-call, reference: README.md:34-36,
        ray_lightning/ray_ddp.py:99-195).  Rank-0 metrics re-hydrate into
        the driver's trainer; predict outputs from every rank's sampler
        shard re-interleave into global dataset order."""
        import functools

        from ..runtime.queue import TrampolineQueue

        n = spec["num_processes"]
        log.warning("fanning %s out to %d processes via agents %s",
                    stage, n, spec.get("agents"))
        # eval fan-outs are runs too: a failure report from a fanned-out
        # validate/test/predict must carry ITS trace id, not a stale one
        self._bind_trace()
        world = self._acquire_world(spec)
        self._strip_for_shipment(module)

        queue = TrampolineQueue()
        body = functools.partial(_remote_eval_worker, self, module,
                                 world.ship_value(dataloaders),
                                 world.ship_value(datamodule), stage)
        results = self._run_in_world(world, module, body, queue,
                                     stage=stage)

        # eval fan-outs ship per-rank telemetry home exactly like fit
        # (_bind_trace cleared the previous run's; this stage is the run)
        self._rank_telemetry = {
            i: (r or {}).get("telemetry") for i, r in enumerate(results)}
        module.trainer = self
        self.module = module
        if stage == "predict":
            return _interleave_predictions(
                [r["outputs"] for r in results],
                total=results[0].get("dataset_len"))
        r0 = results[0]
        self.callback_metrics.update(r0["metrics"])
        return r0["results"]

    def fit(self, module: TpuModule,
            train_dataloaders=None, val_dataloaders=None,
            datamodule=None, ckpt_path: Optional[str] = None) -> None:
        try:
            # bound BEFORE anything that can raise: a failure in
            # launch-plan resolution must be reported under THIS run's
            # fresh trace, not the previous fit's id/telemetry
            self._bind_trace()
            if self.pipeline_stages > 1:
                return self._fit_mpmd(module, train_dataloaders,
                                      datamodule, ckpt_path)
            plan = self._launch_plan()
            if plan is not None:
                return self._fit_via_launcher(plan, module,
                                              train_dataloaders,
                                              val_dataloaders, datamodule,
                                              ckpt_path)
            return self._fit_local(module, train_dataloaders,
                                   val_dataloaders, datamodule, ckpt_path)
        except BaseException as e:
            # crash postmortem (telemetry/registry.py): a WorkerWedged,
            # Preempted or any uncaught fit exception leaves a
            # run_report.json under the run dir — the typed error plus
            # this process's event timeline and metric snapshot —
            # before re-raising untouched (_run_in_world may already
            # have written it; _write_failure_report dedupes)
            self._write_failure_report(e)
            raise

    def _bind_trace(self) -> None:
        """One fit = one trace id.  Inside a fanned-out worker the
        ambient id (stamped by ``_remote_fit_worker`` from the pickled
        trainer, or by the ``RLA_TPU_TRACE_ID`` env overlay at worker
        boot) wins, so driver and workers correlate; a driver fit mints
        a fresh id and makes it ambient for everything this process
        emits during the run."""
        if knobs.get_bool("RLA_TPU_INSIDE_WORKER"):
            # the driver's id arrives ambient (stamped by
            # _remote_fit_worker or the boot env overlay) or rides the
            # pickled trainer itself; mint only if neither made it over
            self.trace_id = (telemetry.current_trace_id() or self.trace_id
                             or telemetry.mint_trace_id())
        else:
            self.trace_id = telemetry.mint_trace_id()
            # one run = one registry: a later run's failure report must
            # not merge a previous fan-out's per-rank telemetry under
            # the fresh trace id
            self._rank_telemetry = {}
        telemetry.set_trace_id(self.trace_id)

    def _write_failure_report(self, exc: BaseException) -> None:
        """Best-effort ``run_report.json`` under ``default_root_dir``:
        never raises over the fit's real exception."""
        if knobs.get_bool("RLA_TPU_INSIDE_WORKER"):
            # only the driver writes the report: N failing ranks racing
            # one shared path would clobber the driver's complete report
            # with partial rank-local data mislabeled "driver" — worker
            # failures reach the driver typed over the pipe and their
            # events via the spill dir
            return
        if getattr(exc, "_rla_report_written", False):
            return  # _run_in_world already wrote this failure's report
        try:
            from ..telemetry import registry as treg
            extra: Dict[str, Any] = {"global_step": self.global_step,
                                     "epoch": self.current_epoch}
            if self._cluster_view is not None:
                # the last LIVE view collected before death: per-rank
                # health/step/serve rows the spill files don't carry
                try:
                    extra["cluster_view"] = \
                        self._cluster_view.last_view()
                except Exception:
                    pass
            treg.write_run_report(
                os.path.join(self.default_root_dir, "run_report.json"),
                error=exc, trace_id=self.trace_id,
                rank_events=treg.gather_spill_dir(),
                stall_diagnosis=self.last_stall_diagnosis,
                registry=self.build_metrics_registry(),
                extra=extra)
            try:
                exc._rla_report_written = True
            except Exception:
                pass  # __slots__ exceptions: worst case a double write
        except BaseException as e:
            log.warning("failed to write fit run report: %s", e)

    def build_metrics_registry(self) -> "Any":
        """This run's unified :class:`~..telemetry.registry
        .MetricsRegistry`: the driver profiler (spans, prefetch
        counters/gauges, comms wire record), every fanned-out rank's
        profiler export (merged with reservoir-correct semantics),
        this process's flight-recorder event tallies and the backend
        compile count.  Serve metrics join via
        ``registry.add_serve(engine.metrics)`` — serving runs outside
        the trainer."""
        from ..telemetry.registry import MetricsRegistry
        reg = MetricsRegistry(trace_id=self.trace_id)
        if self.profiler is not None:
            reg.add_profiler(self.profiler, rank="driver")
        elif self.comms_per_step:
            # no profiler attached: the comms record still belongs in
            # the export (it is analytic, computed at compile time)
            from ..utils.profiler import Profiler
            p = Profiler()
            p.record_comms(self.comms_per_step)
            reg.add_profiler(p, rank="driver")
        for rank, snap in self._rank_telemetry.items():
            if not snap:
                continue
            if snap.get("profiler"):
                reg.add_profiler(snap["profiler"], rank=rank)
            if snap.get("events"):
                reg.add_events(snap["events"], rank=rank)
        reg.add_events(telemetry.get_recorder().events(), rank="driver")
        try:
            reg.add_compile_count(rank="driver")
        except BaseException:  # monitoring unavailable: export without it
            pass
        if self.perf is not None:
            # perf-observatory ledgers (telemetry/perf.py): step
            # timeline + HBM pools (+ goodput when one was fed)
            self.perf.register(reg)
        if self._cluster_view is not None:
            # live per-rank view (telemetry/live.py): rank-labeled
            # health/step rows always; mergeable data only for ranks
            # whose final telemetry did NOT already ship home above
            try:
                self._cluster_view.merge_into(
                    reg, skip_mergeables=[
                        k for k, v in self._rank_telemetry.items()
                        if v])
            except Exception as e:
                log.warning("cluster-view merge failed: %s", e)
        return reg

    def _fit_mpmd(self, module: TpuModule, train_dataloaders=None,
                  datamodule=None, ckpt_path: Optional[str] = None) -> None:
        """MPMD pipeline fit: the training loop is owned by a
        ``parallel/mpmd`` :class:`PipelineRunner` — S stage groups of
        worker processes running the 1F1B/GPipe tick program, microbatch
        activations crossing stages through the shared-memory object
        store, failures attributed to (and replayed within) the faulting
        stage's budget.  The trainer contributes batch collection, the
        run trace, and surfaces the runner's summary (losses, measured
        vs analytic bubble, per-stage budgets) through
        ``self.pipeline_summary`` / ``callback_metrics``."""
        from ..parallel.mpmd.driver import PipelineRunner
        if ckpt_path is not None:
            raise ValueError(
                "ckpt_path is not supported with pipeline_stages > 1: the "
                "pipeline runner manages its own per-stage checkpoints "
                "(and replay) under default_root_dir")
        if datamodule is not None:
            datamodule.setup("fit")
            train_dataloaders = (train_dataloaders
                                 or datamodule.train_dataloader())
        if train_dataloaders is None:
            raise ValueError("fit() needs train_dataloaders or a datamodule")
        self.fitting = True
        self.module = module
        module.trainer = self
        # one pass per epoch over the loader, bounded exactly like the
        # local loop: limit_train_batches per epoch, max_steps overall
        batches: List[Any] = []
        for _ in range(self.max_epochs or 1):
            for i, batch in enumerate(train_dataloaders):
                if (self.limit_train_batches is not None
                        and i >= self.limit_train_batches):
                    break
                batches.append(batch)
                if (self.max_steps is not None
                        and len(batches) >= self.max_steps):
                    break
            if self.max_steps is not None and len(batches) >= self.max_steps:
                break
        runner = PipelineRunner(
            module, num_stages=self.pipeline_stages,
            num_workers=getattr(self.accelerator, "num_workers", None),
            schedule=self.pipeline_schedule,
            num_microbatches=self.pipeline_microbatches,
            seed=self.seed, workdir=self.default_root_dir,
            wedge_timeout_s=self.worker_deadline_s)
        try:
            summary = runner.run(batches)
        finally:
            runner.shutdown()
        self.pipeline_summary = summary
        self.trace_id = summary["trace_id"]
        self.global_step = len(summary["steps"])
        if summary["losses"]:
            self.callback_metrics["train_loss"] = float(
                summary["losses"][-1])
        self.fitting = False

    def _fit_local(self, module: TpuModule,
                   train_dataloaders=None, val_dataloaders=None,
                   datamodule=None, ckpt_path: Optional[str] = None
                   ) -> None:
        self.accelerator.validate_process_topology()
        t0 = time.perf_counter()
        live_resume = ckpt_path == "live"
        if live_resume and (self._state is None or self.module is None):
            raise ValueError(
                "ckpt_path='live' continues from in-memory state; call "
                "fit() (and optionally resize_in_memory()) first")
        self.fitting = True
        self.should_stop = False
        if not live_resume:
            self.current_epoch = 0
            self.epochs_completed = 0
            self.global_step = 0
        else:
            # a live continuation KEEPS its counters, but like a
            # checkpoint restore it re-enters the epoch that was cut
            # short: only COMPLETED epochs count, so the sampler replays
            # the interrupted epoch's permutation rather than skipping
            # to the next one (keeps the live path's trajectory
            # identical to the restore path's)
            self.current_epoch = self.epochs_completed
        self._last_val_step = -1  # stale values skip epoch-end validation
        self.module = module
        module.trainer = self
        module.compute_dtype = self.compute_dtype
        if self.int8_matmul:
            module.int8_matmul = True

        if datamodule is not None:
            datamodule.setup("fit")
            train_dataloaders = train_dataloaders or datamodule.train_dataloader()
            val_dataloaders = val_dataloaders or datamodule.val_dataloader()
        if train_dataloaders is None:
            raise ValueError("fit() needs train_dataloaders or a datamodule")
        train_loader = train_dataloaders
        self._val_loader = val_dataloaders

        self.accelerator.setup_environment()
        self._mesh = self.accelerator.build_mesh()
        self._bind_preemption()
        # numeric anomaly guardian (runtime/guardian.py): host companion
        # for blame attribution + the quarantine ledger; chaos numeric
        # faults (testing/chaos.py) parsed once per fit
        from ..runtime import guardian as guardian_lib
        from ..testing import chaos as chaos_lib
        self._chaos_numeric = chaos_lib.numeric_faults()
        self._guardian = (guardian_lib.Guardian(self.guard,
                                                self.default_root_dir)
                          if self.guard is not None else None)
        # live telemetry plane: the per-process server starts once (when
        # RLA_TPU_METRICS_PORT is configured — on workers it was already
        # started at boot) and this fit's trainer becomes its live
        # source, so /metrics answers with the run's CURRENT registry
        # while steps are still running
        self._live_server = live_lib.maybe_start_from_env()
        if self._live_server is not None:
            self._live_server.sources.bind_trainer(self)
        telemetry.emit("fit_start", step=self.global_step,
                       processes=jax.process_count())

        # sampler auto-injection (reference: ray_ddp.py:280-295)
        if self.accelerator.require_distributed_sampler:
            kwargs = self.accelerator.distributed_sampler_kwargs()
            if isinstance(train_loader, DataLoader):
                # preserve the user's shuffle intent (PTL-style replacement)
                train_loader._inject_sampler(shuffle=train_loader.shuffle,
                                             **kwargs)
            if isinstance(self._val_loader, DataLoader):
                self._val_loader._inject_sampler(shuffle=False, **kwargs)

        # state init / restore
        if live_resume:
            # continue from the LIVE state (a prior fit, possibly after
            # resize_in_memory): no fresh TrainState, no disk read —
            # self._tx is kept because the live opt_state was built
            # against it
            state = self._state
        else:
            rng = rng_from_seed(self.seed)
            init_rng, state_rng = jax.random.split(rng)
            self._tx = self._build_tx(module)
            # a module that already carries weights (prior fit / manual
            # load) continues from them -- the reference's re-hydrated
            # driver model behaves the same way on a second fit
            # (ray_ddp.py:185-189)
            init_params = (module.params if module.params is not None
                           else module.init_params(init_rng))
            state = TrainState.create(init_params, self._tx, state_rng)
            if self.grad_compression is not None:
                residual, grad_accum = self._fresh_exchange_buffers(
                    module, init_params, self._mesh)
                state = state.replace(residual=residual,
                                      grad_accum=grad_accum)
        if self.guard is not None and \
                getattr(state, "guard_ema", None) is None:
            # fresh guard vector; a restore below reconciles against this
            # template (older guard-less checkpoints keep it fresh)
            state = state.replace(
                guard_ema=jnp.asarray(guardian_lib.fresh_state()))
        for c in self.callbacks:
            c.setup(self, module, "fit")
        if not live_resume:
            if ckpt_path == "last":
                # crash-recovery anchor: resume from the newest
                # checkpoint under the run dir, or start fresh when none
                # exists yet (capability the reference lacks, SURVEY.md
                # §5.4)
                ckpt_path = ckpt_lib.latest_checkpoint(
                    self.default_root_dir)
                if ckpt_path is None:
                    log.warning("ckpt_path='last': no checkpoint under "
                                "%s; starting fresh",
                                self.default_root_dir)
            if ckpt_path is not None:
                with self._perf_phase("ckpt"):  # restore cost is a phase
                    state = self._restore(ckpt_path, state)
                if self.guard is not None and \
                        getattr(state, "guard_ema", None) is not None:
                    # a restore (including the guardian's own rewind)
                    # restarts the guard fresh: a sticky trip that was
                    # checkpointed must not re-raise on the first post-
                    # rewind readback
                    state = state.replace(
                        guard_ema=jnp.asarray(guardian_lib.fresh_state()))

        example_batch = next(iter(train_loader))
        self._example_batch = example_batch
        self._check_batch(example_batch)
        self._build_device_cache(train_loader)
        self._compile(module, state, example_batch)

        # place state on mesh with its shardings
        state = jax.device_put(state, self._state_shardings)
        self._state = state
        if self.perf is not None:
            self._register_hbm_pools()

        for c in self.callbacks:
            c.on_fit_start(self, module)

        # optional sanity val steps (reference Tune callback skips these,
        # ray_lightning/tune.py:79-81)
        if self.num_sanity_val_steps and self._val_loader is not None:
            self.sanity_checking = True
            self._run_eval(self._val_loader, self._eval_step_fn,
                           limit=self.num_sanity_val_steps, prefix=None)
            self.sanity_checking = False

        train_metrics: Dict[str, Any] = {}
        use_scan = self._can_scan_epoch()
        while not self._done():
            for c in self.callbacks:
                c.on_train_epoch_start(self, module)
            if hasattr(train_loader, "set_epoch"):
                train_loader.set_epoch(self.current_epoch)

            if use_scan:
                state, train_metrics, complete = self._run_scanned_epoch(
                    state, train_loader)
                if complete:
                    self.epochs_completed = self.current_epoch + 1
                self._after_train_epoch(module, train_metrics)
                # the scanned epoch is ONE dispatch -- un-interruptible
                # mid-flight by design, so the drain granularity here is
                # the epoch boundary (checked unconditionally: epoch ends
                # are rare and SPMD-consistent, and gating them on the
                # per-step modulo could defer the drain past the grace)
                self._maybe_drain_preemption(every_step=True)
                continue

            if self._device_cache is not None:
                source = self._cached_epoch_source(train_loader)
            elif self.prefetch_batches:
                # the pipeline's own data_fetch accounting replaces
                # _iter_profiled: the fetch happens on the producer thread
                source = (("host", b) for b in train_loader)
            else:
                source = (("host", b)
                          for b in self._iter_profiled(train_loader))
            # guardian seams, applied to the HOST-ORDER stream before
            # prefetch placement: quarantined batch indices become
            # ("skip", None) sentinels (a pure function of the ledger —
            # identical on every rank and every restart), and badbatch
            # chaos poisons the batch feeding its 1-based global step
            skip = (self._guardian.skip_set(self.current_epoch)
                    if self._guardian is not None else set())
            badbatch = tuple(f for f in self._chaos_numeric
                             if f.kind == "badbatch")
            if skip or badbatch:
                source = self._wrap_fit_source(source, skip, badbatch,
                                               self.global_step)
            pf = None
            if self.prefetch_batches:
                if self.limit_train_batches is not None:
                    # bound the producer at the epoch's redefined length so
                    # it never pulls (or places) past the limit break
                    source = itertools.islice(source,
                                              self.limit_train_batches)
                pf = prefetch_lib.prefetch_pipeline(
                    source, self.prefetch_batches, self._place_train_item,
                    self.profiler, name="rla-prefetch-fit")
                source = pf
                if self.perf is not None:
                    # in-flight placed batches are real HBM: attribute
                    # them (re-registered per epoch — the pipeline is
                    # rebuilt each time; a closed pipeline reads empty)
                    self.perf.hbm.register_pool("prefetch",
                                                pf.placed_bytes)
            try:
                for batch_idx, (kind, payload) in enumerate(source):
                    if (self.limit_train_batches is not None
                            and batch_idx >= self.limit_train_batches):
                        break
                    if kind == "skip":
                        # quarantined window (runtime/guardian.py): the
                        # batch never dispatches and global_step does not
                        # advance; batch_idx keeps counting so the epoch
                        # enumeration matches the clean run's loader order
                        continue
                    state, train_metrics = self._fit_step(
                        state, kind, payload, pf, module, batch_idx)
                    if (self.val_check_interval
                            and self._val_loader is not None
                            and self.global_step % self.val_check_interval
                            == 0):
                        self._guard_flush(train_metrics)
                        self._mid_epoch_validation(module)
                        self._last_val_step = self.global_step
                    # step-boundary preemption poll: drains into an
                    # emergency checkpoint + typed Preempted (no-op when
                    # no grace budget is configured)
                    self._maybe_drain_preemption()
                    if self.max_steps and self.global_step >= self.max_steps:
                        self.should_stop = True
                        break
                    if self.max_time is not None and \
                            time.perf_counter() - t0 >= self.max_time:
                        self.should_stop = True
                        break
                else:
                    # epoch ran to the end of its loader (a max_steps break
                    # leaves the epoch incomplete for checkpoint accounting;
                    # limit_train_batches redefines the epoch, handled above
                    # by `break` too -- treat it as complete)
                    self.epochs_completed = self.current_epoch + 1
            finally:
                # EVERY way out of the epoch (limit_train_batches,
                # max_steps, max_time, mid-step exceptions) must stop and
                # join the producer thread -- a leaked non-daemon thread
                # hangs interpreter shutdown (conftest guards this)
                if pf is not None:
                    pf.close()
            if (self.limit_train_batches is not None
                    and not self.should_stop):
                self.epochs_completed = self.current_epoch + 1
            self._after_train_epoch(module, train_metrics)

        # re-hydrate weights into the user's module on the driver
        # (reference: ray_ddp.py:185-189)
        self._state = state
        module.params = jax.device_get(state.params)
        for c in self.callbacks:
            c.on_fit_end(self, module)
        if self.checkpoint_format == "sharded-async":
            from ..utils import sharded_checkpoint as sharded_lib
            with self._perf_phase("ckpt"):  # checkpoint fence
                sharded_lib.wait_until_finished()  # fence in-flight saves
        if self._guardian is not None:
            # the fit ran CLEAN to the end: newer verified checkpoints now
            # cover the quarantined window, so the rewind anchor's prune
            # protection can go (the skip entries stay — the data is
            # still bad)
            self._guardian.release_anchor()
        self.fitting = False
        if isinstance(self.logger, CSVLogger):
            self.logger.finalize()
        self.fit_duration_s = time.perf_counter() - t0
        telemetry.emit("fit_end", step=self.global_step,
                       epochs=self.epochs_completed,
                       duration_s=round(self.fit_duration_s, 3))

    def _register_hbm_pools(self) -> None:
        """Bind the perf observatory's HBM ledger to this run's state:
        per-pool readers over the live ``TrainState`` (params, optimizer
        state, compressed-exchange buffers) and the device dataset
        cache.  Readers tolerate released state (0, never a crash) and
        re-registering on a later fit replaces them.  One eager sample
        lands the post-placement watermark before the loop starts."""
        from ..telemetry.perf import tree_nbytes
        hbm = self.perf.hbm

        def field_bytes(*fields):
            def read():
                st = self._state
                if st is None:
                    return 0
                return sum(tree_nbytes(getattr(st, f, None))
                           for f in fields)
            return read

        hbm.register_pool("params", field_bytes("params"))
        hbm.register_pool("opt_state", field_bytes("opt_state"))
        hbm.register_pool("exchange_buffers",
                          field_bytes("residual", "grad_accum"))
        hbm.register_pool("device_cache",
                          lambda: tree_nbytes(self._device_cache))
        hbm.sample()

    def _wrap_fit_source(self, source, skip, badbatch_faults,
                         start_step: int):
        """Guardian/chaos wrap over the host-order fit source (runs on
        the PRODUCER side, before any device placement): quarantined
        batch indices yield ``("skip", None)`` sentinels — these pass
        through ``_place_train_item`` untouched and the fit loop drops
        them without advancing ``global_step`` — and ``badbatch`` chaos
        poisons the host batch that will run as its 1-based global step
        (claimed through the chaos namespace so a post-rewind replay of
        the window stays clean)."""
        from ..testing import chaos as chaos_lib

        def gen():
            dispatched = 0
            for i, item in enumerate(source):
                if i in skip:
                    yield ("skip", None)
                    continue
                dispatched += 1
                kind, payload = item
                if kind == "host":
                    for f in badbatch_faults:
                        if (f.step or 1) == start_step + dispatched and \
                                chaos_lib.claim_numeric(f):
                            payload = chaos_lib.poison_batch(payload)
                yield (kind, payload)

        return gen()

    def _guard_check(self, guard_host) -> None:
        """Hand one already-materialized guard row to the guardian (no-op
        while healthy; raises ``NumericAnomaly`` on a sticky trip)."""
        if self._guardian is None or guard_host is None:
            return
        self._guardian.check(
            guard_host, replay=self._build_guard_replay(),
            compression_active=(self.grad_compression is not None
                                or self.int8_matmul))

    def _guard_flush(self, train_metrics) -> None:
        """Materialize ONLY the guard vector and check it — the fence
        before anything durable (mid-epoch validation checkpoints) can
        observe post-anomaly state.  Gated on validation boundaries, so
        the hot loop stays sync-free."""
        if self._guardian is None or not isinstance(train_metrics, dict):
            return
        g = train_metrics.get("guard")
        if g is None:
            return
        # graftlint: ok(host-sync) — validation-boundary fence
        self._guard_check(jax.device_get(g))

    def _build_guard_replay(self):
        """Blame replay for the guardian (cold path, runs only on a
        trip): recompute loss + grads for the suspect batch with NO
        compressed exchange and NO int8 matmuls — a plain eager
        value_and_grad on the current params.  The guardian splits
        data-poisoned (reproduces plain) from exchange-induced
        (reproduces only compressed) from nondeterministic/SDC (does not
        reproduce) on its result."""
        module, state = self.module, self._state
        if module is None or state is None:
            return None

        def replay(payload):
            int8_prev = getattr(module, "int8_matmul", False)
            module.int8_matmul = False
            try:
                def lf(params):
                    out = module.training_step(
                        params, payload,
                        jax.random.fold_in(state.rng, state.step))
                    return out[0] if isinstance(out, tuple) else out

                loss, grads = jax.value_and_grad(lf)(state.params)
                gn = optax.global_norm(grads)
                # graftlint: ok(host-sync) — post-trip cold path
                loss_h, gn_h = jax.device_get((loss, gn))
            finally:
                module.int8_matmul = int8_prev
            # loss_h/gn_h are host scalars (device_get above) and this
            # replay runs only on the post-trip cold path
            bad_loss = not bool(np.isfinite(loss_h))  # graftlint: ok(host-sync) — host scalar
            bad_grad = not bool(np.isfinite(gn_h))  # graftlint: ok(host-sync) — host scalar
            return {"loss_nonfinite": bad_loss, "grad_nonfinite": bad_grad}

        return replay

    def _fit_step(self, state, kind, payload, pf, module,
                  batch_idx: int):
        """ONE optimizer step of the fit loop: place the batch, run the
        compiled step, fire per-batch callbacks, log on the cadence.

        This is the hot path graftlint's ``host-sync`` rule roots at
        (with ``_run_scanned_epoch``): everything here dispatches async
        — the only device->host materialization is the log-interval-
        gated metrics readback below, and the compile-guard test pins
        the whole loop to zero retraces after warmup (perf observatory
        attached or not).  The step-timeline bracket and the throttled
        HBM sample are host scalars/metadata only."""
        tl = self.perf.timeline if self.perf is not None else None
        if tl is not None:
            tl.step_begin()
        if self._guardian is not None:
            # host refs only (no device work): what the step about to run
            # as global step `global_step` consumes — the blame lookback
            self._guardian.note_step(self.global_step, self.current_epoch,
                                     batch_idx, kind, payload)
        try:
            if kind == "cached_local":
                # synchronous path (prefetch off): the pipeline's
                # _place_train_item does this conversion otherwise
                with self._span("h2d", phase="h2d"):
                    kind, payload = ("cached",
                                     self._put_index_row(payload))
            if kind == "cached":
                with self._span("train_step", phase="compute") as h:
                    state, train_metrics = self._train_step_cached_fn(
                        state, self._device_cache, payload)
                    if h is not None:
                        h.set(train_metrics)
            else:
                if pf is None:
                    with self._span("h2d", phase="h2d"):
                        batch = self._put_batch(payload)
                else:
                    batch = payload  # placed by the pipeline
                with self._span("train_step", phase="compute") as h:
                    state, train_metrics = self._train_step_fn(
                        state, batch)
                    if h is not None:
                        h.set(train_metrics)
            self.global_step += 1
            self._state = state
            # flight-recorder step event: host ints only (graftlint pins
            # this path sync-free; a device value here would also be one)
            telemetry.emit("train_step", step=self.global_step,
                           batch=batch_idx, epoch=self.current_epoch)
            for c in self.callbacks:
                c.on_train_batch_end(self, module, train_metrics,
                                     batch_idx)
            if self.global_step % self.log_every_n_steps == 0:
                # graftlint: ok(host-sync) — log-interval-gated readback
                host = jax.device_get(train_metrics)  # graftlint: ok(host-sync) — gated above
                guard_row = host.pop("guard", None)
                self._guard_check(guard_row)
                self._log_now({f"{k}": float(v) for k, v in host.items()})
            return state, train_metrics
        finally:
            if tl is not None:
                tl.step_end()
            if self.perf is not None:
                self.perf.hbm.maybe_sample()

    def _after_train_epoch(self, module, train_metrics) -> None:
        """Epoch epilogue shared by the step loop and the scanned path:
        harvest metrics, run epoch-boundary validation, fire callbacks,
        advance the epoch counter."""
        if train_metrics:
            # graftlint: ok(host-sync) — epoch-boundary readback
            host = jax.device_get(train_metrics)
            guard_row = host.pop("guard", None)
            # fence FIRST: a sticky trip must raise before checkpoint /
            # early-stop callbacks can act on post-anomaly state
            self._guard_check(guard_row)
            self.callback_metrics.update(
                {k: float(v) for k, v in host.items()})

        run_val = (self._val_loader is not None and
                   (self.current_epoch + 1) % self.check_val_every_n_epoch
                   == 0)
        if run_val and getattr(self, "_last_val_step", -1) == self.global_step:
            # a val_check_interval pass just ran at this exact step;
            # don't validate the same params twice (double-counts
            # EarlyStopping patience and ModelCheckpoint saves)
            run_val = False
        if run_val:
            for c in self.callbacks:
                c.on_validation_start(self, module)
            with self._span("validation", phase="validation"):
                val_metrics = self._run_eval(self._val_loader,
                                             self._eval_step_fn,
                                             limit=self.limit_val_batches,
                                             prefix=None)
            self.callback_metrics.update(val_metrics)
            self._log_now(val_metrics)
            module.on_validation_epoch_end()
            for c in self.callbacks:
                c.on_validation_end(self, module)
            telemetry.emit("validation", step=self.global_step,
                           epoch=self.current_epoch)
        for c in self.callbacks:
            c.on_train_epoch_end(self, module)
        if not run_val and self._val_loader is None:
            # checkpoint/early-stop callbacks keyed on validation_end
            # still fire once per epoch on train metrics
            for c in self.callbacks:
                c.on_validation_end(self, module)
        self.current_epoch += 1
        telemetry.emit("epoch_end", epoch=self.current_epoch,
                       step=self.global_step)
        if self.enable_progress_bar:
            log.warning("epoch %d done (step %d) metrics=%s",
                        self.current_epoch, self.global_step,
                        {k: round(v, 5) for k, v in
                         self.callback_metrics.items()})

    def _mid_epoch_validation(self, module) -> None:
        """Validation pass at a step boundary (val_check_interval); fires
        the same callbacks as epoch-boundary validation so checkpointing /
        early stopping / Tune reporting see mid-epoch metrics."""
        for c in self.callbacks:
            c.on_validation_start(self, module)
        with self._span("validation", phase="validation"):
            val_metrics = self._run_eval(self._val_loader,
                                         self._eval_step_fn,
                                         limit=self.limit_val_batches,
                                         prefix=None)
        self.callback_metrics.update(val_metrics)
        self._log_now(val_metrics)
        module.on_validation_epoch_end()
        for c in self.callbacks:
            c.on_validation_end(self, module)

    def _span(self, name: str, phase: Optional[str] = None):
        """Profiler span, or a null context when no profiler is attached
        (XLA async dispatch makes spans the only honest timing surface --
        SURVEY.md §5.1 build note).  ``phase`` additionally feeds the
        perf observatory's step timeline (one extra perf_counter pair —
        the <50us/emit budget the overhead test pins)."""
        tl = self.perf.timeline if self.perf is not None else None
        if tl is None or phase is None:
            if self.profiler is not None:
                return self.profiler.span(name)
            import contextlib
            return contextlib.nullcontext()
        return self._phased_span(name, tl, phase)

    @contextmanager
    def _phased_span(self, name: str, tl, phase: str):
        t0 = time.perf_counter()
        try:
            if self.profiler is not None:
                with self.profiler.span(name) as h:
                    yield h
            else:
                yield None
        finally:
            tl.observe(phase, time.perf_counter() - t0)

    def _perf_phase(self, phase: str):
        """Timeline-only phase context (checkpoint saves/restores,
        preemption drains) — a no-op without an observatory."""
        if self.perf is not None:
            return self.perf.timeline.phase(phase)
        import contextlib
        return contextlib.nullcontext()

    def _iter_profiled(self, loader):
        """Iterate a loader, timing each fetch under a 'data_fetch' span."""
        if self.profiler is None:
            yield from loader
            return
        it = iter(loader)
        while True:
            with self.profiler.span("data_fetch"):
                try:
                    batch = next(it)
                except StopIteration:
                    return
            yield batch

    def _done(self) -> bool:
        if self.should_stop:
            return True
        if self.max_epochs is not None and self.current_epoch >= self.max_epochs:
            return True
        if self.max_steps is not None and self.global_step >= self.max_steps:
            return True
        return False

    def _check_batch(self, batch) -> None:
        # the loader yields per-process batches; each must split evenly over
        # this process's share of the data-parallel axis
        dp = mesh_lib.data_parallel_size(self._mesh)
        dp_local = max(1, dp // jax.process_count())
        for leaf in jax.tree.leaves(batch):
            n = np.shape(leaf)[0]
            if n % dp_local != 0:
                if self._resumed_world_resize is not None:
                    # the ONE thing an elastic resize genuinely cannot
                    # re-shard: the batch no longer divides the new
                    # data-parallel world -- typed, so orchestration can
                    # tell "pick a compatible size" from a plain config
                    # error
                    from ..runtime.elastic import ElasticResizeError
                    saved_dp, cur_dp = self._resumed_world_resize
                    raise ElasticResizeError(
                        f"cannot resume at the new world size: batch dim "
                        f"{n} is not divisible by the data-parallel size "
                        f"{dp_local} of the shrunk mesh (checkpoint saved "
                        f"at dp={saved_dp}, resuming at dp={cur_dp}); "
                        f"adjust batch_size or the worker count")
                raise ValueError(
                    f"global batch dim {n} not divisible by data-parallel "
                    f"size {dp_local}; adjust batch_size or drop_last")

    def _log_now(self, metrics: Dict[str, float],
                 step: Optional[int] = None) -> None:
        if self.logger is not None and metrics and jax.process_index() == 0:
            self.logger.log_metrics(
                metrics, self.global_step if step is None else step)

    # ------------------------------------------------------------------ #
    # eval loops                                                         #
    # ------------------------------------------------------------------ #
    def ema_params(self):
        """The EMA parameter pytree (device arrays), or None when
        ema_decay is not set."""
        from ..utils.ema import ema_params as _extract
        if self._state is None:
            return None
        return _extract(self._state.opt_state)

    def _run_eval(self, loader, step_fn, limit=None,
                  prefix: Optional[str] = None) -> Dict[str, float]:
        params = self._state.params
        if self.ema_eval:
            averaged = self.ema_params()
            if averaged is not None:
                params = averaged
        sums: Dict[str, float] = {}
        weights = 0.0
        device_metrics = []

        def place(batch):
            # per-sample weight from the HOST batch, then device placement
            n = np.shape(jax.tree.leaves(batch)[0])[0]
            return n, self._put_batch(batch)

        source = iter(loader)
        if limit is not None:
            # bound the source (not a mid-loop break) so the pipeline
            # never pulls or places batches past the limit
            source = itertools.islice(source, limit)
        pf = None
        if self.prefetch_batches:
            pf = prefetch_lib.prefetch_pipeline(
                source, self.prefetch_batches, place, self.profiler,
                name="rla-prefetch-eval")
            source = pf
        else:
            source = map(place, source)
        try:
            for n, batch in source:
                device_metrics.append((n, step_fn(params, batch)))
        finally:
            if pf is not None:
                pf.close()
        for n, m in device_metrics:  # single host sync for the whole loop
            m = jax.device_get(m)
            for k, v in m.items():
                key = f"{prefix}{k}" if prefix else k
                sums[key] = sums.get(key, 0.0) + float(v) * n
            weights += n
        return {k: v / max(weights, 1.0) for k, v in sums.items()}

    def _ensure_eval_state(self, module, dataloaders, stage: str):
        """Bind the module, build the mesh, inject the eval sampler, and
        make sure compiled step fns + a sharded state exist (compiling
        from the module's params when this trainer never fit).  Returns
        the loader to iterate: a one-shot iterable is materialized first,
        because the compile probe consumes its head batch."""
        # A different module (or one whose params were swapped after fit)
        # must be evaluated on ITS weights, not a stale fit state.
        if self._state is not None and module is not self.module:
            self._state = None
        self.module = module
        module.trainer = self
        module.compute_dtype = self.compute_dtype
        self.accelerator.setup_environment()
        self._mesh = self.accelerator.build_mesh()
        if isinstance(dataloaders, DataLoader) and \
                self.accelerator.require_distributed_sampler:
            dataloaders._inject_sampler(
                shuffle=False, **self.accelerator.distributed_sampler_kwargs())
        if self._state is None:
            if module.params is None:
                raise RuntimeError(
                    f"{stage}() before fit(): module has no params")
            self._tx = self._build_tx(module)
            state = TrainState.create(module.params, self._tx,
                                      rng_from_seed(self.seed))
            if not isinstance(dataloaders, DataLoader) and \
                    not hasattr(dataloaders, "__len__"):
                dataloaders = list(dataloaders)  # one-shot iterable
            example = next(iter(dataloaders))
            self._compile(module, state, example)
            self._state = jax.device_put(state, self._state_shardings)
        return dataloaders

    def _eval_entry(self, module, dataloaders, step_fn_name: str,
                    stage: str) -> List[Dict[str, float]]:
        dataloaders = self._ensure_eval_state(module, dataloaders, stage)
        step_fn = getattr(self, step_fn_name)
        if stage == "validate":
            for c in self.callbacks:
                c.on_validation_start(self, module)
        limit = (self.limit_val_batches if stage != "test" else None)
        metrics = self._run_eval(dataloaders, step_fn, limit=limit)
        self.callback_metrics.update(metrics)
        for c in self.callbacks:
            if stage == "test":
                c.on_test_end(self, module)
            elif stage == "validate":
                c.on_validation_end(self, module)
        telemetry.emit("validation", stage=stage, step=self.global_step)
        return [metrics]

    def validate(self, module: TpuModule, dataloaders=None,
                 datamodule=None) -> List[Dict[str, float]]:
        plan = self._launch_plan()
        if plan is not None:
            return self._eval_via_launcher(plan, module, dataloaders,
                                           datamodule, "validate")
        if datamodule is not None:
            datamodule.setup("validate")
            dataloaders = dataloaders or datamodule.val_dataloader()
        return self._eval_entry(module, dataloaders, "_eval_step_fn",
                                "validate")

    def test(self, module: TpuModule, dataloaders=None,
             datamodule=None) -> List[Dict[str, float]]:
        plan = self._launch_plan()
        if plan is not None:
            return self._eval_via_launcher(plan, module, dataloaders,
                                           datamodule, "test")
        if datamodule is not None:
            datamodule.setup("test")
            dataloaders = dataloaders or datamodule.test_dataloader()
        return self._eval_entry(module, dataloaders, "_test_step_fn", "test")

    def predict(self, module: TpuModule, dataloaders=None,
                datamodule=None) -> List[Any]:
        plan = self._launch_plan()
        if plan is not None:
            return self._eval_via_launcher(plan, module, dataloaders,
                                           datamodule, "predict")
        if datamodule is not None:
            datamodule.setup("predict")
            dataloaders = dataloaders or datamodule.predict_dataloader()
        if jax.process_count() > 1:
            # inside a fanned-out world each rank predicts its OWN strided
            # sampler shard locally (outputs must stay fully addressable
            # for the driver-side re-interleave); the global batch
            # sharding below would misread the local shard as the whole
            # batch and produce non-addressable outputs
            self.module = module
            module.trainer = self
            self.accelerator.setup_environment()
            self._mesh = self.accelerator.build_mesh()
            params = (self._state.params if self._state is not None
                      else module.params)
            if params is None:
                raise RuntimeError(
                    "predict() before fit(): module has no params")
            predict = jax.jit(module.predict_step)
            source, pf = dataloaders, None
            if self.prefetch_batches:
                # host-side prefetch only: each rank's batches stay fully
                # addressable (the jit places them), so overlapping the
                # loader fetch is the whole win here
                pf = prefetch_lib.PrefetchIterator(
                    dataloaders, self.prefetch_batches,
                    profiler=self.profiler, name="rla-prefetch-predict")
                source = pf
            try:
                return [jax.device_get(predict(params, batch))
                        for batch in source]
            finally:
                if pf is not None:
                    pf.close()
        # single process: same mesh-aware path as every other stage -- the
        # batch lands with _batch_sharding (data-axis sharded on a
        # multi-device mesh) and runs through the compiled
        # _predict_step_fn, so an 8-device trainer predicts on all 8
        dataloaders = self._ensure_eval_state(module, dataloaders, "predict")
        params = self._state.params
        outs = []
        seen_n = None  # regular (already-compiled) batch size

        def place(batch):
            # pad-to-divisor + device placement, sequential in stream
            # order (seen_n threads the compiled batch size from the
            # first regular batch into later tail pads)
            nonlocal seen_n
            batch, true_n, padded_n = self._wrap_pad_batch(batch, seen_n)
            if true_n is None:
                leaves = jax.tree.leaves(batch)
                if leaves and np.ndim(leaves[0]):
                    seen_n = np.shape(leaves[0])[0]
            return self._put_batch(batch), true_n, padded_n

        source = iter(dataloaders)
        pf = None
        if self.prefetch_batches:
            pf = prefetch_lib.prefetch_pipeline(
                source, self.prefetch_batches, place, self.profiler,
                name="rla-prefetch-predict")
            source = pf
        else:
            source = map(place, source)
        try:
            outs = self._predict_consume(source, params)
        finally:
            if pf is not None:
                pf.close()
        return outs

    def _predict_consume(self, source, params) -> List[Any]:
        """Drain placed (batch, true_n, padded_n) triples through the
        compiled predict step, stripping wrap-padding."""
        outs: List[Any] = []
        for batch, true_n, padded_n in source:
            out = jax.device_get(self._predict_step_fn(params, batch))
            if true_n is not None:
                # strip padding only when every ARRAY leaf carries the
                # padded per-sample axis (mirroring the input-side
                # consistency check in _wrap_pad_batch): a leaf whose
                # leading dim merely COINCIDES with padded_n (per-head
                # stats of shape [16, ...] under a padded batch of 16)
                # must not be silently truncated.  Scalar leaves have no
                # leading axis to mis-truncate, so they pass through
                # without vetoing the strip.
                dims = {np.shape(x)[0] if np.ndim(x) else None
                        for x in jax.tree.leaves(out)}
                if dims - {None} == {padded_n}:
                    out = jax.tree.map(
                        lambda x: x[:true_n] if np.ndim(x) else x, out)
                else:
                    log.warning(
                        "predict outputs carry no consistent padded "
                        "per-sample axis (leading dims %s, padded batch "
                        "%d); returning this batch's outputs with "
                        "wrap-padding intact",
                        sorted(dims, key=str), padded_n)
            outs.append(out)
        return outs

    def _wrap_pad_batch(self, batch, target_n=None):
        """Pad a final partial batch up to the mesh's dim-0 divisor.

        The batch sharding scatters dim 0 over the data(+fsdp) axes, so a
        last batch whose size doesn't divide the mesh cannot be
        device_put at all -- predict() wrap-pads it (sample i mod n), and
        the caller slices the padded rows back off the outputs.  Returns
        ``(batch, true_n, padded_n)`` with ``true_n`` None when nothing
        was done (divisible already, or no consistent per-sample axis)."""
        sh = self._batch_sharding
        spec0 = sh.spec[0] if sh.spec else None
        if spec0 is None:
            return batch, None, None
        axes = spec0 if isinstance(spec0, tuple) else (spec0,)
        div = int(np.prod([sh.mesh.shape[a] for a in axes]))
        leaves = jax.tree.leaves(batch)
        dims = {np.shape(x)[0] if np.ndim(x) else None for x in leaves}
        if len(dims) != 1 or None in dims:
            return batch, None, None
        n = dims.pop()
        if n % div == 0:
            return batch, None, None
        # prefer padding up to ``target_n`` (the regular batch size the
        # step function already compiled for) over the minimal multiple:
        # a novel shape would force a whole extra XLA compile to save a
        # few padded rows
        padded_n = n + (-n) % div
        if target_n and target_n > n and target_n % div == 0:
            padded_n = target_n
        idx = np.arange(padded_n) % n
        return (jax.tree.map(lambda a: np.asarray(a)[idx], batch), n,
                padded_n)

    # ------------------------------------------------------------------ #
    def teardown(self) -> None:
        """Full release: compiled functions + device state (so a fresh fit
        can run in the same process) AND the persistent fan-out world --
        the reference's teardown ends its actors too
        (ray_ddp.py:109-121)."""
        self._release_compiled_state()
        self.shutdown_workers()

    def _release_compiled_state(self) -> None:
        """Device-state half of teardown(), used by _strip_for_shipment --
        which must NOT end the world it just acquired."""
        self._train_step_fn = None
        self._eval_step_fn = None
        self._test_step_fn = None
        self._predict_step_fn = None
        self._state = None
        self._device_cache = None
        self._train_step_cached_fn = None
        self._epoch_scan_fn = None
        # shardings hold live Mesh/Device objects -- they must not survive
        # into a cloudpickled shipment (_strip_for_shipment -> teardown)
        self._batch_sharding = None
        self._state_shardings = None
        self._idx_row_sharding = None
        self._idx_mat_sharding = None
        self._zero1_update_sh = None
        self._fsdp_param_sh = None
        self.accelerator.teardown()


def _remote_eval_worker(trainer: "Trainer", module, dataloaders, datamodule,
                        stage: str, process_id: int) -> Dict[str, Any]:
    """Runs INSIDE each fanned-out worker for validate/test/predict
    (the eval analog of ``_remote_fit_worker``; the reference rides the
    same actor machinery for test, SURVEY.md §3.4).  validate/test compute
    global-batch metrics SPMD (every rank returns the same numbers);
    predict shards the loader with the strided eval sampler and returns
    this rank's outputs for driver-side re-interleaving."""
    from ..runtime.bootstrap import resolve_shipped
    dataloaders = resolve_shipped(dataloaders)
    datamodule = resolve_shipped(datamodule)
    os.environ["RLA_TPU_INSIDE_WORKER"] = "1"
    if trainer.trace_id:
        # same contract as _remote_fit_worker: the driver's per-stage
        # trace id rides the pickled trainer; make it ambient so this
        # rank's events correlate with the driver's timeline
        telemetry.set_trace_id(trainer.trace_id)

    def telemetry_snap():
        # per-rank home-ship, the eval analog of _remote_fit_worker's:
        # the driver's MetricsRegistry merges every rank's view
        return {"rank": process_id,
                "profiler": (trainer.profiler.export_state()
                             if trainer.profiler is not None else None),
                "events": telemetry.get_recorder().events()}

    if stage == "predict":
        if datamodule is not None:
            datamodule.setup("predict")
            dataloaders = dataloaders or datamodule.predict_dataloader()
        if isinstance(dataloaders, DataLoader) and \
                trainer.accelerator.require_distributed_sampler:
            dataloaders._inject_sampler(
                shuffle=False,
                **trainer.accelerator.distributed_sampler_kwargs())
        outs = trainer.predict(module, dataloaders)
        return {"outputs": [jax.tree.map(lambda x: np.asarray(x), o)
                            for o in outs],
                # true dataset length, so the driver can drop the strided
                # sampler's wrap-padding after re-interleaving
                "dataset_len": (len(dataloaders.dataset)
                                if isinstance(dataloaders, DataLoader)
                                else None),
                "telemetry": telemetry_snap()}
    if stage == "validate":
        results = trainer.validate(module, dataloaders,
                                   datamodule=datamodule)
    else:
        results = trainer.test(module, dataloaders, datamodule=datamodule)
    metrics = {}
    for k, v in trainer.callback_metrics.items():
        try:
            metrics[k] = float(v)
        except (TypeError, ValueError):
            pass
    return {"metrics": metrics, "results": results,
            "telemetry": telemetry_snap()}


def _interleave_predictions(per_rank: List[List[Any]],
                            total: Optional[int] = None) -> List[Any]:
    """Merge per-rank predict outputs back into global dataset order.

    The strided sampler gives rank r samples ``r, r+P, r+2P, ...``, so
    local batch i element j is global sample ``(i*B + j)*P + r``: stacking
    ranks on a new axis 1 and flattening restores global order, one merged
    array per batch index.

    ``total``: the true dataset length.  With drop_last=False and
    ``len(dataset) % P != 0`` the sampler wraps, so the merged stream ends
    in padding duplicates; truncating to ``total`` makes driver-mode
    predict() return exactly the single-process result (PTL drops padded
    duplicates for predict the same way)."""
    merged = (per_rank[0] if len(per_rank) == 1 else None)
    if merged is None:

        def merge(*leaves):
            stacked = np.stack(leaves, axis=1)  # (B, P, ...)
            return stacked.reshape((-1,) + stacked.shape[2:])

        merged = [jax.tree.map(merge, *parts) for parts in zip(*per_rank)]
    if total is None:
        return merged
    # wrap-padding truncation only makes sense when every leaf carries a
    # per-sample leading axis; a per-batch scalar or pooled leaf would
    # make the count wrong and silently drop REAL predictions (or keep
    # padding) -- for those outputs, return the merged stream untouched
    for batch in merged:
        dims = {np.shape(leaf)[0] if np.ndim(leaf) else None
                for leaf in jax.tree.leaves(batch)}
        if None in dims or len(dims) != 1:
            log.warning(
                "predict outputs have no consistent per-sample leading "
                "axis (leading dims %s within one batch); returning all "
                "%d merged batches without wrap-padding truncation",
                sorted(dims, key=str), len(merged))
            return merged
    out: List[Any] = []
    seen = 0
    for batch in merged:
        n = np.shape(jax.tree.leaves(batch)[0])[0]
        take = min(n, total - seen)
        if take <= 0:
            break
        out.append(batch if take == n
                   else jax.tree.map(lambda x: x[:take], batch))
        seen += take
    return out


def _remote_fit_worker(trainer: "Trainer", module, train_dataloaders,
                       val_dataloaders, datamodule, ckpt_path,
                       process_id: int) -> Optional[Dict[str, Any]]:
    """Runs INSIDE each fanned-out worker process, after the launcher
    formed the jax.distributed world (the reference's ``train_remote``,
    ray_lightning/ray_ddp.py:199-220).  All ranks fit; rank 0 returns the
    materialized results the driver re-hydrates."""
    from ..runtime.bootstrap import resolve_shipped
    train_dataloaders = resolve_shipped(train_dataloaders)
    val_dataloaders = resolve_shipped(val_dataloaders)
    datamodule = resolve_shipped(datamodule)
    os.environ["RLA_TPU_INSIDE_WORKER"] = "1"
    if trainer.trace_id:
        # the driver's per-fit trace id arrived on the pickled trainer
        # (through the agent execute op); make it ambient so every event
        # this worker emits correlates with the driver's timeline
        telemetry.set_trace_id(trainer.trace_id)
    trainer.fit(module, train_dataloaders, val_dataloaders,
                datamodule=datamodule, ckpt_path=ckpt_path)
    # per-rank telemetry home-ship: the profiler's raw-reservoir export
    # (Profiler.merge-able driver-side) + this rank's recent events
    telemetry_snap = {
        "rank": process_id,
        "profiler": (trainer.profiler.export_state()
                     if trainer.profiler is not None else None),
        "events": telemetry.get_recorder().events(),
    }

    def host(x):
        if isinstance(x, jax.Array) and not x.is_fully_addressable:
            # cross-process shards (FSDP over hosts): collective gather --
            # every rank participates, mirroring the rank-0 state_dict
            # shipment (reference: ray_ddp.py:274)
            from jax.experimental import multihost_utils
            return np.asarray(
                multihost_utils.process_allgather(x, tiled=True))
        return np.asarray(jax.device_get(x))

    params_host = jax.tree.map(host, module.params)
    if jax.process_index() != 0:
        # non-zero ranks used to return None; they now ship their (small)
        # telemetry snapshot so the driver's MetricsRegistry merges EVERY
        # rank's profiler/events, not rank 0's view of the run
        return {"telemetry": telemetry_snap}
    metrics = {}
    for k, v in trainer.callback_metrics.items():
        try:
            metrics[k] = float(v)
        except (TypeError, ValueError):
            pass
    cb_states = {c.state_key: c.state_dict() for c in trainer.callbacks}
    best = getattr(trainer.checkpoint_callback, "best_model_path", None)
    return {"params": params_host,
            "global_step": trainer.global_step,
            "current_epoch": trainer.current_epoch,
            "epochs_completed": trainer.epochs_completed,
            "metrics": metrics,
            "callbacks": {k: v for k, v in cb_states.items() if v},
            "best_model_path": best,
            "telemetry": telemetry_snap}
