"""Training state: one immutable pytree holding everything a step mutates.

The reference mutated a live ``Trainer``/``nn.Module`` in place inside each
worker (reference: ray_lightning/ray_ddp.py:206-219).  Under XLA everything a
step touches must flow through the traced function, so state is a single
donated pytree: params, optimizer state, step counter, PRNG key.
"""

from __future__ import annotations

from typing import Any

import flax.struct
import jax
import jax.numpy as jnp
import optax


@flax.struct.dataclass
class TrainState:
    step: jax.Array            # scalar int32 global step
    params: Any                # model parameter pytree
    opt_state: Any             # optax state pytree
    rng: jax.Array             # base PRNG key; per-step keys are fold_in(step)
    # quantized gradient exchange (parallel/collectives.py), both None
    # unless Trainer(grad_compression=...) is set:
    # - residual: per-replica error-feedback residuals, one [n_replicas,
    #   leaf.size] f32 buffer per compressed leaf (the quantization error
    #   each replica carries into its next exchange)
    # - grad_accum: per-replica local-gradient accumulators
    #   [n_replicas, *leaf.shape] for accumulate_grad_batches > 1, so the
    #   exchange (the only comms) runs once per accumulation boundary
    residual: Any = None
    grad_accum: Any = None
    # numeric anomaly guardian (runtime/guardian.py): a tiny replicated
    # f32[GUARD_WIDTH] vector carrying the grad-norm EMA envelope and
    # sticky trip flags through the donated step; None when guard is off,
    # keeping the unguarded state pytree (and every compiled program that
    # consumes it) bit-identical to the pre-guardian build
    guard_ema: Any = None

    @classmethod
    def create(cls, params: Any, tx: optax.GradientTransformation,
               rng: jax.Array, residual: Any = None,
               grad_accum: Any = None,
               guard_ema: Any = None) -> "TrainState":
        return cls(
            step=jnp.zeros((), jnp.int32),
            params=params,
            opt_state=tx.init(params),
            rng=rng,
            residual=residual,
            grad_accum=grad_accum,
            guard_ema=guard_ema,
        )

    @property
    def num_params(self) -> int:
        return sum(int(x.size) for x in jax.tree.leaves(self.params))
