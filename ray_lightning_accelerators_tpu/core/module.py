"""TpuModule: the user-facing model container.

Capability analog of the reference's ``LightningModule`` usage (the reference
keeps PTL's module untouched and asserts its contract through BoringModel,
reference: ray_lightning/tests/utils.py:24-91).  TPU-native difference: the
step methods are **pure functions of (params, batch)** so the trainer can
trace them once under ``jax.jit`` and shard them over a mesh.  Attributes on
``self`` are trace-time constants (hyperparameters, flax module defs) -- never
per-step mutable state.

Mapping from the reference's API:

- ``self.log("k", v)`` inside a step  ->  return ``(loss, {"k": v})`` /
  a metrics dict; the trainer routes it to loggers, callbacks and
  ``trainer.callback_metrics`` exactly like PTL's ``callback_metrics`` bridge
  the Tune callbacks harvested (reference: ray_lightning/tune.py:82-95).
- ``configure_optimizers`` -> returns an ``optax.GradientTransformation``.
- ``forward``/``__call__``  -> ``predict_step``.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import optax

StepOutput = Union[jax.Array, Tuple[jax.Array, Dict[str, jax.Array]]]


class TpuModule:
    """Base class for user models."""

    # int8 forward matmuls inside the TRAIN step (Trainer(int8_matmul=
    # True) sets it): modules that support it (GPT routes its MLP
    # projections through per-out-channel int8 with straight-through
    # gradients) read this flag; others ignore it
    int8_matmul: bool = False

    def __init__(self):
        self.hparams: Dict[str, Any] = {}
        self.params: Any = None          # populated by Trainer after fit()
        self.trainer = None              # backref set by Trainer
        self.compute_dtype = jnp.float32  # set from Trainer(precision=...)
        self.mesh = None                 # set by Trainer before tracing
        # optional: an optax schedule (step -> lr).  Set it (and pass it to
        # your optimizer) to get a per-step "lr" training metric
        # (utils/schedules.py; wired in core/trainer.py's train_step)
        self.lr_schedule = None

    # ------------------------------------------------------------------ #
    # Methods the user overrides.                                        #
    # ------------------------------------------------------------------ #
    def init_params(self, rng: jax.Array) -> Any:
        """Build and return the parameter pytree."""
        raise NotImplementedError

    def configure_optimizers(self) -> optax.GradientTransformation:
        return optax.adam(1e-3)

    def training_step(self, params: Any, batch: Any,
                      rng: jax.Array) -> StepOutput:
        """Return loss, or (loss, metrics-dict).  Must be jax-traceable."""
        raise NotImplementedError

    def validation_step(self, params: Any, batch: Any) -> Dict[str, jax.Array]:
        """Return a dict of per-batch metrics (means).  Jax-traceable."""
        raise NotImplementedError

    def test_step(self, params: Any, batch: Any) -> Dict[str, jax.Array]:
        return self.validation_step(params, batch)

    def predict_step(self, params: Any, batch: Any) -> Any:
        return self.forward(params, batch)

    def forward(self, params: Any, batch: Any) -> Any:
        raise NotImplementedError

    def scanned_param_subtrees(self) -> Tuple[str, ...]:
        """Top-level param-tree keys holding layer-STACKED leaves that a
        ``lax.scan`` iterates (GPT: ``("layers",)``).  The overlap-aware
        FSDP gather (``Trainer(gather_mode="scan")``) keeps these
        fsdp-sharded as scan operands and all-gathers each layer inside
        the scan body; modules without a layer scan return ``()`` and
        fall back to the whole-tree gather."""
        return ()

    def on_validation_epoch_end(self) -> None:
        """Host-side hook after each validation pass (not traced)."""
        pass

    # ------------------------------------------------------------------ #
    # MPMD pipeline hooks (parallel/mpmd): override all three to run     #
    # with Trainer(pipeline_stages=S).  The PipelineRunner refuses a     #
    # module missing any of them with a typed PipelineConfigError.       #
    # ------------------------------------------------------------------ #
    def pipeline_stage_params(self, params: Any, stage: int,
                              num_stages: int) -> Any:
        """Carve the full parameter tree into the subtree stage
        ``stage`` owns (each stage group holds ONLY its slice).  Raise
        (e.g. for an indivisible layer count) to refuse — the driver
        wraps it into a typed config refusal."""
        raise NotImplementedError(
            f"{type(self).__name__}.pipeline_stage_params is required "
            "for Trainer(pipeline_stages=...)")

    def pipeline_stage_forward(self, stage_params: Any, x: Any,
                               stage: int, num_stages: int) -> Any:
        """One stage's forward: jax-traceable ``stage_params, x -> y``.
        Stage 0 receives the microbatch (as yielded by the dataloader)
        and extracts its own inputs; later stages receive the upstream
        activation."""
        raise NotImplementedError(
            f"{type(self).__name__}.pipeline_stage_forward is required "
            "for Trainer(pipeline_stages=...)")

    def pipeline_loss(self, y: Any, batch: Any) -> StepOutput:
        """Last stage only: loss (or ``(loss, metrics)``) from the final
        activation and the microbatch (labels).  Jax-traceable."""
        raise NotImplementedError(
            f"{type(self).__name__}.pipeline_loss is required for "
            "Trainer(pipeline_stages=...)")

    # Optional hooks mirroring PTL's checkpoint hooks (the reference's
    # BoringModel persists a counter through these,
    # reference: ray_lightning/tests/utils.py:87-91).
    def on_save_checkpoint(self, checkpoint: Dict[str, Any]) -> None:
        pass

    def on_load_checkpoint(self, checkpoint: Dict[str, Any]) -> None:
        pass

    # ------------------------------------------------------------------ #
    # Conveniences.                                                      #
    # ------------------------------------------------------------------ #
    def save_hyperparameters(self, **kwargs) -> None:
        self.hparams.update(kwargs)

    @staticmethod
    def coerce_checkpoint_lr(lr, default: float, model_name: str):
        """An lr *schedule* checkpoints as its repr string (callables are
        not serializable); on rebuild via load_from_checkpoint that string
        arrives as the constructor's ``lr``.  Warn and fall back to
        ``default`` unless the caller overrides."""
        if not isinstance(lr, str):
            return lr
        from ..utils.logging import log
        log.warning(
            "%s: checkpointed lr schedule %s is not reconstructable; "
            "falling back to constant lr=%g -- pass an explicit lr/schedule "
            "override to load_from_checkpoint to silence this",
            model_name, lr, default)
        return default

    def __call__(self, batch: Any) -> Any:
        """Eager convenience: run predict_step with the fitted params."""
        if self.params is None:
            raise RuntimeError(
                "module has no params yet -- call trainer.fit() first or set "
                ".params explicitly")
        # cache the jitted wrapper: a fresh jax.jit per call would retrace
        # (and recompile) every invocation
        if not hasattr(self, "_jit_predict"):
            self._jit_predict = jax.jit(self.predict_step)
        return self._jit_predict(self.params, batch)

    @classmethod
    def load_from_checkpoint(cls, checkpoint_path: str,
                             module: Optional["TpuModule"] = None,
                             **init_kwargs) -> "TpuModule":
        """Rebuild a module and install checkpointed params into it.

        Capability analog of ``LightningModule.load_from_checkpoint``
        (exercised by the reference's load_test,
        reference: ray_lightning/tests/utils.py:129-134).
        """
        from ..utils import checkpoint as ckpt_lib
        from ..utils import sharded_checkpoint as sharded_lib
        sharded = sharded_lib.is_sharded_checkpoint(checkpoint_path)
        payload = (sharded_lib.read_metadata(checkpoint_path) if sharded
                   else ckpt_lib.read_checkpoint(checkpoint_path))
        # explicit kwargs win over checkpointed hparams so callers can
        # override non-reconstructable values (e.g. an lr schedule saved
        # as its repr string)
        ctor_kwargs = dict(payload.get("hparams") or {})
        ctor_kwargs.update(init_kwargs)
        mod = module if module is not None else cls(**ctor_kwargs)
        rng = jax.random.PRNGKey(0)
        template = mod.init_params(rng)
        if sharded:
            import flax.serialization
            state = sharded_lib.restore_sharded(checkpoint_path)
            mod.params = flax.serialization.from_state_dict(
                template, flax.serialization.to_state_dict(state)["params"])
        else:
            mod.params = ckpt_lib.restore_params(payload, template)
        mod.on_load_checkpoint(payload)
        return mod
