"""Callback system: EarlyStopping, ModelCheckpoint, and the hook surface.

The reference inherited all of this from PTL and pinned the behavior in tests
(early stop at patience=2, reference: ray_lightning/tests/test_ddp.py:118-134;
best-checkpoint round trip, reference: ray_lightning/tests/utils.py:129-134).
With no PTL underneath, the TPU framework owns the implementations.  All hook
arguments are host-side values; metric comparisons happen on materialized
floats at validation boundaries (an XLA-friendly cadence -- never per step).
"""

from __future__ import annotations

import math
import os
from typing import Any, Dict, Optional

from ..utils.logging import log
from ..utils.sharded_checkpoint import remove_checkpoint


class Callback:
    """Hook surface.  Subset of PTL's, covering what the reference exercised."""

    def setup(self, trainer, module, stage: str) -> None: ...
    def on_fit_start(self, trainer, module) -> None: ...
    def on_fit_end(self, trainer, module) -> None: ...
    def on_train_epoch_start(self, trainer, module) -> None: ...
    def on_train_epoch_end(self, trainer, module) -> None: ...
    def on_train_batch_end(self, trainer, module, metrics, batch_idx: int) -> None: ...
    def on_validation_start(self, trainer, module) -> None: ...
    def on_validation_end(self, trainer, module) -> None: ...
    def on_test_end(self, trainer, module) -> None: ...
    def on_save_checkpoint(self, trainer, module, checkpoint: Dict[str, Any]) -> None: ...
    def on_load_checkpoint(self, trainer, module, checkpoint: Dict[str, Any]) -> None: ...

    def state_dict(self) -> Dict[str, Any]:
        return {}

    def load_state_dict(self, state: Dict[str, Any]) -> None:
        pass

    @property
    def state_key(self) -> str:
        return type(self).__name__


def _mode_ops(mode: str):
    if mode == "min":
        return (lambda a, b: a < b), math.inf
    if mode == "max":
        return (lambda a, b: a > b), -math.inf
    raise ValueError(f"mode must be 'min' or 'max', got {mode!r}")


class EarlyStopping(Callback):
    """Stop training when `monitor` stops improving.

    Matches the contract the reference tests pin: patience counted in
    validation rounds, min_delta slack, sets ``trainer.should_stop``
    (reference: ray_lightning/tests/test_ddp.py:118-134).
    """

    def __init__(self, monitor: str = "val_loss", patience: int = 3,
                 mode: str = "min", min_delta: float = 0.0,
                 verbose: bool = False):
        self.monitor = monitor
        self.patience = patience
        self.mode = mode
        self.min_delta = abs(min_delta)
        self.verbose = verbose
        self._is_better, self.best_score = _mode_ops(mode)
        self.wait_count = 0
        self.stopped_epoch: Optional[int] = None

    def on_validation_end(self, trainer, module) -> None:
        if trainer.sanity_checking or not trainer.fitting:
            return
        current = trainer.callback_metrics.get(self.monitor)
        if current is None:
            log.warning("EarlyStopping: monitored metric %r not found in %s",
                        self.monitor, sorted(trainer.callback_metrics))
            return
        current = float(current)
        threshold = (self.best_score - self.min_delta if self.mode == "min"
                     else self.best_score + self.min_delta)
        if self._is_better(current, threshold):
            self.best_score = current
            self.wait_count = 0
        else:
            self.wait_count += 1
            if self.wait_count >= self.patience:
                trainer.should_stop = True
                self.stopped_epoch = trainer.current_epoch
                if self.verbose:
                    log.warning("EarlyStopping: stopping at epoch %d (best %s=%.5f)",
                                trainer.current_epoch, self.monitor, self.best_score)

    def state_dict(self) -> Dict[str, Any]:
        return {"best_score": self.best_score, "wait_count": self.wait_count,
                "stopped_epoch": self.stopped_epoch}

    def load_state_dict(self, state: Dict[str, Any]) -> None:
        self.best_score = state["best_score"]
        self.wait_count = state["wait_count"]
        self.stopped_epoch = state.get("stopped_epoch")


class ModelCheckpoint(Callback):
    """Save checkpoints, tracking the best by `monitor`.

    Provides ``best_model_path`` -- the attribute the reference ships from
    rank-0 back to the driver (reference: ray_lightning/ray_ddp.py:269-278)
    and round-trips in load_test (reference: ray_lightning/tests/utils.py:129-134).
    """

    def __init__(self, dirpath: Optional[str] = None, monitor: Optional[str] = "val_loss",
                 mode: str = "min", save_top_k: int = 1, save_last: bool = False,
                 filename: str = "epoch={epoch}-step={step}.ckpt",
                 every_n_epochs: int = 1,
                 keep_last_k: Optional[int] = None):
        self.dirpath = dirpath
        self.monitor = monitor
        self.mode = mode
        self.save_top_k = save_top_k
        self.save_last = save_last
        self.filename = filename
        self.every_n_epochs = max(1, every_n_epochs)
        # retention GC over the WHOLE dirpath (utils/checkpoint
        # .prune_checkpoints): emergency/preemption checkpoints and older
        # runs' leftovers accumulate outside this callback's top-k
        # bookkeeping; keep_last_k bounds the disk footprint while never
        # deleting the only verified resume anchor.  None = no GC.
        if keep_last_k is not None and keep_last_k < 1:
            raise ValueError(f"keep_last_k must be >= 1, got {keep_last_k}")
        self.keep_last_k = keep_last_k
        self._is_better, self.best_model_score = _mode_ops(mode)
        self.best_model_path: str = ""
        self.last_model_path: str = ""
        self._saved: list[tuple[float, str]] = []  # (score, path), best first

    def setup(self, trainer, module, stage: str) -> None:
        if self.dirpath is None:
            self.dirpath = os.path.join(trainer.default_root_dir, "checkpoints")

    def _format_name(self, trainer) -> str:
        return self.filename.format(epoch=trainer.current_epoch,
                                    step=trainer.global_step)

    @staticmethod
    def _remove(path: str) -> None:
        # No async fence needed even under 'sharded-async': orbax
        # serializes async saves (a new save waits out the previous
        # commit), so by the time a sibling is evicted its array commit
        # has finished -- fencing here would block training on the NEW
        # checkpoint's commit, making async saves synchronous.  The only
        # straggler is the meta.json finalize rename, which tolerates a
        # vanished dir (save_sharded._finalize) and whose opposite race
        # (rename landing mid-rmtree) remove_checkpoint re-sweeps.
        remove_checkpoint(path)

    def _prune(self) -> None:
        """``keep_last_k`` retention GC (utils/checkpoint
        .prune_checkpoints): process 0 only, with every path this
        callback still tracks (top-k snapshots, best, last) protected,
        plus the numeric guardian's rewind anchor while a quarantine is
        active — evicting the checkpoint an in-flight anomaly recovery
        rewinds to would turn a cheap rewind into a cold restart."""
        if self.keep_last_k is None or self.dirpath is None:
            return
        import jax

        from ..runtime import guardian as guardian_lib
        from ..utils import checkpoint as ckpt_lib
        if jax.process_index() != 0:
            return
        protect = [self.best_model_path, self.last_model_path]
        protect += [p for _score, p in self._saved]
        protect += guardian_lib.protected_paths(self.dirpath)
        ckpt_lib.prune_checkpoints(self.dirpath, self.keep_last_k,
                                   protect=protect)

    def on_validation_end(self, trainer, module) -> None:
        if trainer.sanity_checking or not trainer.fitting or self.save_top_k == 0:
            return
        if (trainer.current_epoch + 1) % self.every_n_epochs != 0:
            return
        path = os.path.join(self.dirpath, self._format_name(trainer))
        if self.monitor is None:
            # unmonitored: keep only the `save_top_k` most recent snapshots
            trainer.save_checkpoint(path)
            if self.best_model_path and self.best_model_path != path:
                self._saved.append((0.0, self.best_model_path))
                while len(self._saved) > max(0, self.save_top_k - 1):
                    _, evicted = self._saved.pop(0)
                    self._remove(evicted)
            self.best_model_path = path
            self._prune()
            return
        current = trainer.callback_metrics.get(self.monitor)
        if current is None:
            log.warning("ModelCheckpoint: monitored metric %r not found",
                        self.monitor)
            return
        current = float(current)
        if len(self._saved) < self.save_top_k or self._is_better(
                current, self._saved[-1][0]):
            trainer.save_checkpoint(path)
            self._saved.append((current, path))
            self._saved.sort(key=lambda t: t[0],
                             reverse=(self.mode == "max"))
            while len(self._saved) > self.save_top_k:
                _, evicted = self._saved.pop()
                if evicted != path:
                    self._remove(evicted)
            if self._is_better(current, self.best_model_score):
                self.best_model_score = current
                self.best_model_path = path
            self._prune()

    def on_fit_end(self, trainer, module) -> None:
        if self.save_last:
            self.last_model_path = os.path.join(self.dirpath, "last.ckpt")
            trainer.save_checkpoint(self.last_model_path)
            self._prune()

    def state_dict(self) -> Dict[str, Any]:
        return {"best_model_score": self.best_model_score,
                "best_model_path": self.best_model_path,
                "saved": list(self._saved)}

    def load_state_dict(self, state: Dict[str, Any]) -> None:
        self.best_model_score = state["best_model_score"]
        self.best_model_path = state["best_model_path"]
        self._saved = list(state.get("saved", []))
