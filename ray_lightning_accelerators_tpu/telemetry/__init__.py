"""Distributed telemetry: flight recorder, trace IDs, unified metrics
export, and crash postmortem reports.

- :mod:`.recorder` — the bounded per-process event ring with trace-ID
  propagation and crash-observable spill files;
- :mod:`.registry` — the driver-side :class:`MetricsRegistry` (merged
  Profiler/ServeMetrics/compile-count export to Prometheus text and
  JSON) and the ``run_report.json`` postmortem writer.

See docs/API.md "Telemetry & tracing" for event kinds, propagation
rules, export formats and the report schema.
"""

from .recorder import (EMBED_TAIL_N, EVENT_KINDS, FlightRecorder,
                       configure, current_rank, current_trace_id, emit,
                       get_recorder, mint_trace_id, read_spill,
                       set_trace_id, spill_path_for, tail_events)
from .registry import (MetricsRegistry, build_run_report,
                       gather_spill_dir, gather_worker_tails,
                       probe_snapshot_record, write_run_report)

__all__ = [
    "FlightRecorder", "EVENT_KINDS", "EMBED_TAIL_N",
    "get_recorder", "configure", "emit",
    "mint_trace_id", "set_trace_id", "current_trace_id", "current_rank",
    "spill_path_for", "read_spill", "tail_events",
    "MetricsRegistry", "gather_worker_tails", "gather_spill_dir",
    "build_run_report", "write_run_report", "probe_snapshot_record",
]
