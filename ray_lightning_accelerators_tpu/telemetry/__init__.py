"""Distributed telemetry: flight recorder, trace IDs, unified metrics
export, and crash postmortem reports.

- :mod:`.recorder` — the bounded per-process event ring with trace-ID
  propagation and crash-observable spill files;
- :mod:`.registry` — the driver-side :class:`MetricsRegistry` (merged
  Profiler/ServeMetrics/compile-count export to Prometheus text and
  JSON) and the ``run_report.json`` postmortem writer;
- :mod:`.perf` — the perf observatory: :class:`StepTimeline` (per-step
  phase decomposition), :class:`HbmLedger` (per-pool HBM attribution +
  leak alarm) and :class:`GoodputLedger` (run-level wall-time
  partition), exported through the registry.

See docs/API.md "Telemetry & tracing" / "Perf observatory" for event
kinds, phase/pool vocabularies, export formats and the report schema.
"""

from .live import (ClusterView, LiveSources, TelemetryServer,
                   classify_health)
from .perf import (GOODPUT_CATEGORIES, PHASE_KINDS, GoodputLedger,
                   HbmLedger, PerfObservatory, StepTimeline,
                   exposed_comm_crosscheck, placed_bytes_total,
                   tree_nbytes)
from .recorder import (EMBED_TAIL_N, EVENT_KINDS, FlightRecorder,
                       configure, current_rank, current_trace_id, emit,
                       get_recorder, mint_trace_id, read_spill,
                       set_trace_id, spill_path_for, tail_events)
from .registry import (MetricsRegistry, build_run_report,
                       gather_spill_dir, gather_worker_tails,
                       probe_snapshot_record, write_run_report)

__all__ = [
    "FlightRecorder", "EVENT_KINDS", "EMBED_TAIL_N",
    "get_recorder", "configure", "emit",
    "mint_trace_id", "set_trace_id", "current_trace_id", "current_rank",
    "spill_path_for", "read_spill", "tail_events",
    "MetricsRegistry", "gather_worker_tails", "gather_spill_dir",
    "build_run_report", "write_run_report", "probe_snapshot_record",
    "PerfObservatory", "StepTimeline", "HbmLedger", "GoodputLedger",
    "PHASE_KINDS", "GOODPUT_CATEGORIES", "exposed_comm_crosscheck",
    "tree_nbytes", "placed_bytes_total",
    "TelemetryServer", "LiveSources", "ClusterView", "classify_health",
]
