"""Driver-side metrics registry: one export surface for a whole run.

Every per-process island (trainer Profiler spans, prefetch counters,
comms wire accounting, ServeMetrics, compile counts, flight-recorder
events) lands here and renders two ways:

- ``to_json()`` — the machine-readable snapshot (bench probes print it
  as a ``kind="telemetry"`` line next to their metric record);
- ``prometheus_text()`` — the Prometheus exposition format, so a run is
  scrapeable with zero extra glue (span families render as summaries
  with ``quantile`` labels, counters as ``_total``, gauges as gauges).

``write_run_report`` is the crash postmortem: on ``WorkerWedged`` /
``Preempted`` / any uncaught fit exception the driver writes
``run_report.json`` — per-rank flight-recorder timelines (driver ring +
every worker's spill tail), the stall diagnosis, compile counts and the
metric snapshot — so the artifact alone reconstructs what each rank was
doing when the run died.
"""

from __future__ import annotations

import json
import logging
import os
import re
import time
from typing import Any, Dict, List, Mapping, Optional, Sequence

from ..utils.profiler import Profiler
from . import recorder as recorder_lib

log = logging.getLogger("ray_lightning_accelerators_tpu.telemetry")

REPORT_SCHEMA = 1
REPORT_BASENAME = "run_report.json"

_NAME_RE = re.compile(r"[^a-zA-Z0-9_]")


def _prom_name(name: str) -> str:
    """Prometheus-legal metric-name fragment."""
    clean = _NAME_RE.sub("_", str(name)).strip("_")
    return clean or "unnamed"


class MetricsRegistry:
    """Accumulates per-rank telemetry into one mergeable view.

    ``add_profiler`` takes a live :class:`~..utils.profiler.Profiler`
    or its ``export_state()`` dict (the wire shape workers ship home);
    all profilers merge into ONE (``Profiler.merge`` reservoir
    semantics), so the exported percentiles summarize the whole run,
    not one lucky rank.  Serve snapshots, compile counts and event
    tallies are kept per rank label (``"driver"``, ``"0"``, ...).
    """

    def __init__(self, trace_id: Optional[str] = None):
        self.trace_id = trace_id
        self._profiler = Profiler()
        self._profiler_ranks: List[str] = []
        self._serve: Dict[str, Dict[str, Any]] = {}
        self._compile: Dict[str, int] = {}
        self._event_counts: Dict[str, int] = {}
        self._extra: Dict[str, float] = {}
        # perf-observatory ledgers (telemetry/perf.py snapshots)
        self._perf: Dict[str, Dict[str, Any]] = {}
        # live-plane per-rank status rows (telemetry/live.py)
        self._ranks: Dict[str, Dict[str, Any]] = {}
        # serve replica-controller snapshot (serve/controller.py)
        self._replica_controller: Optional[Dict[str, Any]] = None

    @staticmethod
    def _label(rank: Any) -> str:
        return "driver" if rank is None else str(rank)

    # ------------------------------------------------------------------ #
    def add_profiler(self, profiler: Any, rank: Any = None) -> None:
        """Merge one rank's profiler (object or export_state dict)."""
        if profiler is None:
            return
        self._profiler.merge(profiler)
        self._profiler_ranks.append(self._label(rank))

    def add_serve(self, metrics: Any, rank: Any = None) -> None:
        """One rank's ServeMetrics — the object (its latency reservoirs
        merge into the shared profiler) or a ``snapshot()`` dict."""
        if metrics is None:
            return
        snap = metrics
        if hasattr(metrics, "snapshot"):
            snap = metrics.snapshot()
            prof = getattr(metrics, "profiler", None)
            if prof is not None:
                self.add_profiler(prof, rank=rank)
        self._serve[self._label(rank)] = dict(snap)

    def add_compile_count(self, n: Optional[int] = None,
                          rank: Any = None) -> None:
        """A rank's backend-compile total; ``None`` reads this process's
        ``analysis.compile_guard.compile_count()``."""
        if n is None:
            from ..analysis import compile_guard
            n = compile_guard.compile_count()
        self._compile[self._label(rank)] = int(n)

    def add_events(self, events: Sequence[Mapping[str, Any]],
                   rank: Any = None) -> None:
        """Tally a rank's flight-recorder events into per-kind counts —
        the registry is a METRICS surface, so rank granularity is
        deliberately dropped here; full per-rank timelines belong in the
        run report.  The first traced event seeds ``trace_id`` when the
        registry was built without one."""
        del rank  # accepted for signature symmetry with the other adds
        for e in events or ():
            kind = e.get("kind", "?")
            self._event_counts[kind] = self._event_counts.get(kind, 0) + 1
            if self.trace_id is None and e.get("trace"):
                self.trace_id = e["trace"]

    def add_scalar(self, name: str, value: float) -> None:
        """A free-form run-level scalar (probe extras)."""
        self._extra[str(name)] = float(value)

    def add_rank_status(self, rank: Any,
                        status: Mapping[str, Any]) -> None:
        """One rank's live status row (telemetry/live.py
        ``LiveSources.rank_status`` shape): kept per rank label so the
        merged export stays RANK-LABELED — ``rla_tpu_rank_healthy``,
        ``rla_tpu_rank_global_step`` and
        ``rla_tpu_rank_events_per_second`` render one sample per rank,
        which is what a live dashboard keys on."""
        if status:
            self._ranks[self._label(rank)] = dict(status)

    def add_replica_controller(self, snapshot: Any) -> None:
        """The serve tier's :class:`~..serve.controller
        .ReplicaController` snapshot (object or its ``snapshot()``
        dict): per-replica state/load rows rendered as the
        ``rla_tpu_serve_replica_*`` gauge family (one sample per
        replica label) plus tier-level queue/brownout gauges."""
        if snapshot is None:
            return
        if hasattr(snapshot, "snapshot"):
            snapshot = snapshot.snapshot()
        self._replica_controller = dict(snapshot)

    # -- perf-observatory ledgers (telemetry/perf.py) ------------------- #
    @staticmethod
    def _snap(obj: Any) -> Dict[str, Any]:
        return dict(obj.snapshot()) if hasattr(obj, "snapshot") \
            else dict(obj)

    def add_step_timeline(self, timeline: Any) -> None:
        """A :class:`~.perf.StepTimeline` (or its snapshot dict): the
        per-step phase decomposition of the run's hot loop."""
        if timeline is not None:
            self._perf["step_timeline"] = self._snap(timeline)

    def add_hbm(self, ledger: Any) -> None:
        """A :class:`~.perf.HbmLedger` (or snapshot): per-pool device
        memory attribution + watermarks + leak-alarm count."""
        if ledger is not None:
            self._perf["hbm"] = self._snap(ledger)

    def add_goodput(self, ledger: Any) -> None:
        """A :class:`~.perf.GoodputLedger` (or snapshot): the run's
        wall-time partition and goodput fraction."""
        if ledger is not None:
            self._perf["goodput"] = self._snap(ledger)

    def merged_profiler(self) -> Profiler:
        return self._profiler

    # ------------------------------------------------------------------ #
    # Exports                                                             #
    # ------------------------------------------------------------------ #
    def to_json(self) -> Dict[str, Any]:
        """Flat JSON snapshot: merged spans/counters/gauges/comms, serve
        per rank, compile counts, event tallies."""
        prof = self._profiler
        out: Dict[str, Any] = {
            "schema": REPORT_SCHEMA,
            "trace_id": self.trace_id,
            "profiler_ranks": list(self._profiler_ranks),
            "spans": prof.summary(),
            "counters": prof.counters(),
            "gauges": prof.gauges(),
            "comms": prof.comms(),
            "serve": {k: dict(v) for k, v in self._serve.items()},
            "compile": {"per_rank": dict(self._compile),
                        "total_backend_compiles": sum(
                            self._compile.values())},
            "events": dict(self._event_counts),
        }
        if self._ranks:
            out["ranks"] = {k: dict(v) for k, v in self._ranks.items()}
        if self._replica_controller:
            out["replica_controller"] = dict(self._replica_controller)
        if self._perf:
            out["perf"] = {k: dict(v) for k, v in self._perf.items()}
        if self._extra:
            out["extra"] = dict(self._extra)
        return out

    def prometheus_text(self) -> str:
        """Prometheus exposition text.  Span families are summaries
        (``quantile`` labels + ``_sum``/``_count``/``_max``); profiler
        counters and serve counters are ``_total`` counters; gauges and
        comms fields are gauges.  Rank granularity: serve metrics carry
        a ``rank`` label; merged profiler families describe the run."""
        lines: List[str] = []
        typed: set = set()

        def add(name: str, value: Any, labels: str = "",
                mtype: Optional[str] = None) -> None:
            if value is None:
                return
            if mtype is not None and name not in typed:
                # one TYPE line per metric name: exposition parsers
                # reject duplicates (rank-labeled families repeat names)
                typed.add(name)
                lines.append(f"# TYPE {name} {mtype}")
            lines.append(f"{name}{labels} {float(value):g}")

        spans = self._profiler.summary()
        if spans:
            lines.append("# TYPE rla_tpu_span_seconds summary")
        for span, s in sorted(spans.items()):
            lab = f'{{span="{_prom_name(span)}"}}'
            for q, key in (("0.5", "p50_s"), ("0.95", "p95_s"),
                           ("0.99", "p99_s")):
                lines.append(
                    f'rla_tpu_span_seconds{{span="{_prom_name(span)}",'
                    f'quantile="{q}"}} {s[key]:g}')
            lines.append(f"rla_tpu_span_seconds_sum{lab} {s['total_s']:g}")
            lines.append(f"rla_tpu_span_seconds_count{lab} "
                         f"{s['count']:g}")
            lines.append(f"rla_tpu_span_seconds_max{lab} {s['max_s']:g}")
        for name, n in sorted(self._profiler.counters().items()):
            add(f"rla_tpu_{_prom_name(name)}_total", n, mtype="counter")
        for name, g in sorted(self._profiler.gauges().items()):
            add(f"rla_tpu_{_prom_name(name)}", g["last"], mtype="gauge")
        comms = self._profiler.comms()
        if comms:
            for key in ("exchange_bytes_per_step",
                        "baseline_fp32_bytes_per_step",
                        "compression_ratio"):
                if isinstance(comms.get(key), (int, float)):
                    add(f"rla_tpu_comms_{_prom_name(key)}", comms[key],
                        mtype="gauge")
        # key-major: all of a family's rank-labeled samples must be
        # contiguous — the exposition format forbids interleaving
        # metric families, and a rank-major loop would split e.g.
        # serve_completed_total across two rank blocks
        serve_keys = sorted({k for snap in self._serve.values()
                             for k, v in snap.items()
                             if isinstance(v, (int, float))})
        # lazy import: serve/__init__ imports telemetry.recorder, so a
        # module-level import here would cycle through the packages
        from ..serve.metrics import ServeMetrics as _SM
        for key in serve_keys:
            gauge = key in ("queue_depth", "busy_s", "throughput_tok_s",
                            "max_batch") or key in _SM.POOL_GAUGES \
                or key in _SM.SLO_GAUGES or key in _SM.LANE_GAUGES \
                or key in _SM.CHUNK_GAUGES
            name = f"rla_tpu_serve_{_prom_name(key)}"
            if not gauge:
                name = f"{name}_total"
            for rank, snap in sorted(self._serve.items()):
                val = snap.get(key)
                if isinstance(val, (int, float)):
                    add(name, val, f'{{rank="{rank}"}}',
                        mtype="gauge" if gauge else "counter")
        if self._compile:
            add("rla_tpu_backend_compiles_total",
                sum(self._compile.values()), mtype="counter")
        for kind, n in sorted(self._event_counts.items()):
            add("rla_tpu_events_total", n,
                f'{{kind="{_prom_name(kind)}"}}', mtype="counter")
        # live-plane rank rows: key-major like the serve block (one
        # contiguous family per metric name, one sample per rank)
        for key, fam in (("healthy", "rla_tpu_rank_healthy"),
                         ("global_step", "rla_tpu_rank_global_step"),
                         ("events_per_second",
                          "rla_tpu_rank_events_per_second")):
            for rank, row in sorted(self._ranks.items()):
                val = row.get(key)
                if isinstance(val, (int, float)):
                    add(fam, val, f'{{rank="{rank}"}}', mtype="gauge")
        # serve replica-controller rows (serve/controller.py): one
        # sample per replica label, key-major per family; monotone
        # per-replica tallies are counters, load/health levels gauges
        rc = self._replica_controller
        if rc:
            replicas = sorted((rc.get("replicas") or {}).items(),
                              key=lambda kv: kv[0])
            add("rla_tpu_serve_replica_count", len(replicas),
                mtype="gauge")
            for key, kind in (("inflight_requests", "gauge"),
                              ("inflight_chunks", "gauge"),
                              ("slo_burn", "gauge"),
                              ("p99_step_ms", "gauge"),
                              ("dispatched_chunks", "counter"),
                              ("completed_chunks", "counter"),
                              ("infra_failures", "counter"),
                              ("app_failures", "counter"),
                              ("retries", "counter"),
                              ("hedges", "counter"),
                              ("revivals", "counter"),
                              ("prefix_hits", "counter"),
                              ("prefix_misses", "counter"),
                              ("prefix_hit_rate", "gauge")):
                name = f"rla_tpu_serve_replica_{_prom_name(key)}"
                if kind == "counter":
                    name += "_total"
                for label, row in replicas:
                    val = row.get(key)
                    if isinstance(val, (int, float)):
                        add(name, val, f'{{replica="{label}"}}',
                            mtype=kind)
            # state one-hot: dashboards key on the label pair
            for label, row in replicas:
                state = row.get("state")
                if state:
                    add("rla_tpu_serve_replica_state", 1,
                        f'{{replica="{label}",'
                        f'state="{_prom_name(state)}"}}',
                        mtype="gauge")
            # lane one-hot (disaggregated prefill/decode lanes): same
            # label-pair pattern as state, its own contiguous family
            for label, row in replicas:
                lane = row.get("lane")
                if lane:
                    add("rla_tpu_serve_replica_lane", 1,
                        f'{{replica="{label}",'
                        f'lane="{_prom_name(lane)}"}}',
                        mtype="gauge")
            for key in ("queue_depth", "queue_cap",
                        "brownout_watermark", "max_burn"):
                if isinstance(rc.get(key), (int, float)):
                    add(f"rla_tpu_serve_tier_{_prom_name(key)}",
                        rc[key], mtype="gauge")
        # perf-observatory ledgers: phase seconds, HBM pools, goodput —
        # each family key-major like the serve block (exposition format
        # forbids interleaved families)
        tl = self._perf.get("step_timeline")
        if tl:
            add("rla_tpu_steps_total", tl.get("steps"), mtype="counter")
            add("rla_tpu_step_wall_seconds_total",
                tl.get("step_wall_total_s"), mtype="counter")
            for fam in ("phases", "between_step_phases"):
                suffix = "" if fam == "phases" else "_between_step"
                for phase, row in sorted((tl.get(fam) or {}).items()):
                    add(f"rla_tpu_step_phase{suffix}_seconds_total",
                        row.get("total_s"),
                        f'{{phase="{_prom_name(phase)}"}}',
                        mtype="counter")
            add("rla_tpu_step_phase_attributed_fraction",
                tl.get("attributed_fraction"), mtype="gauge")
            add("rla_tpu_step_exposed_comm_fraction_analytic",
                tl.get("analytic_exposed_comm_fraction"), mtype="gauge")
        hbm = self._perf.get("hbm")
        if hbm:
            for pool, row in sorted((hbm.get("pools") or {}).items()):
                add("rla_tpu_hbm_pool_bytes", row.get("bytes"),
                    f'{{pool="{_prom_name(pool)}"}}', mtype="gauge")
            for pool, row in sorted((hbm.get("pools") or {}).items()):
                add("rla_tpu_hbm_pool_peak_bytes", row.get("peak_bytes"),
                    f'{{pool="{_prom_name(pool)}"}}', mtype="gauge")
            add("rla_tpu_hbm_total_bytes", hbm.get("total_bytes"),
                mtype="gauge")
            add("rla_tpu_hbm_peak_total_bytes",
                hbm.get("peak_total_bytes"), mtype="gauge")
            add("rla_tpu_hbm_attributed_fraction",
                hbm.get("attributed_fraction"), mtype="gauge")
            add("rla_tpu_hbm_leak_alarms_total", hbm.get("leak_alarms"),
                mtype="counter")
        gp = self._perf.get("goodput")
        if gp:
            for cat, secs in sorted((gp.get("seconds") or {}).items()):
                add("rla_tpu_goodput_seconds_total", secs,
                    f'{{category="{_prom_name(cat)}"}}', mtype="counter")
            add("rla_tpu_goodput_wall_seconds", gp.get("wall_s"),
                mtype="gauge")
            add("rla_tpu_goodput_fraction", gp.get("goodput_fraction"),
                mtype="gauge")
        for name, v in sorted(self._extra.items()):
            add(f"rla_tpu_{_prom_name(name)}", v, mtype="gauge")
        return "\n".join(lines) + ("\n" if lines else "")


# --------------------------------------------------------------------- #
# Cross-rank event gathering                                             #
# --------------------------------------------------------------------- #
def gather_worker_tails(workers: Sequence[Any]) -> Dict[str, Dict[str, Any]]:
    """Each worker's spilled flight-recorder snapshot, keyed by rank
    label.  Works on local ``Worker``s and agent ``RemoteWorker``s (both
    expose ``telemetry_tail``); a rank with no spill (telemetry dir
    unset, never emitted, host gone with its disk) is simply absent."""
    out: Dict[str, Dict[str, Any]] = {}
    for w in workers or ():
        tail_fn = getattr(w, "telemetry_tail", None)
        if tail_fn is None:
            continue
        try:
            snap = tail_fn()
        except BaseException:
            snap = None
        if snap:
            out[str(getattr(w, "rank", "?"))] = snap
    return out


def gather_spill_dir(tdir: Optional[str] = None) -> Dict[str, Dict[str, Any]]:
    """Every rank snapshot spilled under the telemetry dir (default: the
    ``RLA_TPU_TELEMETRY_DIR`` knob).  The pool-independent gather — it
    still works after the world was killed, which is exactly when the
    run report is written."""
    from ..analysis import knobs
    if tdir is None:
        tdir = knobs.get_str(recorder_lib.DIR_ENV, None)
    if not tdir or not os.path.isdir(tdir):
        return {}
    out: Dict[str, Dict[str, Any]] = {}
    for fname in sorted(os.listdir(tdir)):
        if not fname.endswith(".events.json"):
            continue
        snap = recorder_lib.read_spill(os.path.join(tdir, fname))
        if snap is not None:
            label = fname[:-len(".events.json")]
            out[label.replace("rank", "", 1) if label.startswith("rank")
                else label] = snap
    return out


def probe_snapshot_record(probe: str, *, profiler: Any = None,
                          serve: Any = None,
                          **extra: Any) -> Dict[str, Any]:
    """The bench probes' trailing ``kind="telemetry"`` stdout record
    (scripts/*_probe.py): driver events + compile count (+ optional
    profiler/serve metrics) as one MetricsRegistry snapshot.  One place
    holds the line shape, because bench.py's parser contract depends on
    it: the record must stay value-LESS (no ``value`` key — enforced
    here) so the newest-value-bearing-line rule keeps returning the
    probe's real metric record."""
    reg = MetricsRegistry()
    if profiler is not None:
        reg.add_profiler(profiler, rank="driver")
    if serve is not None:
        reg.add_serve(serve, rank="driver")
    reg.add_events(recorder_lib.get_recorder().events(), rank="driver")
    try:
        reg.add_compile_count(rank="driver")
    except BaseException:  # jax.monitoring unavailable: export without
        pass
    if "value" in extra:
        raise ValueError(
            "a telemetry snapshot record must stay value-less (bench.py "
            "treats any 'value'-keyed line as the probe's metric)")
    rec: Dict[str, Any] = {"probe": probe, "kind": "telemetry",
                           "snapshot": reg.to_json(),
                           "prometheus_lines": len(
                               reg.prometheus_text().splitlines())}
    rec.update(extra)
    return rec


# --------------------------------------------------------------------- #
# Run report (crash postmortem artifact)                                 #
# --------------------------------------------------------------------- #
def build_run_report(*, error: Optional[BaseException] = None,
                     trace_id: Optional[str] = None,
                     rank_events: Optional[Mapping[str, Any]] = None,
                     stall_diagnosis: Optional[Mapping[str, Any]] = None,
                     registry: Optional[MetricsRegistry] = None,
                     include_driver: bool = True,
                     extra: Optional[Mapping[str, Any]] = None
                     ) -> Dict[str, Any]:
    """The ``run_report.json`` payload.  ``rank_events`` maps rank labels
    to spill/wire snapshots (or bare event lists); the driver's own ring
    is added automatically.  Every field is best-effort — a postmortem
    writer must not raise past the error it documents."""
    ranks: Dict[str, Dict[str, Any]] = {}
    if include_driver:
        rec = recorder_lib.get_recorder()
        ranks["driver"] = rec.snapshot()
        if trace_id is None:
            trace_id = rec.trace_id
    for label, snap in (rank_events or {}).items():
        if str(label) in ranks:
            # the live driver ring already landed; a spill of the same
            # rank is up to one throttle tick stale — never clobber the
            # crash-adjacent events with it
            continue
        if isinstance(snap, (list, tuple)):
            snap = {"events": list(snap)}
        ranks[str(label)] = dict(snap)
    err = None
    if error is not None:
        err = {"type": type(error).__name__,
               "message": str(error)[:2000],
               "rank": getattr(error, "rank", None)}
        diag = getattr(error, "diagnosis", None)
        if diag:
            err["diagnosis"] = dict(diag)
    compiles = None
    try:
        from ..analysis import compile_guard
        compiles = compile_guard.compile_count()
    except BaseException:  # jax missing/broken: the report still writes
        pass
    report: Dict[str, Any] = {
        "schema": REPORT_SCHEMA,
        "kind": "run_report",
        "trace_id": trace_id,
        "written_unix": time.time(),
        "error": err,
        "stall_diagnosis": (dict(stall_diagnosis)
                            if stall_diagnosis else None),
        "compile": {"driver_backend_compiles": compiles},
        "ranks": ranks,
        "metrics": registry.to_json() if registry is not None else None,
    }
    if extra:
        report["extra"] = dict(extra)
    return report


def write_run_report(path: str, **kwargs: Any) -> Optional[str]:
    """Write ``build_run_report(**kwargs)`` to ``path`` (a directory gets
    ``run_report.json`` appended).  Atomic tmp+rename; returns the final
    path, or None on failure — a postmortem write error is logged, never
    raised over the run's real exception."""
    try:
        report = build_run_report(**kwargs)
        if os.path.isdir(path) or not path.endswith(".json"):
            path = os.path.join(path, REPORT_BASENAME)
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(report, f, default=str)
        os.replace(tmp, path)
        log.warning("run report written: %s", path)
        return path
    except BaseException as e:
        log.warning("failed to write run report to %s: %s", path, e)
        return None
