"""Live telemetry plane: per-rank HTTP endpoints + a driver ClusterView.

Everything the repo could observe before this module was end-of-run or
post-crash: ``MetricsRegistry`` exports are assembled after fit returns,
flight-recorder spills are read at postmortem time, and
``run_report.json`` exists only once something died.  This module turns
the same ledgers into LIVE, scrapeable signals while the run is still
running:

- **TelemetryServer** — a per-process stdlib ``ThreadingHTTPServer``
  (loopback-bound; remote reads ride the agent relay, never an open
  port) serving four endpoints:

  - ``/metrics``  — Prometheus exposition text built at scrape time
    from the process's *live* sources (trainer Profiler spans, perf
    observatory ledgers, ServeMetrics, flight-recorder event tallies,
    compile counts) via the same ``MetricsRegistry.prometheus_text()``
    machinery the end-of-run export uses;
  - ``/statusz``  — JSON: flight-recorder tail, recent StepTimeline
    rows, HBM pools, goodput, global_step, trace id, serve/SLO gauges
    (what ``scripts/rla_top.py`` renders);
  - ``/healthz``  — heartbeat-age-informed ``ok | slow | wedged``,
    classified with the same thresholds ``runtime/watchdog.py`` uses
    (a chaos-hung rank's ``/healthz`` flips to wedged from its own
    frozen beat BEFORE the watchdog reaps it); HTTP 503 when wedged so
    plain load-balancer checks work;
  - ``/snapshot`` — the mergeable wire shape (profiler
    ``export_state``, events, serve snapshots, perf ledgers) the
    ClusterView aggregates.

  The server is opt-in: it starts only when ``RLA_TPU_METRICS_PORT`` is
  set (0 = ephemeral).  Workers ALWAYS bind ephemeral (a fixed port
  would collide across ranks on one host) and publish the bound port
  via an atomic portfile under ``RLA_TPU_TELEMETRY_DIR`` — the same
  crash-surviving channel the flight-recorder spills use — so the
  driver discovers them without any registration round-trip.
  Installed on the driver in ``Trainer.fit`` / ``ServeEngine.start``
  and on workers in ``runtime.actors._worker_main`` (per-worker env
  overlay honored).

- **ClusterView** — the driver-side aggregator: periodically collects
  every rank's ``/snapshot`` (portfile scrape for local pools; the
  ``live`` wire op on ``runtime/agent.py`` for remote pools — the same
  seam as ``telemetry_tail``) into one rank-labeled merged
  ``MetricsRegistry``, re-exported on the driver's own ``/metrics``
  and embedded in ``run_report.json`` as the last live view before
  death.

Scrape-path discipline: handlers read host-side aggregates only
(profiler exports, recorder rings, metadata byte counts) — never a
device value, so a scrape can never inject a host sync into the loops
it observes (graftlint roots its hot-path rules at ``LiveHandler.do_GET``
and ``ClusterView.refresh``).  No jax import at module scope: the plane
stays importable (and ``rla_top`` runnable) with a wedged backend.
"""

from __future__ import annotations

import json
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, List, Mapping, Optional
from urllib.request import ProxyHandler, build_opener

from ..analysis import knobs
from . import recorder as recorder_lib
from .registry import MetricsRegistry

PORT_ENV = "RLA_TPU_METRICS_PORT"
REFRESH_ENV = "RLA_TPU_LIVE_REFRESH_S"

DEFAULT_REFRESH_S = 2.0
# Prometheus text exposition content type (the version string is part of
# the scrape contract)
PROM_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"
# /statusz ships a bounded tail, never the whole ring
STATUSZ_TAIL_N = 32
FETCH_TIMEOUT_S = 2.0

log = recorder_lib.log

# health states mirror runtime/watchdog.py (kept as literals so this
# module never imports the runtime package — watchdog already imports
# telemetry, and the plane must stay importable standalone)
HEALTH_OK = "ok"
HEALTH_SLOW = "slow"
HEALTH_WEDGED = "wedged"
_WATCHDOG_DEFAULT_WEDGE_S = 60.0
_WATCHDOG_BOOT_GRACE_S = 120.0


def classify_health(beat: Optional[Mapping[str, Any]],
                    wedge_timeout_s: Optional[float] = None,
                    boot_grace_s: float = _WATCHDOG_BOOT_GRACE_S,
                    dispatch_deadline_s: Optional[float] = None
                    ) -> Dict[str, Any]:
    """``ok | slow | wedged`` from a heartbeat snapshot, with the same
    thresholds the driver watchdog applies (``RLA_TPU_WEDGE_TIMEOUT_S``
    staleness, boot grace while the rank never beat,
    busy-past-a-dispatch-deadline = wedged, busy-past-half-the-trigger
    = slow).  ``beat=None`` (no channel: the driver process, or
    heartbeats disabled) is liveness-only and classifies ``ok`` — the
    watchdog's never-false-positive rule.

    ``dispatch_deadline_s`` mirrors ``Watchdog(dispatch_deadline_s=)``;
    it is a driver-side constructor argument with no env knob, so a
    rank's OWN ``/healthz`` cannot see a deadline the driver chose —
    pass it when building sources driver-side; worker endpoints apply
    staleness + straggler rules only (the watchdog default is also
    ``None`` = dispatches may run arbitrarily long)."""
    if beat is None:
        return {"status": HEALTH_OK,
                "detail": "no heartbeat channel (liveness-only)"}
    if wedge_timeout_s is None:
        wedge_timeout_s = knobs.get_float("RLA_TPU_WEDGE_TIMEOUT_S",
                                          _WATCHDOG_DEFAULT_WEDGE_S)
    boot_grace_s = max(boot_grace_s, wedge_timeout_s)
    out: Dict[str, Any] = dict(beat)
    out["wedge_timeout_s"] = wedge_timeout_s
    started = beat.get("started", True)
    stale_after = wedge_timeout_s if started else boot_grace_s
    age = float(beat.get("beat_age_s") or 0.0)
    busy = beat.get("busy_s")
    # slow trigger matches Watchdog: half the dispatch deadline when
    # one is configured, else half the wedge timeout
    trigger = (dispatch_deadline_s if dispatch_deadline_s is not None
               else wedge_timeout_s)
    if age > stale_after:
        what = "wedge timeout" if started else "boot grace"
        out["status"] = HEALTH_WEDGED
        out["detail"] = (f"heartbeat stale {age:.2f}s > {what} "
                         f"{stale_after:.2f}s")
    elif busy is not None and dispatch_deadline_s is not None \
            and busy > dispatch_deadline_s:
        out["status"] = HEALTH_WEDGED
        out["detail"] = (f"dispatch busy {busy:.2f}s > deadline "
                         f"{dispatch_deadline_s:.2f}s")
    elif busy is not None and busy > trigger / 2.0:
        out["status"] = HEALTH_SLOW
        out["detail"] = (f"dispatch busy {busy:.2f}s (straggler past "
                         f"{trigger / 2.0:.2f}s)")
    else:
        out["status"] = HEALTH_OK
    return out


# --------------------------------------------------------------------- #
# Live sources (what the endpoints read at scrape time)                   #
# --------------------------------------------------------------------- #
class LiveSources:
    """Mutable bindings the server reads per scrape — nothing is copied
    at bind time, so the endpoints always reflect the process's CURRENT
    state.  ``bind_trainer`` wires a fitting trainer (profiler, perf
    observatory, global step); ``add_serve`` wires a running engine's
    ServeMetrics (+ its SLO tracker); ``bind_cluster_view`` folds the
    driver's merged per-rank view into the driver export."""

    def __init__(self, rank: Optional[int] = None,
                 beat_snapshot_fn: Optional[Callable[[], Any]] = None,
                 dispatch_deadline_s: Optional[float] = None):
        self.rank = rank
        self.beat_snapshot_fn = beat_snapshot_fn
        # per-dispatch wedge deadline (see classify_health): driver-side
        # callers that configured Watchdog(dispatch_deadline_s=) pass
        # the same value so /healthz agrees with the reaper
        self.dispatch_deadline_s = dispatch_deadline_s
        self._lock = threading.Lock()
        self._trainer: Any = None
        self._serve: "Dict[str, Any]" = {}
        self._slo: "Dict[str, Any]" = {}
        self._cluster_view: Any = None
        # serve replica-tier controller (serve/controller.py): its
        # per-replica table rides /statusz and the replica gauge family
        self._replica_controller: Any = None

    # -- binds ---------------------------------------------------------- #
    def bind_trainer(self, trainer: Any) -> None:
        with self._lock:
            self._trainer = trainer

    def add_serve(self, label: str, metrics: Any, slo: Any = None) -> None:
        with self._lock:
            self._serve[str(label)] = metrics
            if slo is not None:
                self._slo[str(label)] = slo

    def remove_serve(self, label: str) -> None:
        with self._lock:
            self._serve.pop(str(label), None)
            self._slo.pop(str(label), None)

    def bind_cluster_view(self, view: Any) -> None:
        with self._lock:
            self._cluster_view = view

    def bind_replica_controller(self, controller: Any) -> None:
        """Wire (or, with None, unwire) a ``ReplicaController`` so the
        serve tier's per-replica state/load table is scrapeable live
        (``/statusz`` ``replica_controller`` +
        ``rla_tpu_serve_replica_*`` gauges on ``/metrics``).  One
        controller table per process export: with several
        ``ServeReplicas`` groups alive the most recently bound wins —
        use ``unbind_replica_controller`` on teardown so one group's
        shutdown cannot evict another's still-live table."""
        with self._lock:
            self._replica_controller = controller

    def unbind_replica_controller(self, controller: Any) -> None:
        """Remove ``controller`` from the export ONLY if it is the one
        currently bound (a shut-down group must not unbind a sibling
        group that bound after it)."""
        with self._lock:
            if self._replica_controller is controller:
                self._replica_controller = None

    def _bound(self):
        with self._lock:
            return (self._trainer, dict(self._serve), dict(self._slo),
                    self._cluster_view)

    # -- reads ---------------------------------------------------------- #
    @property
    def rank_label(self) -> str:
        return "driver" if self.rank is None else str(self.rank)

    def _beat(self) -> Optional[Dict[str, Any]]:
        fn = self.beat_snapshot_fn
        if fn is None:
            return None
        try:
            snap = fn()
        except Exception:
            return None
        return dict(snap) if snap else None

    def health(self) -> Dict[str, Any]:
        out = classify_health(
            self._beat(),
            dispatch_deadline_s=self.dispatch_deadline_s)
        out["rank"] = self.rank_label
        return out

    def rank_status(self) -> Dict[str, Any]:
        """The compact per-rank row ClusterView/rla_top key on."""
        trainer, serve, slo, _cv = self._bound()
        rec = recorder_lib.get_recorder()
        health = self.health()
        row: Dict[str, Any] = {
            "rank": self.rank_label,
            "pid": os.getpid(),
            "trace_id": rec.trace_id,
            "health": health,
            "healthy": 1.0 if health["status"] in (HEALTH_OK, HEALTH_SLOW)
            else 0.0,
            "events_per_second": round(rec.events_per_second(), 4),
        }
        if trainer is not None:
            row["global_step"] = int(getattr(trainer, "global_step", 0))
            row["epoch"] = int(getattr(trainer, "current_epoch", 0))
        if serve:
            row["serve_engines"] = sorted(serve)
        return row

    def build_registry(self) -> MetricsRegistry:
        """The live ``MetricsRegistry`` behind ``/metrics``: the bound
        trainer's unified registry when one is fitting (same code path
        as the end-of-run export), else a recorder-only base — plus
        every bound engine's ServeMetrics, this rank's status row, and
        the ClusterView's merged per-rank data on the driver."""
        trainer, serve, _slo, cv = self._bound()
        reg: Optional[MetricsRegistry] = None
        if trainer is not None:
            try:
                reg = trainer.build_metrics_registry()
            except Exception as e:  # a scrape must degrade, never 500
                log.warning("live registry build via trainer failed: %s", e)
                reg = None
        if reg is None:
            reg = MetricsRegistry(
                trace_id=recorder_lib.current_trace_id())
            reg.add_events(recorder_lib.get_recorder().events(),
                           rank=self.rank_label)
            try:
                reg.add_compile_count(rank=self.rank_label)
            except BaseException:  # jax.monitoring unavailable
                pass
        for label, m in serve.items():
            reg.add_serve(m, rank=label)
        with self._lock:
            rc = self._replica_controller
        if rc is not None:
            try:
                reg.add_replica_controller(rc.snapshot())
            except Exception as e:  # a scrape must degrade, never 500
                log.warning("replica-controller export failed: %s", e)
        reg.add_rank_status(self.rank_label, self.rank_status())
        reg.add_scalar("events_per_second",
                       recorder_lib.get_recorder().events_per_second())
        if cv is not None \
                and getattr(trainer, "_cluster_view", None) is not cv:
            # merge the bound view UNLESS the bound trainer owns this
            # same view — its build_metrics_registry already merged it,
            # and merging twice would double-count rank data
            try:
                cv.merge_into(reg)
            except Exception as e:
                log.warning("cluster-view merge failed: %s", e)
        return reg

    def statusz(self) -> Dict[str, Any]:
        """The human/CLI-facing JSON: identity + health + the recent
        slices of every live ledger (bounded — the full ring/reservoirs
        stay behind ``/snapshot``)."""
        trainer, serve, slo, cv = self._bound()
        rec = recorder_lib.get_recorder()
        out: Dict[str, Any] = self.rank_status()
        out["ts"] = round(time.monotonic(), 6)
        out["flight_tail"] = rec.tail(STATUSZ_TAIL_N)
        if trainer is not None:
            perf = getattr(trainer, "perf", None)
            if perf is not None:
                tl = perf.timeline.snapshot()
                out["step_timeline"] = {
                    k: tl.get(k) for k in
                    ("steps", "mean_step_ms", "phase_sum_over_wall",
                     "attributed_fraction")}
                out["recent_steps"] = tl.get("recent_steps", [])[-8:]
                hbm = perf.hbm.snapshot()
                out["hbm"] = {"total_bytes": hbm["total_bytes"],
                              "attributed_fraction":
                                  hbm["attributed_fraction"],
                              "pools": {k: v["bytes"] for k, v in
                                        hbm["pools"].items()},
                              "leak_alarms": hbm["leak_alarms"]}
                gp = perf.goodput.snapshot()
                if gp["wall_s"] > 0:
                    out["goodput"] = gp
        if serve:
            out["serve"] = {label: m.snapshot()
                            for label, m in serve.items()}
        if slo:
            out["slo"] = {label: t.snapshot()
                          for label, t in slo.items()}
        with self._lock:
            rc = self._replica_controller
        if rc is not None:
            try:
                out["replica_controller"] = rc.snapshot()
            except Exception:
                pass
        if cv is not None:
            out["cluster"] = cv.last_view()
        return out

    def snapshot(self) -> Dict[str, Any]:
        """The mergeable wire shape ``ClusterView.refresh`` collects:
        everything ``MetricsRegistry`` knows how to fold — profiler
        ``export_state``, raw events, serve snapshots, perf ledgers,
        compile count — plus the status row."""
        trainer, serve, _slo, _cv = self._bound()
        rec = recorder_lib.get_recorder()
        out: Dict[str, Any] = {
            "rank": self.rank_label,
            "status": self.rank_status(),
            "events": rec.events(),
        }
        if trainer is not None:
            prof = getattr(trainer, "profiler", None)
            if prof is not None:
                out["profiler"] = prof.export_state()
            perf = getattr(trainer, "perf", None)
            if perf is not None:
                out["perf"] = {
                    "step_timeline": perf.timeline.snapshot(),
                    "hbm": perf.hbm.snapshot()}
        if serve:
            out["serve"] = {label: m.snapshot()
                            for label, m in serve.items()}
        with self._lock:
            rc = self._replica_controller
        if rc is not None:
            try:
                out["replica_controller"] = rc.snapshot()
            except Exception:
                pass
        try:
            from ..analysis import compile_guard
            out["compile"] = compile_guard.compile_count()
        except BaseException:
            pass
        return out


# --------------------------------------------------------------------- #
# HTTP server                                                             #
# --------------------------------------------------------------------- #
class _LiveHTTPServer(ThreadingHTTPServer):
    daemon_threads = True
    # carries the sources for the handler (set by TelemetryServer.start)
    rla_sources: LiveSources = None  # type: ignore[assignment]


class LiveHandler(BaseHTTPRequestHandler):
    """The four endpoints.  Scrape-time work only — each GET rebuilds
    its payload from the live sources, so there is no cache to go
    stale and nothing runs unless someone is actually looking."""

    server_version = "rla-tpu-live/1"
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):  # stdlib default spams stderr
        pass

    def do_GET(self) -> None:  # noqa: N802 (stdlib handler contract)
        sources: LiveSources = self.server.rla_sources
        path = self.path.split("?", 1)[0]
        try:
            if path == "/metrics":
                body = sources.build_registry().prometheus_text()
                self._reply(200, PROM_CONTENT_TYPE, body.encode())
            elif path == "/statusz":
                self._json(200, sources.statusz())
            elif path == "/healthz":
                health = sources.health()
                code = 200 if health["status"] != HEALTH_WEDGED else 503
                self._json(code, health)
            elif path == "/snapshot":
                self._json(200, sources.snapshot())
            else:
                self._json(404, {"error": f"unknown path {path!r}",
                                 "paths": ["/metrics", "/statusz",
                                           "/healthz", "/snapshot"]})
        except Exception as e:  # a broken source must not kill the server
            try:
                self._json(500, {"error": f"{type(e).__name__}: {e}"})
            except Exception:
                pass

    def _json(self, code: int, payload: Mapping[str, Any]) -> None:
        self._reply(code, "application/json",
                    json.dumps(payload, default=str).encode())

    def _reply(self, code: int, ctype: str, body: bytes) -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)


def portfile_for(rank: Optional[int],
                 env: Optional[Mapping[str, str]] = None) -> Optional[str]:
    """Where ``rank``'s server publishes its bound port under
    ``RLA_TPU_TELEMETRY_DIR`` (None when no dir is configured)."""
    tdir = knobs.get_str(recorder_lib.DIR_ENV, None, env=env)
    if not tdir:
        return None
    label = "driver" if rank is None else f"rank{int(rank)}"
    return os.path.join(tdir, f"{label}.port.json")


def read_portfile(path: Optional[str]) -> Optional[Dict[str, Any]]:
    """A published port record, or None (missing/torn files are an
    expected state around process churn, never an error)."""
    if not path:
        return None
    try:
        with open(path) as f:
            rec = json.load(f)
    except (OSError, ValueError):
        return None
    return rec if isinstance(rec, dict) and rec.get("port") else None


# proxy-free opener: every live-plane fetch targets loopback, and a
# host-level http_proxy (common on pod images) would otherwise route
# 127.0.0.1 through the proxy and silently kill the whole plane
_OPENER = build_opener(ProxyHandler({}))


def fetch_json(url: str,
               timeout: float = FETCH_TIMEOUT_S) -> Optional[Dict[str, Any]]:
    """GET ``url`` (proxy-bypassed — see ``_OPENER``) and parse JSON;
    None on any failure (an unreachable rank is a fact to report, not
    an exception to raise)."""
    try:
        with _OPENER.open(url, timeout=timeout) as resp:
            payload = json.loads(resp.read().decode())
    except Exception:
        return None
    return payload if isinstance(payload, dict) else None


def scrape_rank(rank: Optional[int],
                env: Optional[Mapping[str, str]] = None,
                path: str = "/snapshot") -> Optional[Dict[str, Any]]:
    """Portfile-discovered scrape of one LOCAL rank's endpoint — the
    driver-side half of ``Worker.live_snapshot`` (remote ranks go
    through the agent ``live`` wire op, which calls this agent-side)."""
    rec = read_portfile(portfile_for(rank, env=env))
    if rec is None:
        return None
    return fetch_json(f"http://127.0.0.1:{rec['port']}{path}")


class TelemetryServer:
    """One process's live-telemetry HTTP server (loopback-bound).

    ``port``: explicit bind port; 0 = ephemeral; None reads
    ``RLA_TPU_METRICS_PORT``.  ``start()`` binds, publishes the
    portfile (when a telemetry dir is configured) and serves from a
    daemon thread; ``shutdown()`` unbinds and removes the portfile."""

    def __init__(self, sources: Optional[LiveSources] = None,
                 port: Optional[int] = None,
                 rank: Optional[int] = None,
                 env: Optional[Mapping[str, str]] = None):
        self.sources = sources or LiveSources(rank=rank)
        if port is None:
            port = knobs.get_int(PORT_ENV, None, env=env)
        self._requested_port = int(port or 0)
        self.rank = rank if rank is not None else self.sources.rank
        self._env = dict(env) if env else None
        self._httpd: Optional[_LiveHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self._portfile: Optional[str] = None

    @property
    def port(self) -> Optional[int]:
        return (self._httpd.server_address[1]
                if self._httpd is not None else None)

    @property
    def url(self) -> Optional[str]:
        p = self.port
        return f"http://127.0.0.1:{p}" if p else None

    def start(self) -> "TelemetryServer":
        if self._httpd is not None:
            return self
        httpd = _LiveHTTPServer(("127.0.0.1", self._requested_port),
                                LiveHandler)
        httpd.rla_sources = self.sources
        self._httpd = httpd
        self._thread = threading.Thread(
            target=httpd.serve_forever, kwargs={"poll_interval": 0.2},
            daemon=True, name="rla-tpu-live-telemetry")
        self._thread.start()
        self._publish_portfile()
        log.warning("live telemetry serving on %s (rank %s)",
                    self.url, self.sources.rank_label)
        return self

    def _publish_portfile(self) -> None:
        path = portfile_for(self.rank, env=self._env)
        if path is None:
            return
        tmp = f"{path}.tmp.{os.getpid()}"
        try:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            with open(tmp, "w") as f:
                json.dump({"rank": self.sources.rank_label,
                           "pid": os.getpid(), "port": self.port,
                           "url": self.url}, f)
            os.replace(tmp, path)
            self._portfile = path
        except OSError as e:  # discovery degrades; the server still runs
            try:
                os.unlink(tmp)
            except OSError:
                pass
            log.warning("live telemetry portfile %s failed: %s", path, e)

    def shutdown(self) -> None:
        httpd, self._httpd = self._httpd, None
        if httpd is not None:
            httpd.shutdown()
            httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        if self._portfile:
            try:
                os.unlink(self._portfile)
            except OSError:
                pass
            self._portfile = None

    def __enter__(self) -> "TelemetryServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.shutdown()


# --------------------------------------------------------------------- #
# Process singleton                                                       #
# --------------------------------------------------------------------- #
_server: Optional[TelemetryServer] = None
_server_lock = threading.Lock()


def get_server() -> Optional[TelemetryServer]:
    return _server


def maybe_start_from_env(rank: Optional[int] = None,
                         env: Optional[Mapping[str, str]] = None,
                         beat_snapshot_fn: Optional[Callable[[], Any]]
                         = None) -> Optional[TelemetryServer]:
    """Start (once per process) the live server when
    ``RLA_TPU_METRICS_PORT`` is configured; None when the knob is unset
    or the bind failed.  Workers (``rank`` set) always bind ephemeral —
    a knob-fixed port would collide across ranks on one host; the
    portfile is the discovery channel either way.  A failure degrades
    (warn + no server): the plane observes runs, it must never take
    one down."""
    global _server
    port = knobs.get_int(PORT_ENV, None, env=env)
    if port is None:
        return _server
    with _server_lock:
        if _server is not None:
            return _server
        try:
            srv = TelemetryServer(
                sources=LiveSources(rank=rank,
                                    beat_snapshot_fn=beat_snapshot_fn),
                port=0 if rank is not None else port,
                rank=rank, env=env)
            _server = srv.start()
        except Exception as e:
            log.warning("live telemetry server failed to start: %s", e)
            _server = None
        return _server


def shutdown_server() -> None:
    global _server
    with _server_lock:
        srv, _server = _server, None
    if srv is not None:
        srv.shutdown()


def _reset_for_tests() -> None:
    shutdown_server()


# --------------------------------------------------------------------- #
# ClusterView (driver-side aggregator)                                    #
# --------------------------------------------------------------------- #
class ClusterView:
    """Periodically collects every rank's live ``/snapshot`` into one
    rank-labeled merged view.

    ``workers``: pool workers exposing ``live_snapshot()`` (local
    ``Worker`` reads the rank's portfile and scrapes loopback; agent
    ``RemoteWorker`` relays the ``live`` wire op so the scrape happens
    on the rank's own host).  Without workers, the telemetry dir's
    portfiles are scanned directly — the pool-independent mode
    ``rla_top`` and serve deployments use.  ``refresh()`` tolerates
    dead/unreachable ranks (they drop out of the view; the LAST
    successful view survives, which is exactly what the run report
    wants to embed after a crash)."""

    def __init__(self, workers: Optional[List[Any]] = None,
                 refresh_s: Optional[float] = None,
                 env: Optional[Mapping[str, str]] = None):
        if refresh_s is None:
            refresh_s = knobs.get_float(REFRESH_ENV, DEFAULT_REFRESH_S,
                                        env=env)
        self.refresh_s = max(0.05, float(refresh_s))
        self.workers = list(workers) if workers is not None else None
        self._env = dict(env) if env else None
        self._lock = threading.Lock()
        self._view: Dict[str, Dict[str, Any]] = {}
        self._refreshed_at: Optional[float] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- collection ----------------------------------------------------- #
    def _scan_portfiles(self) -> Dict[str, Dict[str, Any]]:
        tdir = knobs.get_str(recorder_lib.DIR_ENV, None, env=self._env)
        if not tdir or not os.path.isdir(tdir):
            return {}
        out: Dict[str, Dict[str, Any]] = {}
        for fname in sorted(os.listdir(tdir)):
            if not fname.endswith(".port.json"):
                continue
            label = fname[:-len(".port.json")]
            if label == "driver":
                continue  # the driver's own sources are already local
            rec = read_portfile(os.path.join(tdir, fname))
            if rec is None:
                continue
            snap = fetch_json(f"http://127.0.0.1:{rec['port']}/snapshot")
            if snap:
                out[label.replace("rank", "", 1)
                    if label.startswith("rank") else label] = snap
        return out

    def refresh(self) -> Dict[str, Dict[str, Any]]:
        """One collection sweep; returns {rank label: snapshot}.  Ranks
        that fail to answer are absent from THIS sweep but the merged
        last-view keeps their final successful snapshot."""
        snaps: Dict[str, Dict[str, Any]] = {}
        if self.workers is not None:
            for w in self.workers:
                fn = getattr(w, "live_snapshot", None)
                if fn is None:
                    continue
                try:
                    snap = fn()
                except BaseException:
                    snap = None
                if snap:
                    snaps[str(getattr(w, "rank", "?"))] = snap
        else:
            snaps = self._scan_portfiles()
        with self._lock:
            self._view.update(snaps)
            self._refreshed_at = time.monotonic()
        return snaps

    # -- export --------------------------------------------------------- #
    def view(self) -> Dict[str, Dict[str, Any]]:
        with self._lock:
            return {k: dict(v) for k, v in self._view.items()}

    def last_view(self) -> Dict[str, Any]:
        """Compact JSON-able form for ``/statusz`` and the run report:
        per-rank status rows + serve gauges (the bulky mergeable parts —
        profiler reservoirs, full event rings — stay out; spill files
        already carry the timelines)."""
        with self._lock:
            view = {k: dict(v) for k, v in self._view.items()}
            refreshed = self._refreshed_at
        ranks: Dict[str, Any] = {}
        for label, snap in view.items():
            row = dict(snap.get("status") or {})
            if snap.get("serve"):
                row["serve"] = snap["serve"]
            if snap.get("compile") is not None:
                row["compile"] = snap["compile"]
            ranks[label] = row
        return {
            "refreshed_age_s": (round(time.monotonic() - refreshed, 3)
                                if refreshed is not None else None),
            "ranks": ranks,
        }

    def merge_into(self, reg: MetricsRegistry,
                   skip_mergeables: Any = ()) -> MetricsRegistry:
        """Fold the last collected view into ``reg`` rank-labeled:
        profilers merge reservoir-correct, events tally, serve
        snapshots and status rows keep their rank labels.
        ``skip_mergeables``: rank labels whose profiler/events/serve
        data is ALREADY in the registry from another channel (the
        post-run ``_rank_telemetry`` home-ship) — only their live
        status rows are added, so nothing double-counts."""
        skip = {str(s) for s in skip_mergeables}
        for label, snap in self.view().items():
            if snap.get("status"):
                reg.add_rank_status(label, snap["status"])
            if label in skip:
                continue
            if snap.get("profiler"):
                reg.add_profiler(snap["profiler"], rank=label)
            if snap.get("events"):
                reg.add_events(snap["events"], rank=label)
            for slabel, s in (snap.get("serve") or {}).items():
                reg.add_serve(s, rank=f"{label}:{slabel}")
            if snap.get("compile") is not None:
                reg.add_compile_count(int(snap["compile"]), rank=label)
        return reg

    def merged_registry(self) -> MetricsRegistry:
        return self.merge_into(MetricsRegistry())

    # -- background refresh --------------------------------------------- #
    def start(self) -> "ClusterView":
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, daemon=True,
                name="rla-tpu-cluster-view")
            self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.refresh_s):
            try:
                self.refresh()
            except Exception as e:  # observation must never crash
                log.warning("cluster-view refresh failed: %s", e)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def __enter__(self) -> "ClusterView":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
