"""Flight recorder: a bounded per-process ring of typed structured events.

The per-process observability primitives that already exist — Profiler
spans, ServeMetrics counters, watchdog diagnosis dicts — are *aggregates*:
they say a run got slow, not WHAT HAPPENED in what order on which rank.
This module records the order: every interesting transition (a train
step, a prefetch starvation, a preemption drain, a serve admission) is
one structured event ``(monotonic ts, rank, kind, trace id, payload)``
appended to a fixed-capacity ring.  The ring is the black-box flight
recorder — bounded allocation by construction (a ``deque(maxlen=N)``
drops the oldest event per append; nothing ever grows with run length),
pure host-side work (no device values may enter a payload, so the emit
path can never introduce a host sync — graftlint roots its ``host-sync``
rule at :meth:`FlightRecorder.emit`), and cheap enough for hot loops
(one lock + one tuple per event).

**Trace IDs** correlate one logical operation across processes: the
driver mints an id at ``fit()``/request entry (``mint_trace_id``) and
every event carries the ambient id (``set_trace_id``) unless the emit
overrides it per event (serve requests each carry their own).  Workers
inherit the id from the ``RLA_TPU_TRACE_ID`` env overlay (raw actor
pools) or from the pickled trainer crossing the agent execute op
(``Trainer`` fan-out) — either way, driver, agent-spawned workers and
local workers stamp the SAME id, so a ``run_report.json`` timeline
reads as one run.

**Spill** makes the recorder crash-observable: when
``RLA_TPU_TELEMETRY_DIR`` is set, the ring is snapshotted to
``rank{N}.events.json`` in that directory (atomic tmp+rename, at most
once per ``RLA_TPU_TELEMETRY_SPILL_S`` seconds, first emit always).
A rank that wedges or dies leaves its last events on disk, where the
watchdog (``runtime/watchdog.py``), the agent ``telemetry`` wire op
(``runtime/agent.py``), and the run-report writer
(``telemetry/registry.py``) read them — the flight-recorder property:
the record survives the crash it describes.
"""

from __future__ import annotations

import json
import logging
import os
import secrets
import threading
import time
from collections import deque
from typing import Any, Dict, List, Mapping, Optional

from ..analysis import knobs

# child of the package logger (utils/logging.py configures the parent);
# importing utils.logging here would be circular — its formatter asks
# THIS module for the process rank
log = logging.getLogger("ray_lightning_accelerators_tpu.telemetry")

TELEMETRY_ENV = "RLA_TPU_TELEMETRY"
EVENTS_ENV = "RLA_TPU_TELEMETRY_EVENTS"
DIR_ENV = "RLA_TPU_TELEMETRY_DIR"
SPILL_S_ENV = "RLA_TPU_TELEMETRY_SPILL_S"
TRACE_ENV = "RLA_TPU_TRACE_ID"

DEFAULT_CAPACITY = 256
DEFAULT_SPILL_S = 0.5
# events embedded into a WorkerWedged diagnosis / report rank tails:
# the typed exception must stay a bounded, log-printable postmortem
EMBED_TAIL_N = 16

# the documented event vocabulary (docs/API.md "Telemetry & tracing").
# Emit sites may add kinds — the recorder is a transport, not a schema
# police — but everything the framework itself emits is declared here so
# dashboards and tests have one name list to key on.
EVENT_KINDS = frozenset({
    # trainer (core/trainer.py)
    "fit_start", "fit_end", "train_step", "epoch_end", "validation",
    "preempt_drain", "emergency_checkpoint",
    # input pipeline (data/prefetch.py)
    "prefetch_starved",
    # sharding resolution (accelerators/base.py): a large param leaf (or
    # the optimizer-state mapping) fell back to REPLICATED under
    # use_fsdp — silent loss of FSDP memory savings, surfaced
    "fsdp_fallback",
    # perf observatory (telemetry/perf.py): the HBM ledger saw placed
    # bytes grow monotonically for a whole leak streak
    "hbm_leak",
    # SPMD sanitizer (testing/spmd_sanitizer.py): one traced collective
    # call recorded while the opt-in sanitizer is installed — the
    # unified timeline's view of the per-rank collective sequence (the
    # authoritative diff channel is the sanitizer's own spill file)
    "spmd_collective",
    # worker dispatch loop (runtime/actors.py)
    "dispatch_begin", "dispatch_end",
    # supervision / retry layers (runtime/watchdog.py, runtime/elastic.py)
    "watchdog_transition", "elastic_attempt", "elastic_failure",
    "elastic_preempt_resume", "elastic_shrink", "elastic_grow",
    # numeric anomaly guardian (runtime/guardian.py): a tripped in-step
    # guard (train tier) or non-finite decode logits (serve tier); a
    # blamed data window entering the quarantine ledger; an ElasticRunner
    # resume that rewinds to the last verified checkpoint
    "anomaly_trip", "quarantine", "rewind",
    # live resize (runtime/elastic.py resize_in_memory /
    # core/trainer.py resize_in_memory): the between-attempt in-memory
    # resharding window — old/new world size, redistribution bytes
    # moved, waves and wall seconds
    "resize_begin", "resize_end",
    # MPMD pipeline (parallel/mpmd): one slot of a stage's tick program
    # (worker-side), one optimizer step across all stage groups
    # (driver-side), and one checkpoint-replay recovery — all stamped
    # with the fit's trace id so the cross-stage timeline stitches
    "pipeline_tick", "pipeline_step", "pipeline_replay",
    # serve lifecycle (serve/engine.py)
    "serve_admit", "serve_prefill", "serve_decode_step", "serve_respond",
    # serve SLO engine (serve/slo.py): a request missed its attached
    # SLO — TTFT/token-cadence target exceeded, or the deadline passed
    # while it was still queued (family "deadline" = shed before
    # prefill, typed DeadlineExceeded)
    "slo_violation",
    # serve replica controller (serve/controller.py): per-replica
    # state transitions (ok/slow/open/draining), hedged re-dispatch,
    # circuit-breaker revival, autoscale moves and typed brownout sheds
    "serve_replica_state", "serve_hedge", "serve_revive",
    "serve_scale_up", "serve_scale_down", "serve_brownout_shed",
})


def mint_trace_id() -> str:
    """A fresh 16-hex-char trace id (one logical fit / request / run)."""
    return secrets.token_hex(8)


class FlightRecorder:
    """Bounded ring of structured events for ONE process.

    ``capacity`` bounds allocation (oldest events drop); ``rank`` is
    stamped on every event (None = the driver process); ``spill_path``
    (optional) is where snapshots land for cross-process readers.
    Thread-safe: serve threads, the prefetch consumer and the fit loop
    all emit into the same ring.
    """

    def __init__(self, capacity: int = DEFAULT_CAPACITY,
                 rank: Optional[int] = None,
                 trace_id: Optional[str] = None,
                 spill_path: Optional[str] = None,
                 spill_min_s: float = DEFAULT_SPILL_S,
                 enabled: bool = True):
        self.capacity = max(1, int(capacity))
        self.rank = rank
        self.trace_id = trace_id
        self.spill_path = spill_path
        self.spill_min_s = max(0.0, float(spill_min_s))
        self.enabled = enabled
        self._ring: deque = deque(maxlen=self.capacity)
        self._lock = threading.Lock()
        self._spill_lock = threading.Lock()
        self._last_spill = float("-inf")  # first emit always spills
        self._spill_warned = False

    # ------------------------------------------------------------------ #
    def emit(self, kind: str, trace: Optional[str] = None,
             **data: Any) -> None:
        """Append one event.  ``data`` values MUST be host scalars /
        strings (events cross pickles, JSON spills and exception
        messages; a device array here would also make this hot-path call
        a host sync).  ``trace`` overrides the ambient trace id for this
        event only (per-request serve traces)."""
        if not self.enabled:
            return
        evt = (time.monotonic(), self.rank, kind,
               trace if trace is not None else self.trace_id,
               data or None)
        with self._lock:
            self._ring.append(evt)
        if self.spill_path is not None:
            self._maybe_spill()

    def events(self, last_n: Optional[int] = None) -> List[Dict[str, Any]]:
        """The ring's events as JSON-able dicts, oldest first."""
        with self._lock:
            evts = list(self._ring)
        if last_n is not None:
            evts = evts[-last_n:]
        out = []
        for ts, rank, kind, trace, data in evts:
            row: Dict[str, Any] = {"ts": round(ts, 6), "rank": rank,
                                   "kind": kind, "trace": trace}
            if data:
                row["data"] = dict(data)
            out.append(row)
        return out

    def tail(self, n: int = EMBED_TAIL_N,
             kind: Optional[str] = None) -> List[Dict[str, Any]]:
        """The last ``n`` events, optionally only those of one ``kind``
        — the ``/statusz`` "what is this rank doing" slice, bounded by
        construction (never the whole ring over the wire).  ``n <= 0``
        means no tail (an ``evts[-0:]`` slice would be the WHOLE
        ring)."""
        if n is None or int(n) <= 0:
            return []
        evts = self.events()
        if kind is not None:
            evts = [e for e in evts if e["kind"] == kind]
        return evts[-int(n):]

    def events_per_second(self, window_s: float = 60.0) -> float:
        """Emit rate over (up to) the trailing ``window_s`` seconds —
        the cheap liveness gauge ``/statusz`` and the rank-status rows
        report.  The denominator is floored at 1s so a single fresh
        event reads ~1 ev/s, not a spike."""
        now = time.monotonic()
        with self._lock:
            stamps = [ts for ts, *_rest in self._ring
                      if now - ts <= window_s]
        if not stamps:
            return 0.0
        return len(stamps) / max(1.0, now - stamps[0])

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
        self._last_spill = float("-inf")

    def snapshot(self, last_n: Optional[int] = None) -> Dict[str, Any]:
        """Wire/spill-shaped record: identity + the recent events."""
        return {
            "rank": self.rank,
            "pid": os.getpid(),
            "trace_id": self.trace_id,
            "ts": round(time.monotonic(), 6),
            "events": self.events(last_n),
        }

    # ------------------------------------------------------------------ #
    # Spill (crash-observability)                                         #
    # ------------------------------------------------------------------ #
    def _maybe_spill(self) -> None:
        if time.monotonic() - self._last_spill < self.spill_min_s:
            return
        # non-blocking: if another thread is mid-write its snapshot is
        # fresh enough — a hot-path emit must never block on disk I/O
        if not self._spill_lock.acquire(blocking=False):
            return
        try:
            if time.monotonic() - self._last_spill < self.spill_min_s:
                return
            self._spill_unlocked()
        finally:
            self._spill_lock.release()

    def spill(self) -> Optional[str]:
        """Snapshot the ring to ``spill_path`` (atomic tmp+rename).
        Blocks until the write lands (deliberate spills — e.g. the last
        one before a crash report — must not be skipped).  Never raises:
        telemetry must not take down the path it watches — a failing
        disk logs one warning and the ring stays in memory."""
        with self._spill_lock:
            return self._spill_unlocked()

    def _spill_unlocked(self) -> Optional[str]:
        path = self.spill_path
        if path is None:
            return None
        tmp = f"{path}.tmp.{os.getpid()}"
        self._last_spill = time.monotonic()
        try:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            with open(tmp, "w") as f:
                json.dump(self.snapshot(), f)
            os.replace(tmp, path)
            return path
        except Exception as e:
            # OSError = failing disk; TypeError/ValueError = a caller
            # handed emit() a non-JSON-able payload — either way the
            # ring stays in memory and the hot path keeps running
            try:
                os.unlink(tmp)
            except OSError:
                pass
            if not self._spill_warned:
                self._spill_warned = True
                log.warning("telemetry spill to %s failed: %s",
                            path, e)
            return None


# --------------------------------------------------------------------- #
# Process singleton                                                      #
# --------------------------------------------------------------------- #
_recorder: Optional[FlightRecorder] = None
_recorder_lock = threading.Lock()


def _build(rank: Optional[int],
           env: Optional[Mapping[str, str]]) -> FlightRecorder:
    return FlightRecorder(
        capacity=knobs.get_int(EVENTS_ENV, DEFAULT_CAPACITY, env=env),
        rank=rank,
        trace_id=knobs.get_str(TRACE_ENV, None, env=env),
        spill_path=spill_path_for(rank, env=env),
        spill_min_s=knobs.get_float(SPILL_S_ENV, DEFAULT_SPILL_S, env=env),
        enabled=knobs.get_bool(TELEMETRY_ENV, True, env=env))


def get_recorder() -> FlightRecorder:
    """This process's flight recorder (built from knobs on first use;
    the driver's rank is None until ``configure`` says otherwise)."""
    global _recorder
    if _recorder is None:
        with _recorder_lock:
            if _recorder is None:
                _recorder = _build(None, None)
    return _recorder


def configure(rank: Optional[int] = None,
              env: Optional[Mapping[str, str]] = None,
              trace_id: Optional[str] = None,
              enabled: Optional[bool] = None) -> FlightRecorder:
    """(Re)build the process recorder.  Worker processes call this at
    boot (``runtime.actors._worker_main``) with their rank and per-worker
    env overlay, so the spill file is rank-keyed and the trace id /
    enable switch honor the overlay; tests use it to rebuild after
    monkeypatching knobs."""
    global _recorder
    with _recorder_lock:
        rec = _build(rank, env)
        if trace_id is not None:
            rec.trace_id = trace_id
        if enabled is not None:
            rec.enabled = enabled
        _recorder = rec
    return rec


def _reset_for_tests() -> None:
    global _recorder
    with _recorder_lock:
        _recorder = None


# -- module-level conveniences (the emit-site API) ---------------------- #
def emit(kind: str, trace: Optional[str] = None, **data: Any) -> None:
    get_recorder().emit(kind, trace=trace, **data)


def set_trace_id(trace_id: Optional[str]) -> None:
    get_recorder().trace_id = trace_id


def current_trace_id() -> Optional[str]:
    return get_recorder().trace_id


def current_rank() -> Optional[int]:
    """The configured process rank (None = driver) — consumed by the
    log formatter (utils/logging.py) so every log line is rank-stamped."""
    rec = _recorder
    return rec.rank if rec is not None else None


# --------------------------------------------------------------------- #
# Cross-process readers (spill files)                                    #
# --------------------------------------------------------------------- #
def spill_path_for(rank: Optional[int],
                   env: Optional[Mapping[str, str]] = None
                   ) -> Optional[str]:
    """Where ``rank``'s recorder spills under ``RLA_TPU_TELEMETRY_DIR``
    (per-worker env overlay honored), or None when no dir is set."""
    tdir = knobs.get_str(DIR_ENV, None, env=env)
    if not tdir:
        return None
    label = "driver" if rank is None else f"rank{int(rank)}"
    return os.path.join(tdir, f"{label}.events.json")


def read_spill(path: Optional[str]) -> Optional[Dict[str, Any]]:
    """A spilled snapshot, or None (missing / torn / unreadable files are
    an expected state mid-crash, never an error)."""
    if not path:
        return None
    try:
        with open(path) as f:
            snap = json.load(f)
    except (OSError, ValueError):
        return None
    return snap if isinstance(snap, dict) else None


def tail_events(snapshot: Optional[Dict[str, Any]],
                n: int = EMBED_TAIL_N) -> List[Dict[str, Any]]:
    """The last ``n`` events of a spill/wire snapshot (empty when None)."""
    if not snapshot:
        return []
    evts = snapshot.get("events") or []
    return list(evts[-n:])
