"""Perf observatory: quantitative ledgers over the telemetry layer.

PR 7's flight recorder answers *what happened in what order*; this
module answers *where each millisecond and each HBM byte went, and
whether that is getting worse*.  Three ledgers, all exported through
:class:`~.registry.MetricsRegistry` (JSON + Prometheus) and embedded in
``run_report.json``:

- :class:`StepTimeline` — per-step phase decomposition of the train
  loop (and the serve prefill/decode loop): host wall time between step
  boundaries partitioned into named phases (``h2d``, ``compile``,
  ``compute``, ``ckpt``, ``drain``, ...) from low-overhead hooks in
  ``core/trainer.py`` / ``serve/engine.py``, with the un-attributed
  remainder surfaced as ``other`` instead of silently vanishing.  The
  jitted step is ONE dispatch, so its interior (forward/backward vs
  exposed comm vs optimizer) cannot be split from the host; the
  analytic wire split (``collectives.wire_bytes_per_step``) rides along
  in the snapshot and :func:`exposed_comm_crosscheck` turns a tree-vs-
  scan A/B measurement into a measured exposed-comm fraction with the
  measured-vs-analytic discrepancy exported, not asserted away.
- :class:`HbmLedger` — per-pool HBM attribution (FSDP param/optimizer/
  exchange-buffer shards, paged KV pool, device cache, prefetch
  buffers) with live watermarks sampled off the hot path (throttled)
  and a monotonic-growth leak alarm that emits a typed ``hbm_leak``
  flight-recorder event.
- :class:`GoodputLedger` — wall time across ``ElasticRunner`` attempts
  partitioned into productive step time vs compile, checkpoint
  save/restore, preemption drain, restart/boot and wedge-detection
  wait: ONE goodput fraction per run, the number an operator pages on.

The hot-path discipline matches the flight recorder's: host scalars
only (graftlint roots its ``host-sync`` rule at the sampling seams
here), bounded allocation (aggregates + a fixed ring of recent steps),
and a per-emit cost in the recorder's <50us/emit spirit (test-pinned).
No jax import at module scope — the ledgers stay importable (and the
gate runnable) on a machine whose backend is wedged.
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from typing import Any, Callable, Dict, List, Mapping, Optional

from ..analysis import knobs
from . import recorder as recorder_lib

HBM_SAMPLE_S_ENV = "RLA_TPU_PERF_HBM_SAMPLE_S"
LEAK_SAMPLES_ENV = "RLA_TPU_PERF_LEAK_SAMPLES"
LEAK_MIN_BYTES_ENV = "RLA_TPU_PERF_LEAK_MIN_BYTES"
TIMELINE_RING_ENV = "RLA_TPU_PERF_TIMELINE_RING"

DEFAULT_HBM_SAMPLE_S = 2.0
DEFAULT_LEAK_SAMPLES = 8
DEFAULT_LEAK_MIN_BYTES = 32 * 1024 * 1024
DEFAULT_TIMELINE_RING = 64

# the documented phase vocabulary (docs/API.md "Perf observatory").
# Emit sites may add phases; everything the framework itself observes
# is declared here so dashboards have one name list to key on.
PHASE_KINDS = frozenset({
    # trainer fit loop (core/trainer.py)
    "h2d", "compute", "compile", "ckpt", "drain", "validation", "other",
    # serve engine loop (serve/engine.py)
    "prefill", "decode",
    # MPMD pipeline driver (parallel/mpmd/driver.py): step wall minus
    # mean per-member busy — the schedule's idle fraction as a phase
    "pipeline_bubble",
})

GOODPUT_CATEGORIES = ("productive", "compile", "checkpoint", "drain",
                      "restart", "wedge_wait")


def tree_nbytes(tree: Any) -> int:
    """Total logical bytes of a pytree of (device or host) arrays —
    ``leaf.nbytes`` is shape metadata, never a device sync.  Deleted
    leaves (donated buffers whose python handle outlived them) count
    zero instead of raising."""
    if tree is None:
        return 0
    import jax  # lazy: the ledgers must import without a backend
    total = 0
    for leaf in jax.tree.leaves(tree):
        try:
            total += int(getattr(leaf, "nbytes", 0) or 0)
        except Exception:  # deleted donated buffer: worth 0, not a crash
            continue
    return total


def placed_bytes_total() -> int:
    """This process's total placed device bytes: PjRt ``bytes_in_use``
    where the backend reports it (real HBM), else the summed ``nbytes``
    of every live ``jax.Array`` (the CPU-mesh fallback — same logical-
    bytes measure the per-pool attribution uses, so the two sides of
    the ledger stay comparable)."""
    import jax

    from ..utils.profiler import device_bytes_in_use
    in_use = device_bytes_in_use()
    if in_use:
        return in_use
    total = 0
    for a in jax.live_arrays():
        try:
            total += int(a.nbytes)
        except Exception:  # racing deletion: skip, don't crash the sample
            continue
    return total


# --------------------------------------------------------------------- #
# Step timeline                                                          #
# --------------------------------------------------------------------- #
class StepTimeline:
    """Per-step phase decomposition of a host-driven loop.

    One driving thread brackets each step with ``step_begin()`` /
    ``step_end()`` and wraps its phases in ``phase(name)`` (or reports
    externally timed durations via ``observe``).  ``step_end`` computes
    the step's wall time and attributes the un-covered remainder to
    ``other`` — so in-step phases sum to the measured step wall by
    construction, and a growing ``other`` means the hooks are missing
    something, visibly.  Phases observed OUTSIDE a step bracket
    (checkpoint saves at epoch boundaries, preemption drains) accumulate
    in the same totals under ``in_step=False``.

    ``compile_seconds_fn`` (e.g. ``analysis.compile_guard
    .compile_seconds``) is snapshotted at each step boundary; compile
    time landing inside a step is split out of the containing measured
    phase (a warmup step reads as compile + compute, not one opaque
    blob).  Memory is bounded: per-phase aggregates plus a fixed ring
    of the most recent per-step rows.
    """

    def __init__(self, ring: Optional[int] = None,
                 compile_seconds_fn: Optional[Callable[[], float]] = None):
        if ring is None:
            ring = knobs.get_int(TIMELINE_RING_ENV, DEFAULT_TIMELINE_RING)
        self.ring_capacity = max(1, int(ring))
        self._compile_fn = compile_seconds_fn
        self._lock = threading.Lock()
        # phase -> [count, total_s]; in-step and out-of-step tracked
        # separately so the sum-to-wall invariant stays checkable
        self._phases: Dict[str, List[float]] = {}
        self._out_phases: Dict[str, List[float]] = {}
        self._recent: List[Dict[str, Any]] = []
        self._steps = 0
        self._wall_total = 0.0
        self._comms: Optional[Dict[str, Any]] = None
        # live step bracket: owned by the thread that called
        # step_begin — foreign threads (a serve loop sharing the
        # timeline) must not write into an open train step
        self._t_step: Optional[float] = None
        self._step_thread: Optional[int] = None
        self._step_phases: Dict[str, float] = {}
        self._compile_at_begin = 0.0

    def __getstate__(self):
        """Ship-able across processes (the Trainer pickles itself into
        workers): locks and accumulated state stay behind."""
        return {"ring": self.ring_capacity}

    def __setstate__(self, state):
        self.__init__(ring=state["ring"])

    # -- hooks ----------------------------------------------------------- #
    def step_begin(self) -> None:
        self._step_phases = {}
        self._step_thread = threading.get_ident()
        self._t_step = time.perf_counter()
        if self._compile_fn is not None:
            self._compile_at_begin = self._compile_fn()

    def observe(self, name: str, dt_s: float) -> None:
        """Report one externally timed phase duration — attributed to
        the open step only from the thread that OPENED it; any other
        thread (a serve loop sharing the timeline with a fitting
        trainer) lands in the between-step totals instead of corrupting
        the open step's row."""
        if self._t_step is not None \
                and self._step_thread == threading.get_ident():
            # bracket-owner fast path: single-threaded by construction,
            # so the dict update needs no lock
            self._step_phases[name] = self._step_phases.get(name, 0.0) \
                + dt_s
            return
        with self._lock:
            row = self._out_phases.setdefault(name, [0, 0.0])
            row[0] += 1
            row[1] += dt_s

    @contextmanager
    def phase(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.observe(name, time.perf_counter() - t0)

    def step_end(self) -> None:
        t0 = self._t_step
        if t0 is None or self._step_thread != threading.get_ident():
            return  # no open bracket, or not the thread that opened it
        wall = time.perf_counter() - t0
        phases = self._step_phases
        self._t_step = None
        self._step_thread = None
        if self._compile_fn is not None:
            dc = self._compile_fn() - self._compile_at_begin
            if dc > 0:
                # compile happened inside one of the measured phases
                # (warmup dispatch): split it out so the phase reads as
                # what it was, never double-counted past the wall
                host = max(phases, key=phases.get) if phases else None
                dc = min(dc, phases.get(host, wall)) if host else \
                    min(dc, wall)
                if host:
                    phases[host] = phases[host] - dc
                phases["compile"] = phases.get("compile", 0.0) + dc
        other = wall - sum(phases.values())
        if other > 0:
            phases["other"] = phases.get("other", 0.0) + other
        with self._lock:
            self._steps += 1
            self._wall_total += wall
            for name, dt in phases.items():
                row = self._phases.setdefault(name, [0, 0.0])
                row[0] += 1
                row[1] += dt
            self._recent.append(
                {"step": self._steps, "wall_s": round(wall, 6),
                 "phases": {k: round(v, 6) for k, v in phases.items()}})
            if len(self._recent) > self.ring_capacity:
                del self._recent[0]

    def observe_scan_epoch(self, wall_s: float, n_steps: int) -> None:
        """The scanned-epoch path is ONE dispatch for a whole epoch —
        per-step phases do not exist there, so the epoch's wall is
        attributed to ``compute`` across ``n_steps`` equal steps (one
        coarse ring row marks the batch)."""
        n = max(1, int(n_steps))
        with self._lock:
            self._steps += n
            self._wall_total += wall_s
            row = self._phases.setdefault("compute", [0, 0.0])
            row[0] += n
            row[1] += wall_s
            self._recent.append(
                {"step": self._steps, "wall_s": round(wall_s, 6),
                 "scanned_steps": n,
                 "phases": {"compute": round(wall_s, 6)}})
            if len(self._recent) > self.ring_capacity:
                del self._recent[0]

    def attach_comms(self, report: Optional[Mapping[str, Any]]) -> None:
        """Carry the analytic wire split (``wire_bytes_per_step``) in
        the snapshot, so the exported timeline states the exchange's
        exposed/hidden byte claim next to the measured phase times."""
        with self._lock:
            self._comms = dict(report) if report else None

    # -- export ---------------------------------------------------------- #
    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            phases = {k: {"count": int(c), "total_s": round(t, 6)}
                      for k, (c, t) in sorted(self._phases.items())}
            out_phases = {k: {"count": int(c), "total_s": round(t, 6)}
                          for k, (c, t) in
                          sorted(self._out_phases.items())}
            steps, wall = self._steps, self._wall_total
            recent = list(self._recent)
            comms = dict(self._comms) if self._comms else None
        in_step_total = sum(p["total_s"] for p in phases.values())
        attributed = sum(p["total_s"] for k, p in phases.items()
                         if k != "other")
        snap: Dict[str, Any] = {
            "steps": steps,
            "step_wall_total_s": round(wall, 6),
            "mean_step_ms": round(wall / steps * 1e3, 3) if steps else 0.0,
            "phases": phases,
            "between_step_phases": out_phases,
            # phases sum to wall by construction (`other` absorbs the
            # remainder); both fractions exported so a drifting hook
            # shows up as coverage loss, not silence
            "phase_sum_over_wall": round(in_step_total / wall, 4)
            if wall else 0.0,
            "attributed_fraction": round(attributed / wall, 4)
            if wall else 0.0,
            "recent_steps": recent,
        }
        if comms is not None:
            snap["comms_per_step"] = comms
            exch = comms.get("exchange_bytes_per_step") or 0
            if exch:
                snap["analytic_exposed_comm_fraction"] = round(
                    (comms.get("exposed_bytes_per_step", exch)) / exch, 4)
        return snap


# --------------------------------------------------------------------- #
# HBM ledger                                                             #
# --------------------------------------------------------------------- #
class HbmLedger:
    """Per-pool device-memory attribution with watermarks + leak alarm.

    Pools register a zero-argument ``bytes_fn`` returning their CURRENT
    logical bytes (``tree_nbytes`` over the pool's arrays — metadata
    only, never a sync).  ``maybe_sample()`` is the hot-path seam: a
    monotonic-clock throttle makes it a no-op most steps, and a real
    sample walks the registered pools, takes ``placed_bytes_total()``
    as ground truth, attributes the remainder to ``other``, advances
    per-pool peaks, and feeds the leak detector — ``leak_samples``
    consecutive total-growth samples adding up to at least
    ``leak_min_bytes`` emit ONE typed ``hbm_leak`` flight-recorder
    event per growth streak (the alarm re-arms when the growth stops).
    """

    def __init__(self, sample_min_s: Optional[float] = None,
                 leak_samples: Optional[int] = None,
                 leak_min_bytes: Optional[int] = None,
                 total_bytes_fn: Callable[[], int] = placed_bytes_total):
        if sample_min_s is None:
            sample_min_s = knobs.get_float(HBM_SAMPLE_S_ENV,
                                           DEFAULT_HBM_SAMPLE_S)
        if leak_samples is None:
            leak_samples = knobs.get_int(LEAK_SAMPLES_ENV,
                                         DEFAULT_LEAK_SAMPLES)
        if leak_min_bytes is None:
            leak_min_bytes = knobs.get_int(LEAK_MIN_BYTES_ENV,
                                           DEFAULT_LEAK_MIN_BYTES)
        self.sample_min_s = max(0.0, float(sample_min_s))
        self.leak_samples = max(2, int(leak_samples))
        self.leak_min_bytes = max(1, int(leak_min_bytes))
        self._total_fn = total_bytes_fn
        self._lock = threading.Lock()
        self._pools: Dict[str, Callable[[], int]] = {}
        self._last: Dict[str, int] = {}
        self._peaks: Dict[str, int] = {}
        self._last_total = 0
        self._peak_total = 0
        self._n_samples = 0
        self._last_sample_t = float("-inf")
        # leak streak: consecutive growth samples, values at streak
        # start (for growth attribution), one alarm per streak
        self._prev_total: Optional[int] = None
        self._prev_pools: Dict[str, int] = {}
        self._growth_run = 0
        self._growth_base_total = 0
        self._growth_base_pools: Dict[str, int] = {}
        self._alarmed = False
        self._leak_events = 0

    def __getstate__(self):
        return {"sample_min_s": self.sample_min_s,
                "leak_samples": self.leak_samples,
                "leak_min_bytes": self.leak_min_bytes}

    def __setstate__(self, state):
        self.__init__(**state)

    def register_pool(self, name: str,
                      bytes_fn: Callable[[], int]) -> None:
        """(Re)register one attribution pool.  Re-registering replaces
        the reader — a second fit on one trainer re-binds its state."""
        with self._lock:
            self._pools[str(name)] = bytes_fn

    def unregister_pool(self, name: str) -> None:
        with self._lock:
            self._pools.pop(str(name), None)
            self._last.pop(str(name), None)

    # -- sampling -------------------------------------------------------- #
    def maybe_sample(self) -> Optional[Dict[str, int]]:
        """Throttled sample — the per-step seam.  Costs one monotonic
        read when inside the throttle window."""
        if time.monotonic() - self._last_sample_t < self.sample_min_s:
            return None
        return self.sample()

    def sample(self) -> Dict[str, int]:
        """Walk the pools now.  Returns {pool: bytes} including the
        derived ``other`` and ``total``."""
        self._last_sample_t = time.monotonic()
        with self._lock:
            readers = list(self._pools.items())
        pools: Dict[str, int] = {}
        for name, fn in readers:
            try:
                pools[name] = int(fn() or 0)
            except Exception:  # a dead reader reports 0, never crashes
                pools[name] = 0  # the loop it samples from
        try:
            total = int(self._total_fn() or 0)
        except Exception:
            total = 0
        attributed = sum(pools.values())
        # a backend whose ground truth under-reports the attribution
        # (device stats lag a placement) still renders coherently:
        # other is the non-negative remainder
        pools["other"] = max(0, total - attributed)
        with self._lock:
            self._n_samples += 1
            self._last = dict(pools)
            self._last_total = total
            self._peak_total = max(self._peak_total, total)
            for name, b in pools.items():
                self._peaks[name] = max(self._peaks.get(name, 0), b)
            self._feed_leak_detector(total, pools)
        out = dict(pools)
        out["total"] = total
        return out

    def _feed_leak_detector(self, total: int,
                            pools: Dict[str, int]) -> None:
        # called under self._lock.  A "leak streak" is a run of
        # consecutive samples where the total strictly grew; the base
        # values (from the sample BEFORE the streak) attribute the
        # growth to a suspect pool when the alarm fires.
        prev, prev_pools = self._prev_total, self._prev_pools
        self._prev_total, self._prev_pools = total, dict(pools)
        if prev is None:
            return
        if total > prev:
            if self._growth_run == 0:
                self._growth_base_total = prev
                self._growth_base_pools = prev_pools
            self._growth_run += 1
        else:
            self._growth_run = 0
            self._alarmed = False
            return
        growth = total - self._growth_base_total
        if (not self._alarmed and self._growth_run >= self.leak_samples
                and growth >= self.leak_min_bytes):
            self._alarmed = True
            self._leak_events += 1
            deltas = {k: pools.get(k, 0) - self._growth_base_pools.get(k, 0)
                      for k in pools}
            top = max(deltas, key=deltas.get) if deltas else None
            recorder_lib.emit(
                "hbm_leak", total_bytes=total, growth_bytes=int(growth),
                samples=int(self._growth_run),
                suspect_pool=top,
                suspect_growth_bytes=int(deltas.get(top, 0)) if top
                else 0)

    # -- export ---------------------------------------------------------- #
    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            pools = {name: {"bytes": int(self._last.get(name, 0)),
                            "peak_bytes": int(self._peaks.get(name, 0))}
                     for name in sorted(set(self._last)
                                        | set(self._peaks))}
            total = self._last_total
            snap = {
                "samples": self._n_samples,
                "total_bytes": int(total),
                "peak_total_bytes": int(self._peak_total),
                "pools": pools,
                "attributed_bytes": int(sum(
                    v["bytes"] for k, v in pools.items() if k != "other")),
                "leak_alarms": int(self._leak_events),
                "leak_streak_samples": int(self._growth_run),
            }
        snap["attributed_fraction"] = round(
            snap["attributed_bytes"] / total, 4) if total else 0.0
        return snap


# --------------------------------------------------------------------- #
# Goodput ledger                                                         #
# --------------------------------------------------------------------- #
class GoodputLedger:
    """Run-level wall-time partition: productive step time vs everything
    a retrying, checkpointing, preemptible run spends around it.

    The driver-side owner (``ElasticRunner``) accounts what it can see
    (restart/boot, backoff, wedge-detection wait — or ``resize`` when
    the runner reshards in memory instead of restarting, so the live
    path and the checkpoint round-trip are priced in the same ledger);
    worker-side fits report their interior split — ``absorb_timeline``
    maps a
    :class:`StepTimeline` snapshot's phases into categories, and
    ``absorb_profiler`` does the same from a ``Profiler`` export for
    bodies without a timeline.  ``goodput_fraction`` =
    productive / total wall; the un-accounted remainder is exported as
    ``unattributed_s``, never silently folded into goodput.
    """

    # timeline phase / profiler span -> goodput category
    _PHASE_MAP = {"h2d": "productive", "compute": "productive",
                  "other": "productive", "compile": "compile",
                  "ckpt": "checkpoint", "drain": "drain",
                  "validation": "productive"}
    _SPAN_MAP = {"train_step": "productive", "h2d": "productive",
                 "data_fetch": "productive", "validation": "productive",
                 "ckpt": "checkpoint", "drain": "drain"}

    def __init__(self):
        self._lock = threading.Lock()
        self._t0: Optional[float] = None
        self._wall: Optional[float] = None
        self._seconds: Dict[str, float] = {}
        self._preemptions = 0
        self._attempts = 0

    def run_begin(self) -> None:
        """Stamp the run's wall-clock start.  One ledger = one run: a
        ``run_begin`` AFTER a finished run (``run_end`` was called)
        resets everything — otherwise a reused ``ElasticRunner``'s
        second ``run()`` would compute wall from the FIRST run's start
        and dilute the fraction with inter-run idle time.  A
        ``run_begin`` while a run is still open stays a no-op."""
        with self._lock:
            if self._t0 is not None and self._wall is None:
                return  # run already open
            if self._wall is not None:
                # fresh run on a reused ledger: prior totals would
                # conflate two runs' seconds against one wall
                self._seconds = {}
                self._attempts = 0
                self._preemptions = 0
            self._t0 = time.monotonic()
            self._wall = None

    def run_end(self) -> None:
        with self._lock:
            if self._t0 is not None:
                self._wall = time.monotonic() - self._t0

    def account(self, category: str, seconds: float) -> None:
        with self._lock:
            self._seconds[category] = self._seconds.get(category, 0.0) \
                + max(0.0, float(seconds))

    @contextmanager
    def measure(self, category: str):
        t0 = time.monotonic()
        try:
            yield
        finally:
            self.account(category, time.monotonic() - t0)

    def note_attempt(self) -> None:
        with self._lock:
            self._attempts += 1

    def note_preemption(self) -> None:
        with self._lock:
            self._preemptions += 1

    def absorb_timeline(self, snapshot: Mapping[str, Any]) -> None:
        """Fold a :class:`StepTimeline` snapshot's phase totals (in-step
        AND between-step) into categories."""
        for fam in ("phases", "between_step_phases"):
            for name, row in (snapshot.get(fam) or {}).items():
                cat = self._PHASE_MAP.get(name)
                if cat:
                    self.account(cat, float(row.get("total_s", 0.0)))

    def absorb_profiler(self, profiler: Any) -> None:
        """Fold a ``Profiler`` (or its ``export_state()`` dict) span
        totals into categories — the no-timeline fallback."""
        state = profiler.export_state() if hasattr(profiler,
                                                   "export_state") \
            else profiler
        for name, row in (state.get("stats") or {}).items():
            cat = self._SPAN_MAP.get(name.split("/")[-1])
            if cat:
                self.account(cat, float(row.get("total", 0.0)))

    def absorb_events(self, events: Any) -> None:
        """Best-effort drain accounting from a flight-recorder timeline:
        a ``preempt_drain`` event followed by its ``emergency_checkpoint``
        bounds the drain the driver never directly timed."""
        t_drain = None
        for e in events or ():
            kind = e.get("kind")
            if kind == "preempt_drain":
                t_drain = e.get("ts")
            elif kind == "emergency_checkpoint" and t_drain is not None:
                ts = e.get("ts")
                if ts is not None and ts >= t_drain:
                    self.account("drain", ts - t_drain)
                t_drain = None

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            wall = self._wall
            if wall is None and self._t0 is not None:
                wall = time.monotonic() - self._t0
            wall = wall or 0.0
            seconds = {k: round(v, 6)
                       for k, v in sorted(self._seconds.items())}
            attempts, preemptions = self._attempts, self._preemptions
        accounted = sum(seconds.values())
        productive = seconds.get("productive", 0.0)
        return {
            "wall_s": round(wall, 6),
            "seconds": seconds,
            "unattributed_s": round(max(0.0, wall - accounted), 6),
            # clamped: absorbing N ranks' interior seconds against one
            # driver wall can overshoot 1.0 (absorb ONE rank's breakdown
            # per run for an exact fraction); productive_s stays raw
            "goodput_fraction": round(min(1.0, productive / wall), 4)
            if wall > 0 else 0.0,
            "productive_s": round(productive, 6),
            "attempts": attempts,
            "preemptions": preemptions,
        }


# --------------------------------------------------------------------- #
# Composite + crosscheck                                                 #
# --------------------------------------------------------------------- #
class PerfObservatory:
    """The three ledgers as one attachable unit: pass to
    ``Trainer(perf_observatory=...)`` (timeline + HBM wired into the fit
    loop) and feed ``goodput`` from an ``ElasticRunner`` or a probe.
    ``register()`` on a :class:`~.registry.MetricsRegistry` exports all
    three."""

    def __init__(self, timeline: Optional[StepTimeline] = None,
                 hbm: Optional[HbmLedger] = None,
                 goodput: Optional[GoodputLedger] = None):
        if timeline is None:
            try:
                from ..analysis import compile_guard
                timeline = StepTimeline(
                    compile_seconds_fn=compile_guard.compile_seconds)
            except Exception:  # jax.monitoring unavailable: no compile split
                timeline = StepTimeline()
        self.timeline = timeline
        self.hbm = hbm if hbm is not None else HbmLedger()
        self.goodput = goodput if goodput is not None else GoodputLedger()
        try:
            # host-side shm owned by this process's object store (the
            # pipeline-handoff transport) as an attribution pool: the
            # reader returns 0 until a store exists and never builds one
            from ..runtime.object_store import global_shm_bytes
            self.hbm.register_pool("object_store_shm", global_shm_bytes)
        except Exception:
            pass

    def __getstate__(self):
        return {}

    def __setstate__(self, state):
        self.__init__()

    def register(self, registry: Any) -> Any:
        registry.add_step_timeline(self.timeline)
        registry.add_hbm(self.hbm)
        if self.goodput.snapshot()["wall_s"] > 0:
            registry.add_goodput(self.goodput)
        return registry


def exposed_comm_crosscheck(
        measured_step_s: Mapping[str, float],
        wire_reports: Mapping[str, Mapping[str, Any]]) -> Dict[str, Any]:
    """Measured vs analytic exposed-comm accounting over an A/B of
    gather modes (the mfu_overlap probe's tree-vs-scan pair).

    The jitted step cannot be split from the host, so the MEASURED
    exposed-comm estimate is differential: the best-overlapped mode's
    step time is the compute floor, and each mode's excess over it is
    comm that mode exposes (a lower bound — the floor mode's own exposed
    comm is invisible to this measurement, which is exactly why the
    analytic split rides alongside).  The ANALYTIC share is
    ``exposed_bytes / exchange_bytes`` per ``wire_bytes_per_step``.
    Both directions and the per-mode discrepancy are exported; nothing
    is asserted away — a direction disagreement is a finding, not an
    error."""
    modes = [m for m in measured_step_s if m in wire_reports]
    if len(modes) < 2:
        raise ValueError(
            "exposed_comm_crosscheck needs >= 2 modes present in both "
            f"measured_step_s and wire_reports, got {modes!r}")
    floor = min(measured_step_s[m] for m in modes)
    out: Dict[str, Any] = {"modes": {}}
    for m in modes:
        step = float(measured_step_s[m])
        rep = wire_reports[m]
        exch = float(rep.get("exchange_bytes_per_step") or 0)
        exposed = float(rep.get("exposed_bytes_per_step", exch))
        analytic = (exposed / exch) if exch else 0.0
        measured = ((step - floor) / step) if step > 0 else 0.0
        out["modes"][m] = {
            "step_s": round(step, 6),
            "measured_exposed_s": round(step - floor, 6),
            "measured_exposed_fraction": round(measured, 4),
            "analytic_exposed_bytes": int(exposed),
            "analytic_exposed_fraction": round(analytic, 4),
            "discrepancy": round(measured - analytic, 4),
        }
    by_measured = sorted(modes, key=lambda m: measured_step_s[m])
    by_analytic = sorted(
        modes, key=lambda m: wire_reports[m].get(
            "exposed_bytes_per_step",
            wire_reports[m].get("exchange_bytes_per_step", 0)))
    out["measured_order"] = by_measured
    out["analytic_order"] = by_analytic
    out["direction_agrees"] = by_measured == by_analytic
    return out
