"""Wire-exception registry: typed errors that survive the worker pipe.

A worker-side exception crosses the actor pipe (and the agent relay) as
``(type name, message, traceback)`` — see ``actors._worker_main``.  The
retry layers only work when the SEMANTIC types are rebuilt driver-side:
``Preempted`` must resume without charging the failure budget,
``WorkerWedged`` must read as a retryable hang, ``ElasticResizeError``
must read as "pick a compatible size", never as a generic crash.

This module is the single reconstruction point.  ``WIRE_EXCEPTION_NAMES``
is the declared set (a literal, so graftlint's ``wire-exception`` rule
can extract it statically and reject raises of unregistered typed
exceptions in worker-dispatched code); ``rebuild_remote`` is the runtime
half used by both the local collector (``actors.Worker._collect``) and
the agent relay (``agent._recv_loop``) — before this registry the two
paths drifted (the relay rebuilt typed wedges, the local pipe wrapped
everything in ``RemoteError``).

Classes carrying structured payloads embed them in the message
(``WorkerWedged._MARKER`` / ``Preempted._MARKER``) and rebuild via
``from_message``; plain typed outcomes rebuild from the message alone.
"""

from __future__ import annotations

from typing import Callable, Dict

# the declared registry: keep this a LITERAL set of class names — the
# static analyzer reads it without importing the runtime
WIRE_EXCEPTION_NAMES = frozenset({
    "WorkerWedged",
    "Preempted",
    "ElasticResizeError",
    "QueueShutdown",
    "ObjectStoreError",
    "CollectiveMismatch",
    "PipelineHandoffTimeout",
    "NumericAnomaly",
})


def _rebuilders() -> Dict[str, Callable[[str], BaseException]]:
    # imported lazily: wire.py must stay importable from any runtime
    # module without creating cycles
    from ..parallel.mpmd.handoff import PipelineHandoffTimeout
    from ..testing.spmd_sanitizer import CollectiveMismatch
    from .elastic import ElasticResizeError
    from .guardian import NumericAnomaly
    from .object_store import ObjectStoreError
    from .preemption import Preempted
    from .queue import QueueShutdown
    from .watchdog import WorkerWedged

    return {
        "WorkerWedged": WorkerWedged.from_message,
        "Preempted": Preempted.from_message,
        "ElasticResizeError": ElasticResizeError,
        "QueueShutdown": QueueShutdown,
        "ObjectStoreError": ObjectStoreError,
        "CollectiveMismatch": CollectiveMismatch.from_message,
        "PipelineHandoffTimeout": PipelineHandoffTimeout.from_message,
        "NumericAnomaly": NumericAnomaly.from_message,
    }


def rebuild_remote(name: str, message: str,
                   remote_traceback: str) -> BaseException:
    """The typed exception for a wire tuple, or ``RemoteError`` for
    anything unregistered (builtins and one-off errors stay generic on
    purpose: only types a retry/orchestration layer branches on belong
    in the registry).

    Rebuilt exceptions carry ``remote_typed = True``: they came from an
    ``(name, message, tb)`` error payload — i.e. the DISPATCHED CODE
    raised them — as opposed to the same types constructed driver-side
    by supervision (a watchdog ``WorkerWedged.for_rank``).  Failure
    classifiers (``serve/replicas.py``) use the flag to keep worker-side
    application errors from reading as infrastructure death."""
    from .actors import RemoteError

    rebuild = _rebuilders().get(name)
    if rebuild is not None:
        try:
            exc = rebuild(message)
            exc.remote_typed = True
            return exc
        except Exception:  # a malformed payload must not mask the error
            pass
    return RemoteError(name, message, remote_traceback)
