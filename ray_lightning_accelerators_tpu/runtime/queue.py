"""Callable-trampoline queue + driver result pump.

Direct capability analog of the reference's queue/poll machinery
(reference: ray_lightning/util.py -- `_QueueActor` :22-68, `Queue` :71-85,
`_handle_queue` :88-93, `process_results` :96-109): workers ship zero-arg
callables to the process that owns the Tune session; the driver executes
them while the training work runs.

TPU-native simplifications: without Ray the queue is a thread-safe
``queue.Queue`` (in-process trials, the default -- one process owns the TPU)
or a ``multiprocessing`` queue (subprocess trials); the "future" being polled
is a ``concurrent.futures.Future`` instead of a Ray ObjectRef.  The
drain-then-check loop and the final drain after completion (the race-closure
the reference handles at util.py:106-108) are preserved.
"""

from __future__ import annotations

import queue as queue_mod
import time
from concurrent.futures import Future
from typing import Any, Callable, List, Optional, Tuple


class TrampolineQueue:
    """put((rank, callable)) from workers; driver get()s and invokes."""

    def __init__(self, backend: Optional[Any] = None):
        self._q = backend if backend is not None else queue_mod.Queue()

    def put(self, item: Tuple[int, Callable[[], Any]]) -> None:
        self._q.put(item)

    def get_nowait(self):
        try:
            return self._q.get_nowait()
        except queue_mod.Empty:
            return None

    def empty(self) -> bool:
        return self._q.empty()

    def shutdown(self) -> None:
        pass


def drain_queue(q: Optional[TrampolineQueue]) -> int:
    """Execute every queued callable in the driver process
    (reference: util.py:88-93)."""
    if q is None:
        return 0
    n = 0
    while True:
        item = q.get_nowait()
        if item is None:
            break
        _rank, fn = item
        fn()
        n += 1
    return n


def process_results(futures: List[Future], q: Optional[TrampolineQueue],
                    poll_s: float = 0.01) -> List[Any]:
    """Poll training futures while draining the trampoline queue; final drain
    after completion closes the enqueue/finish race
    (reference: util.py:96-109).

    Fails FAST on the first errored future (the ray.get-on-ready semantics,
    reference: util.py:103): in a collective job one crashed rank leaves its
    peers blocked in a barrier forever, so waiting for all futures would
    hang the driver with the failure already in hand.
    """
    pending = list(futures)
    while pending:
        drain_queue(q)
        still = []
        for f in pending:
            if f.done():
                if f.exception() is not None:
                    drain_queue(q)
                    f.result()  # re-raise
            else:
                still.append(f)
        pending = still
        if pending:
            time.sleep(poll_s)
    drain_queue(q)
    return [f.result() for f in futures]
