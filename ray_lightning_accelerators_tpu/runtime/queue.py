"""Callable-trampoline queue + driver result pump.

Direct capability analog of the reference's queue/poll machinery
(reference: ray_lightning/util.py -- `_QueueActor` :22-68, `Queue` :71-85,
`_handle_queue` :88-93, `process_results` :96-109): workers ship zero-arg
callables to the process that owns the Tune session; the driver executes
them while the training work runs.

TPU-native simplifications: without Ray the queue is a thread-safe
``queue.Queue`` (in-process trials, the default -- one process owns the TPU)
or a ``multiprocessing`` queue (subprocess trials); the "future" being polled
is a ``concurrent.futures.Future`` instead of a Ray ObjectRef.  The
drain-then-check loop and the final drain after completion (the race-closure
the reference handles at util.py:106-108) are preserved.
"""

from __future__ import annotations

import os
import queue as queue_mod
import threading
import time
from concurrent.futures import Future
from typing import Any, Callable, List, Optional, Tuple


class QueueShutdown(RuntimeError):
    """Typed rejection for ``put()`` on a shut-down queue: the item would
    never be drained or executed, so silently accepting it loses work."""


class TrampolineQueue:
    """put((rank, callable)) from workers; driver get()s and invokes."""

    def __init__(self, backend: Optional[Any] = None):
        self._q = backend if backend is not None else queue_mod.Queue()
        self._lock = threading.Lock()
        self._closed = False

    @property
    def closed(self) -> bool:
        return self._closed

    def put(self, item: Tuple[int, Callable[[], Any]]) -> None:
        with self._lock:
            if self._closed:
                raise QueueShutdown(
                    "TrampolineQueue is shut down; the item would never "
                    "be drained")
            self._q.put(item)

    def get_nowait(self):
        try:
            return self._q.get_nowait()
        except queue_mod.Empty:
            return None

    def empty(self) -> bool:
        return self._q.empty()

    def shutdown(self) -> List[Any]:
        """Idempotent close.  Marks the queue closed (later ``put``s raise
        ``QueueShutdown``) and drains anything still enqueued WITHOUT
        executing it, returning the drained items so the caller can cancel
        them in a typed way (the serve engine fails each drained request
        with ``ServeCancelled``; executing driver thunks mid-teardown
        would race the state they close over).  Second and later calls
        are no-ops returning []."""
        with self._lock:
            first, self._closed = not self._closed, True
        drained: List[Any] = []
        if first:
            while True:
                item = self.get_nowait()
                if item is None:
                    break
                drained.append(item)
        return drained


class QueueServer:
    """Driver-side TCP endpoint feeding a TrampolineQueue from workers in
    OTHER processes/machines (the reference's queue was a Ray actor
    reachable from any node, reference: util.py:22-68; this is the
    no-Ray equivalent).  Each worker connects a QueueClient and streams
    ``(rank, thunk)`` frames; a reader thread per connection deserializes
    and enqueues locally."""

    def __init__(self, queue: TrampolineQueue, bind: Optional[str] = None,
                 query_handler=None):
        """``bind=None`` (default) binds loopback: queued thunks EXECUTE in
        this process, so the port is only opened to the network when remote
        workers actually need it (pass ``bind="0.0.0.0"`` for that, and set
        ``RLA_TPU_AGENT_TOKEN`` -- an open wide bind is warned about)."""
        import socket as socket_mod

        from .agent import _node_ip, _token_from_env
        from ..utils.logging import log

        self._queue = queue
        self._token = _token_from_env()  # fixed at construction
        # optional request/response channel riding the same socket: workers
        # can ASK the driver something (e.g. "was my trial STOPped?") --
        # handler(name, payload) -> result, run on the reader thread
        self._query_handler = query_handler
        from .agent import check_tokenless_wide_bind, is_loopback
        loopback = bind is None or is_loopback(bind)
        if bind is None:
            bind = "127.0.0.1"
        # queued frames are unpickled and EXECUTED driver-side -- the
        # same RCE gate as HostAgent (refuse tokenless wide binds;
        # RLA_TPU_ALLOW_TOKENLESS_BIND=1 opts out with a logged warning)
        check_tokenless_wide_bind("QueueServer", bind, self._token)
        self._srv = socket_mod.socket(socket_mod.AF_INET,
                                      socket_mod.SOCK_STREAM)
        self._srv.setsockopt(socket_mod.SOL_SOCKET,
                             socket_mod.SO_REUSEADDR, 1)
        self._srv.bind((bind, 0))
        self._srv.listen(128)
        host = "127.0.0.1" if loopback else _node_ip()
        self.address = f"{host}:{self._srv.getsockname()[1]}"
        import threading
        self._accept_thread = threading.Thread(target=self._accept_loop,
                                               daemon=True)
        self._accept_thread.start()

    def _accept_loop(self) -> None:
        import threading
        while True:
            try:
                conn, _addr = self._srv.accept()
            except OSError:
                return  # closed
            threading.Thread(target=self._reader, args=(conn,),
                             daemon=True).start()

    def _reader(self, conn) -> None:
        import cloudpickle

        from .agent import check_auth_frame, recv_raw, send_msg

        # same shared-secret contract as HostAgent: queued thunks EXECUTE
        # driver-side, so the FIRST frame is auth-checked on RAW bytes
        # before any unpickling.  A token-less server skips a leading auth
        # frame (tokened workers talking to an open driver); a tokened
        # server drops anything unauthenticated.
        def close():
            try:
                conn.close()
            except OSError:
                pass

        first_frame = True
        while True:
            try:
                raw = recv_raw(conn)
            except (ConnectionError, OSError):
                close()
                return
            if first_frame:
                first_frame = False
                verdict = check_auth_frame(raw, self._token)
                if verdict is True:
                    continue  # auth frame consumed
                if verdict is False:
                    close()
                    return
            try:
                item = cloudpickle.loads(raw)
            except BaseException:
                close()
                return  # malformed frame: drop the connection
            if isinstance(item, tuple) and len(item) == 2 \
                    and item[0] == "__rla_ack__":
                # flush barrier: everything this client sent earlier is
                # already enqueued locally (same reader thread, in order)
                try:
                    send_msg(conn, item)
                except OSError:
                    pass
                continue
            if isinstance(item, tuple) and len(item) == 3 \
                    and item[0] == "__rla_query__":
                _tag, name, payload = item
                try:
                    result = (None if self._query_handler is None
                              else self._query_handler(name, payload))
                except Exception as e:
                    # a broken handler must not kill the pump, but a silent
                    # None coerces to "keep going" downstream -- say so
                    from ..utils.logging import log
                    log.warning("queue query handler failed for %r: %s",
                                name, e)
                    result = None
                try:
                    send_msg(conn, ("__rla_query__", result))
                except OSError:
                    pass
                continue
            self._queue.put(item)

    def close(self) -> None:
        try:
            self._srv.close()
        except OSError:
            pass


class QueueClient:
    """Worker-side TrampolineQueue stand-in: ``put`` ships the thunk to
    the driver's QueueServer over TCP.  Duck-typed to the queue interface
    sessions use (put only -- workers never drain)."""

    def __init__(self, address: str):
        import socket as socket_mod
        import threading

        host, _, port = address.partition(":")
        self._sock = socket_mod.create_connection((host, int(port)),
                                                  timeout=30)
        self._sock.settimeout(None)
        self._lock = threading.Lock()
        from .agent import _token_from_env, auth_frame, send_raw
        token = _token_from_env()
        if token is not None:
            send_raw(self._sock, auth_frame(token))

    def put(self, item) -> None:
        from .agent import send_msg
        with self._lock:
            send_msg(self._sock, item)

    def flush(self) -> None:
        """Block until everything put() so far is ENQUEUED on the driver.

        Workers call this before returning their result: the result
        travels a different channel (the worker pipe) and could otherwise
        outrun the queue's reader thread, losing final reports."""
        from .agent import recv_msg, send_msg
        with self._lock:
            send_msg(self._sock, ("__rla_ack__", 0))
            recv_msg(self._sock)

    def query(self, name: str, payload=None):
        """Ask the driver's query handler something; blocks for the reply.
        The lock serializes queries with puts/flushes, so the next frame
        received is this query's response."""
        from .agent import recv_msg, send_msg
        with self._lock:
            send_msg(self._sock, ("__rla_query__", name, payload))
            _tag, result = recv_msg(self._sock)
            return result

    def empty(self) -> bool:
        return True

    def get_nowait(self):
        return None

    def shutdown(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass


def drain_queue(q: Optional[TrampolineQueue]) -> int:
    """Execute every queued callable in the driver process
    (reference: util.py:88-93)."""
    if q is None:
        return 0
    n = 0
    while True:
        item = q.get_nowait()
        if item is None:
            break
        _rank, fn = item
        fn()
        n += 1
    return n


def process_results(futures: List[Future], q: Optional[TrampolineQueue],
                    poll_s: float = 0.01,
                    deadline_s: Optional[float] = None) -> List[Any]:
    """Poll training futures while draining the trampoline queue; final drain
    after completion closes the enqueue/finish race
    (reference: util.py:96-109).

    Fails FAST on the first errored future (the ray.get-on-ready semantics,
    reference: util.py:103): in a collective job one crashed rank leaves its
    peers blocked in a barrier forever, so waiting for all futures would
    hang the driver with the failure already in hand.

    ``deadline_s``: monotonic wall-clock budget for the WHOLE set.  The
    watchdog normally fails a hung rank's futures first (WorkerWedged);
    this is the driver-side backstop for when heartbeats are disabled or
    the supervision channel itself is broken -- raises ``TimeoutError``
    with the unresolved ranks still pending (callers kill/restart the
    workers; the futures themselves stay unresolved).
    """
    pending = list(futures)
    deadline = (time.monotonic() + deadline_s
                if deadline_s is not None else None)
    while pending:
        drain_queue(q)
        still = []
        for f in pending:
            if f.done():
                if f.exception() is not None:
                    drain_queue(q)
                    f.result()  # re-raise
            else:
                still.append(f)
        pending = still
        if pending and deadline is not None \
                and time.monotonic() >= deadline:
            drain_queue(q)
            raise TimeoutError(
                f"process_results: {len(pending)} of {len(futures)} "
                f"futures unresolved past the {deadline_s:.1f}s deadline "
                "(workers hung without tripping the watchdog?)")
        if pending:
            time.sleep(poll_s)
    drain_queue(q)
    return [f.result() for f in futures]
