"""Routable-IP discovery shared by agents, workers, and the bootstrap.

The reference leans on Ray's ``get_node_ip_address`` which probes with a
routable UDP socket; ``socket.gethostbyname(socket.gethostname())`` is not
equivalent -- on common Debian/Ubuntu ``/etc/hosts`` layouts it resolves to
``127.0.1.1``, and that value gets advertised cross-machine as the
jax.distributed coordinator / queue-server address, making the rendezvous
unreachable from other hosts.
"""

from __future__ import annotations

import socket


def node_ip() -> str:
    """This host's routable IP.

    UDP-connect probe first (no packets are sent -- connect() on a datagram
    socket only runs the routing lookup), falling back to
    ``gethostbyname(gethostname())`` and finally loopback for hosts with no
    route at all (air-gapped CI).
    """
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        s.connect(("8.8.8.8", 80))
        ip = s.getsockname()[0]
        if not ip.startswith("127."):
            return ip
    except OSError:
        pass
    finally:
        s.close()
    try:
        return socket.gethostbyname(socket.gethostname())
    except OSError:
        return "127.0.0.1"
