"""Liveness subsystem: detect workers that stopped making PROGRESS.

The actor runtime's failure detection is process-liveness only: a dead
worker fails its futures ("worker died", runtime/actors.py collector) and
shows dead in ``ActorPool.health_check()``.  A worker wedged *inside* a
dispatched fn -- stuck in a broken collective, a hung TPU dispatch, a
deadlocked data pipeline -- never fails its future, so the driver waits
forever (the failure mode bench.py already guards against with subprocess
isolation; this module is the same upgrade for the training runtime,
mirroring the stall-detection-first design of eager-SPMD runtimes such as
veScale, PAPERS.md).

Three pieces:

- **HeartbeatChannel**: shared-memory beat between each worker process and
  the driver.  A worker-side daemon thread (``WorkerBeat``) stamps a
  monotonic beat every ``RLA_TPU_WORKER_HEARTBEAT_S``; the dispatch loop
  brackets every execution with a busy-since marker and a dispatch
  counter.  CLOCK_MONOTONIC is system-wide, so driver-side age reads need
  no cross-process clock agreement; for workers on OTHER machines the
  snapshot is taken agent-side and only *ages* cross the wire
  (runtime/agent.py ``heartbeat`` op).
- **Watchdog**: a driver-side thread classifying each rank
  ``ok | slow | wedged | dead`` from (process liveness, beat age, busy
  duration).  A rank is *wedged* when its beat went stale past
  ``RLA_TPU_WEDGE_TIMEOUT_S`` (frozen process) or a dispatch overran an
  explicit per-dispatch deadline (hung work).  Wedged ranks are reaped --
  SIGTERM-then-SIGKILL via ``Worker.reap`` -- so their pending futures
  fail with **WorkerWedged** (distinct from ``RemoteError``/died) and
  ``ElasticRunner`` retries exactly like a crash.
- **Diagnosis records**: every reap produces a machine-readable dict
  (bench.py death-record shape: ``error``/``detail`` plus ``stall_*``
  context) surfaced on the exception, the watchdog (``.reaped``), and
  ``Trainer.last_stall_diagnosis``.

State transitions are condition-signaled (``wait_for_state``), so tests
assert on events and monotonic deadlines, never sleep-poll loops.
"""

from __future__ import annotations

import json
import multiprocessing as mp
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..analysis import knobs
from ..telemetry import recorder as telemetry
from ..utils.logging import log

HEARTBEAT_ENV = "RLA_TPU_WORKER_HEARTBEAT_S"
WEDGE_ENV = "RLA_TPU_WEDGE_TIMEOUT_S"
DEFAULT_HEARTBEAT_S = 1.0
DEFAULT_WEDGE_TIMEOUT_S = 60.0

STATE_OK = "ok"
STATE_SLOW = "slow"
STATE_WEDGED = "wedged"
STATE_DEAD = "dead"


def heartbeat_interval_s(env: Optional[Dict[str, str]] = None) -> float:
    """Beat interval; a per-worker env overrides the process env.
    ``<= 0`` disables the channel entirely (liveness-only supervision)."""
    return knobs.get_float(HEARTBEAT_ENV, DEFAULT_HEARTBEAT_S, env=env)


def wedge_timeout_from_env() -> Optional[float]:
    """The env-configured wedge threshold, or None when unset (supervision
    stays opt-in for entry points that only watch when configured)."""
    return knobs.get_float(WEDGE_ENV, None)


class WorkerWedged(RuntimeError):
    """A rank was alive but stopped making progress and was killed by the
    watchdog.  Distinct from ``RemoteError`` (worker-side exception) and
    the generic 'worker died' (process exited on its own): callers such as
    ``ElasticRunner`` treat it as a retryable whole-attempt failure."""

    _MARKER = "| diagnosis="

    def __init__(self, message: str, rank: Optional[int] = None,
                 diagnosis: Optional[Dict[str, Any]] = None):
        super().__init__(message)
        self.rank = rank
        self.diagnosis = dict(diagnosis or {})

    @classmethod
    def for_rank(cls, rank: int,
                 diagnosis: Dict[str, Any]) -> "WorkerWedged":
        diagnosis = dict(diagnosis)
        diagnosis.setdefault("rank", rank)
        detail = diagnosis.get("detail", "stopped making progress")
        msg = (f"worker {rank} wedged (killed by watchdog): {detail} "
               f"{cls._MARKER}{json.dumps(diagnosis, sort_keys=True, default=str)}")
        return cls(msg, rank=rank, diagnosis=diagnosis)

    @classmethod
    def from_message(cls, message: str) -> "WorkerWedged":
        """Rebuild from a message that crossed a wire as (name, str, tb) --
        the agent relay path -- recovering the embedded diagnosis."""
        diagnosis: Dict[str, Any] = {}
        i = message.find(cls._MARKER)
        if i >= 0:
            try:
                diagnosis = json.loads(message[i + len(cls._MARKER):])
            except ValueError:
                pass
        return cls(message, rank=diagnosis.get("rank"), diagnosis=diagnosis)


# --------------------------------------------------------------------- #
# Heartbeat channel (shared memory, driver <-> worker process)           #
# --------------------------------------------------------------------- #
class HeartbeatChannel:
    """Three shared scalars: last beat stamp, busy-since marker (0 = idle),
    dispatch counter.  Created driver-side with the pool's mp context so
    it ships through spawn ``Process`` args; stamped worker-side; read
    driver-side as ages against the shared CLOCK_MONOTONIC."""

    def __init__(self, ctx: Optional[Any] = None):
        ctx = ctx or mp.get_context("spawn")
        now = time.monotonic()
        self._beat = ctx.Value("d", now)
        self._busy_since = ctx.Value("d", 0.0)
        self._dispatches = ctx.Value("L", 0)
        # flips on the worker's FIRST stamp: until then the process is
        # booting (interpreter spawn + imports can take tens of seconds)
        # and staleness is judged against the watchdog's boot grace, not
        # the wedge timeout
        self._started = ctx.Value("b", 0)

    # -- worker side --------------------------------------------------- #
    def stamp(self) -> None:
        self._beat.value = time.monotonic()
        self._started.value = 1

    def begin_dispatch(self) -> None:
        now = time.monotonic()
        with self._dispatches.get_lock():
            self._dispatches.value += 1
        self._busy_since.value = now
        self._beat.value = now
        self._started.value = 1

    def end_dispatch(self) -> None:
        self._busy_since.value = 0.0
        self._beat.value = time.monotonic()

    # -- driver side --------------------------------------------------- #
    def snapshot(self) -> Dict[str, Any]:
        """Ages, not absolute times: safe to relay across machines."""
        now = time.monotonic()
        beat = self._beat.value
        busy = self._busy_since.value
        return {
            "beat_age_s": max(0.0, now - beat),
            "busy_s": max(0.0, now - busy) if busy > 0.0 else None,
            "dispatches": int(self._dispatches.value),
            "started": bool(self._started.value),
        }


class WorkerBeat:
    """Worker-process side: a daemon thread stamping the channel every
    ``interval_s``.  ``freeze()`` stops stamping permanently -- used by
    chaos 'hang' injection to simulate a fully frozen process (a real
    frozen process stops beating by definition)."""

    def __init__(self, channel: HeartbeatChannel, interval_s: float):
        self.channel = channel
        self.interval_s = interval_s
        self._stop = threading.Event()
        self._frozen = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self.channel.stamp()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="rla-tpu-heartbeat")
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            if not self._frozen.is_set():
                self.channel.stamp()

    def begin_dispatch(self) -> None:
        if not self._frozen.is_set():
            self.channel.begin_dispatch()

    def end_dispatch(self) -> None:
        if not self._frozen.is_set():
            self.channel.end_dispatch()

    def freeze(self) -> None:
        self._frozen.set()

    def stop(self) -> None:
        self._stop.set()


# --------------------------------------------------------------------- #
# Watchdog (driver side)                                                 #
# --------------------------------------------------------------------- #
class Watchdog:
    """Classify every rank of a pool ``ok | slow | wedged | dead`` and
    (by default) reap wedged ranks so their futures fail ``WorkerWedged``.

    ``wedge_timeout_s``: beat staleness past this = frozen process ->
    wedged (default ``RLA_TPU_WEDGE_TIMEOUT_S``, else 60s).
    ``dispatch_deadline_s``: a single dispatched fn busy past this ->
    wedged.  None (default) = dispatches may run arbitrarily long
    (a legitimate fit body is one long dispatch); only beat staleness
    and process death are failures then.
    ``slow_after_s``: busy past this = ``slow`` (advisory straggler
    signal, never killed); defaults to half the wedge trigger.
    ``auto_reap``: SIGTERM-then-SIGKILL wedged ranks (via
    ``worker.reap``) and record a diagnosis; False = observe only.
    ``boot_grace_s``: staleness threshold while a worker process has
    never beaten -- interpreter spawn plus imports legitimately take
    tens of seconds, so judging boot by the wedge timeout would kill
    healthy workers mid-import.
    """

    def __init__(self, workers: Any,
                 wedge_timeout_s: Optional[float] = None,
                 dispatch_deadline_s: Optional[float] = None,
                 slow_after_s: Optional[float] = None,
                 poll_s: Optional[float] = None,
                 auto_reap: bool = True,
                 boot_grace_s: float = 120.0,
                 on_transition: Optional[
                     Callable[[int, str, str], None]] = None):
        # the source is kept, not just a snapshot: an ActorPool that
        # grows (serve scale-up, ActorPool.add_worker) or shrinks
        # (ActorPool.drop) between polls is re-listed every sweep, so
        # new ranks are supervised from their first poll and dropped
        # ranks stop being classified
        self._source = workers
        self.workers = list(getattr(workers, "workers", workers))
        if wedge_timeout_s is None:
            wedge_timeout_s = wedge_timeout_from_env()
        if wedge_timeout_s is None:
            wedge_timeout_s = DEFAULT_WEDGE_TIMEOUT_S
        self.wedge_timeout_s = float(wedge_timeout_s)
        self.dispatch_deadline_s = dispatch_deadline_s
        trigger = (dispatch_deadline_s if dispatch_deadline_s is not None
                   else self.wedge_timeout_s)
        self.slow_after_s = (slow_after_s if slow_after_s is not None
                             else trigger / 2.0)
        if poll_s is None:
            candidates = [self.wedge_timeout_s / 4.0]
            if dispatch_deadline_s is not None:
                candidates.append(dispatch_deadline_s / 4.0)
            poll_s = min(1.0, max(0.02, min(candidates)))
        self.poll_s = poll_s
        self.auto_reap = auto_reap
        self.boot_grace_s = max(boot_grace_s, self.wedge_timeout_s)
        self.on_transition = on_transition
        self.reaped: List[Dict[str, Any]] = []
        self._states: Dict[int, str] = {
            w.rank: STATE_OK for w in self.workers}
        self._cond = threading.Condition()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- classification ------------------------------------------------ #
    def classify(self, worker: Any) -> Tuple[str, Dict[str, Any]]:
        """Pure classification of one worker's current snapshot."""
        try:
            alive = worker.is_alive
        except BaseException:
            alive = False
        if not alive:
            return STATE_DEAD, {
                "detail": "process dead "
                          f"(exitcode={getattr(worker, 'exitcode', None)})"}
        hb = getattr(worker, "heartbeat", None)
        snap = None
        if hb is not None:
            try:
                snap = hb.snapshot()
            except BaseException:
                snap = None
        if snap is None:
            # no channel (heartbeats disabled / unreachable agent probe):
            # liveness-only supervision, never a false-positive kill
            return STATE_OK, {}
        info = dict(snap)
        busy = snap.get("busy_s")
        started = snap.get("started", True)
        stale_after = (self.wedge_timeout_s if started
                       else self.boot_grace_s)
        if snap["beat_age_s"] > stale_after:
            what = "wedge timeout" if started else "boot grace"
            info["detail"] = (f"heartbeat stale {snap['beat_age_s']:.2f}s "
                              f"> {what} {stale_after:.2f}s")
            return STATE_WEDGED, info
        if (busy is not None and self.dispatch_deadline_s is not None
                and busy > self.dispatch_deadline_s):
            info["detail"] = (f"dispatch busy {busy:.2f}s > deadline "
                              f"{self.dispatch_deadline_s:.2f}s")
            return STATE_WEDGED, info
        if busy is not None and busy > self.slow_after_s:
            info["detail"] = (f"dispatch busy {busy:.2f}s "
                              f"(straggler past {self.slow_after_s:.2f}s)")
            return STATE_SLOW, info
        return STATE_OK, info

    def _diagnosis(self, worker: Any,
                   info: Dict[str, Any]) -> Dict[str, Any]:
        diagnosis = {
            "error": "worker wedged",
            "rank": worker.rank,
            "state": STATE_WEDGED,
            "detail": info.get("detail", "stopped making progress"),
            "beat_age_s": info.get("beat_age_s"),
            "busy_s": info.get("busy_s"),
            "dispatches": info.get("dispatches"),
            "wedge_timeout_s": self.wedge_timeout_s,
            "dispatch_deadline_s": self.dispatch_deadline_s,
        }
        # flight-recorder tail (telemetry/recorder.py): the wedged rank's
        # last events, read from its spill file — a frozen process can't
        # answer, the file can.  Embedded here so the typed WorkerWedged
        # alone is a usable postmortem, across BOTH rebuild paths (local
        # pipe and agent relay both re-derive diagnosis from the
        # message's JSON marker, runtime/wire.py).
        try:
            tail_fn = getattr(worker, "telemetry_tail", None)
            snap = tail_fn() if tail_fn is not None else None
            if snap:
                diagnosis["events"] = telemetry.tail_events(snap)
                if snap.get("trace_id"):
                    diagnosis["trace_id"] = snap["trace_id"]
        except BaseException:
            pass  # a postmortem garnish must never block the reap
        return diagnosis

    # -- polling ------------------------------------------------------- #
    def poll_once(self) -> Dict[int, str]:
        """One classification sweep; reaps newly wedged ranks when
        ``auto_reap``.  Returns {rank: state}."""
        # re-list the source pool: ranks added/dropped since the last
        # sweep enter/leave supervision here (see __init__)
        self.workers = list(getattr(self._source, "workers",
                                    self._source))
        new_states: Dict[int, str] = {}
        to_reap: List[Tuple[Any, Dict[str, Any]]] = []
        for w in self.workers:
            state, info = self.classify(w)
            if state == STATE_WEDGED and self.auto_reap \
                    and self._states.get(w.rank) != STATE_WEDGED:
                to_reap.append((w, info))
            new_states[w.rank] = state
        for w, info in to_reap:
            diagnosis = self._diagnosis(w, info)
            self.reaped.append(diagnosis)
            log.error("watchdog reaping wedged worker %d: %s", w.rank,
                      json.dumps(diagnosis, sort_keys=True, default=str))
            try:
                w.reap(diagnosis)
            except BaseException as e:
                log.warning("reap of worker %d failed: %s", w.rank, e)
        transitions: List[Tuple[int, Optional[str], str]] = []
        with self._cond:
            for rank, state in new_states.items():
                old = self._states.get(rank)
                if old != state:
                    transitions.append((rank, old, state))
                    if self.on_transition is not None:
                        try:
                            self.on_transition(rank, old, state)
                        except BaseException:
                            pass
            self._states = new_states
            self._cond.notify_all()
        # emitted OUTSIDE the condition lock: a recorder spill is disk
        # I/O, and wait_for_state/poll consumers must not stall on it
        for rank, old, state in transitions:
            try:
                telemetry.emit("watchdog_transition", rank=rank,
                               prev=old, state=state)
            except BaseException:
                pass
        return dict(new_states)

    def states(self) -> Dict[int, str]:
        with self._cond:
            return dict(self._states)

    def report(self) -> Dict[str, Any]:
        """Machine-readable supervision summary (states + reap records)."""
        return {"states": self.states(), "reaped": list(self.reaped),
                "wedge_timeout_s": self.wedge_timeout_s,
                "dispatch_deadline_s": self.dispatch_deadline_s}

    def wait_for(self, predicate: Callable[[Dict[int, str]], bool],
                 timeout: float) -> bool:
        """Block until ``predicate(states)`` holds (condition-signaled per
        poll -- the event-based alternative to sleep-poll test loops)."""
        deadline = time.monotonic() + timeout
        with self._cond:
            while not predicate(dict(self._states)):
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._cond.wait(remaining)
            return True

    def wait_for_state(self, rank: int, state: str, timeout: float) -> bool:
        return self.wait_for(lambda s: s.get(rank) == state, timeout)

    # -- lifecycle ----------------------------------------------------- #
    def start(self) -> "Watchdog":
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="rla-tpu-watchdog")
        self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.poll_s):
            try:
                self.poll_once()
            except BaseException as e:
                # supervision must never take the driver down
                log.warning("watchdog poll failed: %s", e)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)

    def __enter__(self) -> "Watchdog":
        return self

    def __exit__(self, *exc) -> None:
        self.stop()


def stall_record(exc: BaseException, stage: str) -> Dict[str, Any]:
    """A machine-readable stall diagnosis mirroring bench.py's
    death-record shape: flat JSON-able dict with ``error``/``detail``
    plus ``stall_*`` context keys from the wedge diagnosis.  A graceful
    preemption drain (runtime/preemption.py) is classified distinctly --
    it is a resume point, not a stall, and dashboards keying on
    ``error`` must not count it against reliability."""
    from .preemption import is_preemption
    if is_preemption(exc):
        error = "preempted"
    elif isinstance(exc, WorkerWedged):
        error = "worker wedged"
    elif isinstance(exc, TimeoutError):
        error = "attempt deadline exceeded"
    else:
        error = "worker died"
    record: Dict[str, Any] = {
        "metric": "worker_stall", "value": 0, "unit": "alive",
        "error": error, "stage": stage,
        "detail": str(exc)[-500:],
        "rank": getattr(exc, "rank", None),
    }
    for k, v in getattr(exc, "diagnosis", {}).items():
        record[f"stall_{k}"] = v
    return record
