"""Numeric anomaly guardian: in-step detection, blame, rewind-and-skip.

The fault-tolerance stack up to here handles *process-level* failure —
hangs (watchdog), preemptions, wedged replicas, mesh resizes, lost
pipeline stages.  A NaN loss, an exploding grad norm, or a silently
corrupted activation is invisible to all of it: the run keeps training
garbage until a human reads a loss curve.  This module closes that gap
in three layers:

- **Detection (traced, zero extra syncs)**: every train step carries a
  tiny guard vector in ``TrainState.guard_ema`` (f32[``GUARD_WIDTH``])
  updated by ``update()`` INSIDE the jitted step: finiteness of loss and
  global grad norm, grad-norm spike vs. a traced EMA envelope,
  update/param-norm ratio, and — where a per-replica gradient stack is
  available (compressed DP/FSDP) — a per-rank badness vector whose
  divergence names a suspect rank.  The packed flags piggyback on the
  existing metrics readback (``metrics["guard"]``), so guarded steps add
  no device round-trips and no retraces; ``guard=None`` keeps the step
  functions bit-identical to the unguarded build.
- **Blame (host, cold path)**: on trip, ``Guardian.check`` classifies
  before anyone acts.  Per-rank flag divergence → nondeterministic
  hardware fault (suspected SDC) with the rank named; non-finite values
  in the recorded host batch, or a reproducing plain replay (compression
  and int8 disabled) → data-poisoned; a trip that only reproduces with
  the compressed exchange enabled → exchange-induced; a trip that does
  not reproduce at all → suspected SDC.  The verdict ships as a typed
  ``NumericAnomaly`` (wire-registered like ``WorkerWedged``) carrying
  the offending step, the batch index range, and the blame taxonomy.
- **Recovery (ElasticRunner)**: rewind to the newest *verified*
  checkpoint (``latest_checkpoint``'s digest walk — a truncated newest
  file is skipped, never restored), quarantine the blamed data window
  through a skip-list applied to the deterministic loader order (so the
  skip is identical across ranks and across restarts), bounded by a
  ``max_rewinds`` budget separate from the failure budget; the same step
  tripping twice post-quarantine is terminal, and an SDC-suspect verdict
  demotes the named rank via the existing elastic shrink path instead.
"""

from __future__ import annotations

import dataclasses
import json
import os
import tempfile
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Set, Tuple

from ..analysis import knobs
from ..telemetry import recorder as telemetry
from ..utils.logging import log

# --------------------------------------------------------------------- #
# Guard vector layout                                                    #
# --------------------------------------------------------------------- #
# One f32 vector rides in TrainState.guard_ema.  Scalars, not a struct:
# the vector crosses checkpoint serialization, sharding templates, and
# the scan carry unchanged, and a single replicated [GUARD_WIDTH] leaf is
# the cheapest possible addition to the donated state pytree.
I_EMA = 0           # EMA of the global grad norm (healthy steps only)
I_COUNT = 1         # healthy steps folded into the EMA (warmup gate)
I_TRIPPED = 2       # sticky 0/1: any flag fired since the last reset
I_TRIP_STEP = 3     # 0-based TrainState.step of the FIRST trip (-1)
I_FLAG_LOSS = 4     # first-trip flag: loss non-finite
I_FLAG_GRAD = 5     # first-trip flag: global grad norm non-finite
I_FLAG_SPIKE = 6    # first-trip flag: grad norm > spike_factor * EMA
I_FLAG_UPDATE = 7   # first-trip flag: update/param norm ratio too large
I_SUSPECT = 8       # first-trip suspect replica index, -1 = no single rank
I_NBAD = 9          # first-trip count of bad replicas (0 = no rank info)
GUARD_WIDTH = 10

# metrics["guard"] = concat(guard_ema, [grad_norm, update_ratio]) — the
# two live diagnostics ride along for the postmortem without being part
# of the carried state
METRIC_WIDTH = GUARD_WIDTH + 2

BLAME_DATA = "data"          # poisoned batch: quarantine the window
BLAME_EXCHANGE = "exchange"  # compressed-exchange overflow: rewind only
BLAME_SDC = "sdc"            # nondeterministic / rank-divergent: demote
BLAME_UNKNOWN = "unknown"


@dataclasses.dataclass(frozen=True)
class GuardConfig:
    """Guardian tuning; ``Trainer(guard="auto")`` builds it from the
    guard knob family (``from_env`` below names each one) and disables
    the guardian entirely when ``RLA_TPU_GUARD`` is false."""

    spike_factor: float = 10.0     # trip when gnorm > factor * EMA
    spike_floor: float = 1e-3      # gnorm below this never counts as a
    #   spike: a fully converged model's EMA decays toward 0 and the
    #   relative check would otherwise trip on numerically-zero jitter
    ema_decay: float = 0.9         # grad-norm EMA decay (healthy steps)
    warmup_steps: int = 20         # healthy steps before spike/update arm
    update_ratio_max: float = 0.5  # trip when |Δparams|/|params| exceeds
    max_rewinds: int = 2           # rewind budget (ElasticRunner default)

    @classmethod
    def from_env(cls) -> Optional["GuardConfig"]:
        if not knobs.get_bool("RLA_TPU_GUARD", True):
            return None
        return cls(
            spike_factor=knobs.get_float("RLA_TPU_GUARD_SPIKE_FACTOR", 10.0),
            spike_floor=knobs.get_float("RLA_TPU_GUARD_SPIKE_FLOOR", 1e-3),
            ema_decay=knobs.get_float("RLA_TPU_GUARD_EMA_DECAY", 0.9),
            warmup_steps=knobs.get_int("RLA_TPU_GUARD_WARMUP_STEPS", 20),
            update_ratio_max=knobs.get_float(
                "RLA_TPU_GUARD_UPDATE_RATIO_MAX", 0.5),
            max_rewinds=knobs.get_int("RLA_TPU_GUARD_MAX_REWINDS", 2),
        )


# --------------------------------------------------------------------- #
# Traced half: runs INSIDE the jitted train step                         #
# --------------------------------------------------------------------- #
def fresh_state():
    """A new guard vector (host-buildable: used in state templates)."""
    import numpy as np
    g = np.zeros((GUARD_WIDTH,), np.float32)
    g[I_TRIP_STEP] = -1.0
    g[I_SUSPECT] = -1.0
    return g


def per_replica_bad(stacked_local: Any, spike_factor: float):
    """Per-replica badness from a replica-stacked local-gradient tree
    ([n_replicas, ...] leaves): non-finite local grads, or a local norm
    spiking past ``spike_factor`` times the replica median.  Returns
    f32[n_replicas]; divergence (some-but-not-all bad) is the SDC
    signature — a poisoned *global* batch trips every replica at once."""
    import jax
    import jax.numpy as jnp

    sq = None
    finite = None
    for leaf in jax.tree.leaves(stacked_local):
        flat = leaf.reshape((leaf.shape[0], -1)).astype(jnp.float32)
        row_sq = jnp.sum(jnp.where(jnp.isfinite(flat), flat * flat, 0.0),
                         axis=1)
        row_fin = jnp.all(jnp.isfinite(flat), axis=1)
        sq = row_sq if sq is None else sq + row_sq
        finite = row_fin if finite is None else finite & row_fin
    if sq is None:
        return None
    norms = jnp.sqrt(sq)
    med = jnp.median(norms)
    bad = (~finite) | (norms > spike_factor * (med + 1e-12))
    return bad.astype(jnp.float32)


def update(cfg: GuardConfig, guard: Any, step: Any, loss: Any, gnorm: Any,
           ratio: Any, rank_bad: Any = None) -> Tuple[Any, Any]:
    """One traced guard-state transition.  Returns ``(new_guard,
    guard_metric)``: the carried f32[GUARD_WIDTH] vector and the
    f32[METRIC_WIDTH] row packed into ``metrics["guard"]``.  Pure
    element-wise math on scalars — no collectives, no host callbacks —
    so it fuses into the step program and costs nothing observable."""
    import jax.numpy as jnp

    loss = jnp.asarray(loss, jnp.float32)
    gnorm = jnp.asarray(gnorm, jnp.float32)
    ratio = jnp.asarray(ratio, jnp.float32)
    ema = guard[I_EMA]
    count = guard[I_COUNT]
    tripped = guard[I_TRIPPED]

    f_loss = ~jnp.isfinite(loss)
    f_grad = ~jnp.isfinite(gnorm)
    warm = count >= cfg.warmup_steps
    f_spike = warm & jnp.isfinite(gnorm) & (gnorm > cfg.spike_floor) & (
        gnorm > cfg.spike_factor * (ema + 1e-12))
    f_update = warm & ((~jnp.isfinite(ratio)) |
                       (ratio > cfg.update_ratio_max))
    unhealthy = f_loss | f_grad | f_spike | f_update

    if rank_bad is not None:
        n_bad = jnp.sum(rank_bad)
        n = rank_bad.shape[0]
        lone = (n_bad > 0) & (n_bad < n)
        suspect = jnp.where(lone, jnp.argmax(rank_bad).astype(jnp.float32),
                            -1.0)
    else:
        n_bad = jnp.float32(0.0)
        suspect = jnp.float32(-1.0)

    healthy = ~unhealthy
    new_ema = jnp.where(healthy,
                        jnp.where(count > 0,
                                  cfg.ema_decay * ema +
                                  (1.0 - cfg.ema_decay) * gnorm,
                                  gnorm),
                        ema)
    new_count = count + healthy.astype(jnp.float32)
    # the FIRST trip freezes the postmortem fields; later steps keep the
    # sticky bit but never overwrite the evidence
    first = unhealthy & (tripped == 0.0)

    def _pin(new, old):
        return jnp.where(first, new, old)

    new_g = jnp.stack([
        new_ema,
        new_count,
        jnp.maximum(tripped, unhealthy.astype(jnp.float32)),
        _pin(jnp.asarray(step, jnp.float32), guard[I_TRIP_STEP]),
        _pin(f_loss.astype(jnp.float32), guard[I_FLAG_LOSS]),
        _pin(f_grad.astype(jnp.float32), guard[I_FLAG_GRAD]),
        _pin(f_spike.astype(jnp.float32), guard[I_FLAG_SPIKE]),
        _pin(f_update.astype(jnp.float32), guard[I_FLAG_UPDATE]),
        _pin(suspect, guard[I_SUSPECT]),
        _pin(jnp.asarray(n_bad, jnp.float32), guard[I_NBAD]),
    ])
    metric = jnp.concatenate([new_g, jnp.stack([gnorm, ratio])])
    return new_g, metric


# --------------------------------------------------------------------- #
# Typed anomaly (wire-registered)                                        #
# --------------------------------------------------------------------- #
class NumericAnomaly(RuntimeError):
    """A guarded step tripped (or a serve decode produced non-finite
    logits).  Carries the blame verdict so retry layers can branch:
    ``ElasticRunner`` rewinds on data/exchange blame without charging the
    failure budget, and demotes the suspect rank on SDC blame.  Crosses
    the worker pipe via the wire registry (``runtime/wire.py``), with the
    structured postmortem embedded in the message after ``_MARKER``."""

    _MARKER = "| anomaly="

    def __init__(self, message: str, step: Optional[int] = None,
                 blame: str = BLAME_UNKNOWN,
                 suspect_rank: Optional[int] = None,
                 epoch: Optional[int] = None,
                 batch_idx: Optional[int] = None,
                 stage: Optional[int] = None,
                 diagnosis: Optional[Dict[str, Any]] = None):
        super().__init__(message)
        self.step = step
        self.blame = blame
        self.suspect_rank = suspect_rank
        self.epoch = epoch
        self.batch_idx = batch_idx
        self.stage = stage
        self.diagnosis = dict(diagnosis or {})

    @classmethod
    def for_trip(cls, step: int, blame: str,
                 flags: Optional[Dict[str, Any]] = None,
                 suspect_rank: Optional[int] = None,
                 epoch: Optional[int] = None,
                 batch_idx: Optional[int] = None,
                 stage: Optional[int] = None,
                 detail: str = "") -> "NumericAnomaly":
        diagnosis: Dict[str, Any] = {
            "step": step, "blame": blame, "flags": dict(flags or {}),
        }
        if suspect_rank is not None:
            diagnosis["suspect_rank"] = suspect_rank
        if epoch is not None:
            diagnosis["epoch"] = epoch
        if batch_idx is not None:
            diagnosis["batch_idx"] = batch_idx
        if stage is not None:
            diagnosis["stage"] = stage
        where = f"stage {stage} " if stage is not None else ""
        msg = (f"numeric anomaly at {where}step {step} (blame={blame})"
               f"{': ' + detail if detail else ''} "
               f"{cls._MARKER}"
               f"{json.dumps(diagnosis, sort_keys=True, default=str)}")
        return cls(msg, step=step, blame=blame, suspect_rank=suspect_rank,
                   epoch=epoch, batch_idx=batch_idx, stage=stage,
                   diagnosis=diagnosis)

    @classmethod
    def from_message(cls, message: str) -> "NumericAnomaly":
        """Rebuild from a message that crossed a wire as (name, str, tb),
        recovering the embedded postmortem (tolerant of truncation)."""
        diagnosis: Dict[str, Any] = {}
        i = message.find(cls._MARKER)
        if i >= 0:
            try:
                diagnosis = json.loads(message[i + len(cls._MARKER):])
            except ValueError:
                pass
        return cls(message,
                   step=diagnosis.get("step"),
                   blame=diagnosis.get("blame", BLAME_UNKNOWN),
                   suspect_rank=diagnosis.get("suspect_rank"),
                   epoch=diagnosis.get("epoch"),
                   batch_idx=diagnosis.get("batch_idx"),
                   stage=diagnosis.get("stage"),
                   diagnosis=diagnosis)


# --------------------------------------------------------------------- #
# Quarantine ledger (atomic JSON under <root>/guardian/)                 #
# --------------------------------------------------------------------- #
def _quarantine_path(root_dir: str) -> str:
    return os.path.join(root_dir, "guardian", "quarantine.json")


def load_quarantine(root_dir: str) -> Dict[str, Any]:
    path = _quarantine_path(root_dir)
    try:
        with open(path, "r", encoding="utf-8") as fh:
            doc = json.load(fh)
        if isinstance(doc, dict) and isinstance(doc.get("entries"), list):
            return doc
    except (OSError, ValueError):
        pass
    return {"entries": [], "anchor": None}


def _write_quarantine(root_dir: str, doc: Dict[str, Any]) -> None:
    path = _quarantine_path(root_dir)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path),
                               prefix=".quarantine-", suffix=".tmp")
    try:
        with os.fdopen(fd, "w", encoding="utf-8") as fh:
            json.dump(doc, fh, sort_keys=True, indent=1)
        os.replace(tmp, path)  # atomic: a crashed writer never tears it
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def add_quarantine(root_dir: str, epoch: int, batch_idx: int, step: int,
                   anchor: Optional[str] = None) -> Dict[str, Any]:
    """Append one blamed (epoch, batch_idx) window and pin the rewind
    anchor (the checkpoint pruning must keep alive while the quarantine
    is active)."""
    doc = load_quarantine(root_dir)
    entry = {"epoch": int(epoch), "batch_idx": int(batch_idx),
             "step": int(step)}
    if entry not in doc["entries"]:
        doc["entries"].append(entry)
    if anchor:
        doc["anchor"] = anchor
    _write_quarantine(root_dir, doc)
    return doc


def release_anchor(root_dir: str) -> None:
    """Drop the prune protection once a fit ran CLEAN past the quarantined
    window — newer verified checkpoints now cover it.  The skip entries
    stay (the data is still bad); only the pin goes."""
    doc = load_quarantine(root_dir)
    if doc.get("anchor"):
        doc["anchor"] = None
        _write_quarantine(root_dir, doc)


def skip_set(root_dir: str, epoch: int) -> Set[int]:
    """Batch indices quarantined for ``epoch`` — consulted by the loader
    wrap; a pure function of the JSON ledger, so every rank and every
    restart computes the identical set."""
    return {int(e["batch_idx"]) for e in load_quarantine(root_dir)["entries"]
            if int(e["epoch"]) == int(epoch)}


def protected_paths(dirpath: str) -> List[str]:
    """Checkpoint paths pruning must keep: the active rewind anchor, if
    a quarantine ledger lives at ``dirpath`` or one directory up (the
    checkpoint dir is usually ``<root>/`` itself or ``<root>/checkpoints``).
    Called by ``ModelCheckpoint._prune``, which has no trainer handle."""
    out: List[str] = []
    for root in (dirpath, os.path.dirname(os.path.abspath(dirpath))):
        anchor = load_quarantine(root).get("anchor")
        if anchor:
            out.append(anchor)
    return out


# --------------------------------------------------------------------- #
# Host half: trip handling, blame, quarantine                            #
# --------------------------------------------------------------------- #
class Guardian:
    """Driver-side companion to the traced guard vector.  Remembers the
    last few dispatched batches (``note_step``), and on a tripped guard
    readback classifies blame, writes the quarantine ledger, emits the
    flight-recorder events, and raises the typed ``NumericAnomaly``."""

    RING = 8  # batches of lookback; trips surface within one readback

    def __init__(self, cfg: GuardConfig, root_dir: str):
        self.cfg = cfg
        self.root_dir = root_dir
        self._ring: deque = deque(maxlen=self.RING)

    # -- bookkeeping ---------------------------------------------------- #
    def note_step(self, step: int, epoch: int, batch_idx: int,
                  kind: str, payload: Any) -> None:
        """Record what the step ABOUT to run at ``step`` consumes.  Host
        refs only — no device work, no copies."""
        self._ring.append((int(step), int(epoch), int(batch_idx), kind,
                           payload))

    def _lookup(self, step: int):
        for rec in reversed(self._ring):
            if rec[0] == step:
                return rec
        return None

    def skip_set(self, epoch: int) -> Set[int]:
        return skip_set(self.root_dir, epoch)

    def has_quarantine(self) -> bool:
        return bool(load_quarantine(self.root_dir)["entries"])

    def release_anchor(self) -> None:
        release_anchor(self.root_dir)

    # -- blame ---------------------------------------------------------- #
    @staticmethod
    def _batch_nonfinite(payload: Any) -> bool:
        import numpy as np
        try:
            for leaf in _tree_leaves(payload):
                arr = np.asarray(leaf)
                if arr.dtype.kind == "f" and not np.all(np.isfinite(arr)):
                    return True
        except Exception:
            return False
        return False

    def classify(self, flags: Dict[str, Any], suspect_rank: int,
                 n_bad: int, entry: Optional[Tuple],
                 replay: Optional[Callable[[Any], Dict[str, bool]]],
                 compression_active: bool) -> Tuple[str, Optional[int]]:
        """The blame cascade.  Cheap evidence first, the replay (a fresh
        compile on the cold path) last:

        1. rank divergence (some-but-not-all replicas bad) → SDC, named;
        2. non-finite floats in the recorded host batch → data;
        3. plain replay (compression/int8 off) reproduces → data;
        4. reproducible only through the compressed exchange → exchange;
        5. nothing reproduces → nondeterministic, suspected SDC.
        """
        if n_bad > 0 and suspect_rank >= 0:
            return BLAME_SDC, suspect_rank
        payload = entry[4] if entry is not None else None
        kind = entry[3] if entry is not None else None
        if kind == "host" and payload is not None and \
                self._batch_nonfinite(payload):
            return BLAME_DATA, None
        if replay is not None and payload is not None and kind == "host":
            try:
                plain = replay(payload)
            except Exception as e:  # replay must never mask the trip
                log(f"guardian: blame replay failed ({e!r})")
                plain = None
            if plain is not None:
                if plain.get("loss_nonfinite") or plain.get(
                        "grad_nonfinite"):
                    return BLAME_DATA, None
                if compression_active and (flags.get("grad_nonfinite") or
                                           flags.get("spike")):
                    return BLAME_EXCHANGE, None
                return BLAME_SDC, None
        return BLAME_UNKNOWN, None

    # -- trip ----------------------------------------------------------- #
    def check(self, guard_host: Any, *,
              replay: Optional[Callable[[Any], Dict[str, bool]]] = None,
              compression_active: bool = False) -> None:
        """Inspect one host guard row (``metrics["guard"]`` after the
        readback that was happening anyway).  No-op while healthy; on a
        sticky trip: blame → quarantine (data blame) → telemetry →
        raise ``NumericAnomaly``."""
        if guard_host is None:
            return
        import numpy as np
        g = np.asarray(guard_host, np.float32).reshape(-1)
        if g.shape[0] < GUARD_WIDTH or g[I_TRIPPED] == 0.0:
            return
        step = int(g[I_TRIP_STEP])
        flags = {
            "loss_nonfinite": bool(g[I_FLAG_LOSS]),
            "grad_nonfinite": bool(g[I_FLAG_GRAD]),
            "spike": bool(g[I_FLAG_SPIKE]),
            "update_ratio": bool(g[I_FLAG_UPDATE]),
        }
        if g.shape[0] >= METRIC_WIDTH:
            flags["grad_norm"] = float(g[GUARD_WIDTH])
            flags["update_ratio_value"] = float(g[GUARD_WIDTH + 1])
        suspect = int(g[I_SUSPECT])
        n_bad = int(g[I_NBAD])
        entry = self._lookup(step)
        epoch = entry[1] if entry is not None else None
        batch_idx = entry[2] if entry is not None else None
        blame, named = self.classify(flags, suspect, n_bad, entry, replay,
                                     compression_active)
        telemetry.emit("anomaly_trip", step=step, blame=blame,
                       suspect_rank=named, epoch=epoch,
                       batch_idx=batch_idx, **{
                           k: v for k, v in flags.items()
                           if isinstance(v, bool)})
        if blame == BLAME_DATA and epoch is not None and \
                batch_idx is not None:
            anchor = self._rewind_anchor()
            add_quarantine(self.root_dir, epoch, batch_idx, step,
                           anchor=anchor)
            telemetry.emit("quarantine", epoch=epoch, batch_idx=batch_idx,
                           step=step, anchor=anchor)
        raise NumericAnomaly.for_trip(
            step, blame, flags=flags, suspect_rank=named, epoch=epoch,
            batch_idx=batch_idx,
            detail=", ".join(k for k, v in flags.items()
                             if isinstance(v, bool) and v) or "tripped")

    def _rewind_anchor(self) -> Optional[str]:
        """Newest VERIFIED checkpoint at trip time — the digest walk in
        ``latest_checkpoint`` skips a truncated newest file, so the
        anchor is always restorable."""
        from ..utils import checkpoint as ckpt_lib
        try:
            return ckpt_lib.latest_checkpoint(self.root_dir)
        except Exception:
            return None


def _tree_leaves(payload: Any):
    """Flatten a host batch without importing jax on the cold path when
    numpy suffices (dicts/tuples/lists of arrays)."""
    if isinstance(payload, dict):
        for v in payload.values():
            yield from _tree_leaves(v)
    elif isinstance(payload, (list, tuple)):
        for v in payload:
            yield from _tree_leaves(v)
    elif payload is not None and not isinstance(payload, (str, bytes)):
        yield payload
