"""Per-worker session: rank + driver-queue singleton.

Direct capability analog of the reference's session module
(reference: ray_lightning/session.py:6-63): a process-global singleton giving
worker-side code (callbacks) its global rank and a channel to ship callables
to the driver -- the "callable trampoline" that makes Tune reporting work
from inside workers (reference: ray_lightning/tune.py:97-101 ->
session.py:61-63).

In the TPU framework the "worker" is a per-host process (SPMD: often just
one); the session is initialized by the trainer/runtime and by tune trials.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Optional


class TpuSession:
    def __init__(self, rank: int, queue: Optional[Any] = None):
        self._rank = rank
        self._queue = queue

    @property
    def rank(self) -> int:
        return self._rank

    def put_queue(self, item: Callable[[], Any]) -> None:
        if self._queue is None:
            raise ValueError(
                "this session has no queue attached -- it was not launched "
                "under a driver that drains one (e.g. tune.run)")
        self._queue.put((self._rank, item))


_session: Optional[TpuSession] = None
# thread-local overlay: concurrent tune trials each bind their own session
# on their trial + trainable threads without touching the process global
_tls = threading.local()


def _current() -> Optional[TpuSession]:
    return getattr(_tls, "session", None) or _session


def init_session(rank: int, queue: Optional[Any] = None) -> None:
    install_session(TpuSession(rank, queue))


def install_session(session: TpuSession) -> None:
    """Set an existing session object as the process global (so callers
    that also thread-bind it keep ONE session object, not two twins)."""
    global _session
    if _session is not None:
        raise ValueError("a session already exists in this process; "
                         "call shutdown_session() first")
    _session = session


def bind_session_to_thread(session: Optional[TpuSession]) -> None:
    """Attach (or clear, with None) a session for the CURRENT thread only;
    shadows the process-global one.  Used by concurrent tune trials."""
    _tls.session = session


def get_session() -> TpuSession:
    s = _current()
    if s is None:
        raise ValueError("no session initialized in this process")
    return s


def shutdown_session() -> None:
    global _session
    _session = None


def session_exists() -> bool:
    return _current() is not None


def get_actor_rank() -> int:
    """Rank of this worker process (reference: session.py:56-58)."""
    return get_session().rank


def put_queue(item: Callable[[], Any]) -> None:
    """Ship a zero-arg callable to the driver process for execution there
    (reference: session.py:61-63)."""
    get_session().put_queue(item)
