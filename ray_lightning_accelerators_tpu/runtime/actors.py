"""Actor runtime: persistent worker processes with remote-execute futures.

Capability analog of the reference's Ray-actor control plane
(reference: ray_lightning/ray_ddp.py -- `RayExecutor` actor :17-31, actor
creation :92-97,105, env propagation :21-23,154-159, init_hook :106-107,
fan-out :178-182, teardown/kill :109-121, node-IP census :25-27,132-143).

Without Ray in the image, this is a from-scratch actor system on
``multiprocessing`` spawn workers:

- each **Worker** is a long-lived subprocess running a request loop; work
  arrives as cloudpickled (fn, args, kwargs) so closures/lambdas ship like
  they do through Ray;
- ``execute()`` returns a ``concurrent.futures.Future`` resolved by a
  driver-side collector thread -- the ObjectRef analog that
  ``runtime.queue.process_results`` polls;
- env vars can be set pre-fork (TPU topology variables such as
  ``TPU_PROCESS_BOUNDS`` / coordinator addresses must exist before the
  child's XLA backend initializes -- the TPU twist on the reference's
  `set_env_var` RPC);
- ``kill()``/``shutdown()`` terminate workers (`no_restart` semantics,
  reference: ray_ddp.py:119).

The TPU multi-host bootstrap built on top lives in `runtime/bootstrap.py`.

Note: scripts creating pools must guard pool construction with
``if __name__ == "__main__":`` -- spawn children re-import the main module
(standard multiprocessing semantics; Ray's driver/worker split hid this).
"""

from __future__ import annotations

import multiprocessing as mp
import os
import socket
import threading
import traceback
from concurrent.futures import Future
from typing import Any, Callable, Dict, List, Optional, Sequence

import cloudpickle

from ..analysis import knobs
from ..utils.logging import log
from .watchdog import (HeartbeatChannel, WorkerBeat, WorkerWedged,
                       heartbeat_interval_s)

_SENTINEL = b"__shutdown__"

# worker-process side: this process's beat thread, installed by
# _worker_main so in-process layers (the replica-level chaos seam in
# serve/replicas.py) can freeze it without plumbing the object through
# every dispatch signature
_CURRENT_BEAT: Optional["WorkerBeat"] = None


def freeze_current_heartbeat() -> None:
    """Freeze THIS worker process's heartbeat thread (no-op on the
    driver / when heartbeats are disabled).  A chaos ``hang`` injected
    above the dispatch loop — e.g. inside a replica's serve-chunk path —
    calls this so the hang reads as a frozen process to the watchdog,
    not as a long-running dispatch."""
    if _CURRENT_BEAT is not None:
        _CURRENT_BEAT.freeze()


def _worker_main(conn, env: Dict[str, str], rank: int = 0,
                 heartbeat: Optional[HeartbeatChannel] = None,
                 heartbeat_s: float = 0.0) -> None:
    os.environ.update(env)
    # flight recorder (telemetry/recorder.py): rank-keyed so the spill
    # file and every event carry this worker's identity; the trace id /
    # telemetry dir come from the per-worker env overlay.  A failure
    # here must not take the worker down — telemetry observes, never
    # gates.
    try:
        from ..telemetry import recorder as telemetry
        telemetry.configure(rank=rank, env=env)
    except Exception:
        telemetry = None
    # live telemetry plane (telemetry/live.py): with RLA_TPU_METRICS_PORT
    # in the overlay this rank serves /metrics + /statusz + /healthz on
    # an ephemeral loopback port published via its portfile — /healthz
    # classifies from THIS rank's own heartbeat channel, so a hung
    # dispatch flips it to wedged before the driver watchdog reaps.
    # Observes, never gates: a bind failure leaves the worker running.
    try:
        from ..telemetry import live as live_telemetry
        live_telemetry.maybe_start_from_env(
            rank=rank, env=env,
            beat_snapshot_fn=(heartbeat.snapshot
                              if heartbeat is not None else None))
    except Exception:
        pass
    # opt-in SPMD collective sanitizer (testing/spmd_sanitizer.py):
    # when RLA_TPU_SPMD_SANITIZER is in the overlay, every collective
    # this worker traces is recorded + spilled rank-keyed so the driver
    # can diff sequences across ranks.  Observes, never gates.
    try:
        from ..testing.spmd_sanitizer import maybe_install_from_env
        maybe_install_from_env(rank=rank, env=env)
    except Exception:
        pass
    try:
        # the package logger was configured at import, BEFORE the
        # per-worker overlay landed in os.environ — re-read
        # RLA_TPU_LOG_JSON / RLA_TPU_LOG_LEVEL so overlays are honored
        from ..utils.logging import configure_logging
        configure_logging()
    except Exception:
        pass
    # a device plugin loaded from sitecustomize may have forced
    # jax_platforms via CONFIG during interpreter startup; the
    # environment's explicit choice must win (per-worker env first, then
    # the env inherited from the spawning process), or a CPU-pinned
    # trial/worker hangs trying to claim the TPU
    platforms = env.get("JAX_PLATFORMS") or os.environ.get("JAX_PLATFORMS")
    if platforms:
        try:
            import jax
            jax.config.update("jax_platforms", platforms)
        except Exception:
            pass
    beat = None
    if heartbeat is not None and heartbeat_s > 0:
        beat = WorkerBeat(heartbeat, heartbeat_s)
        beat.start()
        global _CURRENT_BEAT
        _CURRENT_BEAT = beat
    # preemption notice handler (runtime/preemption.py), installed only
    # when a grace budget is configured: SIGTERM then flips a drain flag
    # the dispatched body polls (busy) or exits immediately (idle), so
    # spot notices drain gracefully while pool teardown stays fast
    notice = None
    try:
        from .preemption import install_from_env
        notice = install_from_env(worker_mode=True)
    except Exception:
        pass
    # deterministic fault injection (testing/chaos.py), imported ONLY when
    # requested -- the test harness must not be a production dependency.
    # A broken spec surfaces on the first dispatch's future, not by
    # killing the worker silently.
    chaos = chaos_error = None
    if knobs.get_raw("RLA_TPU_CHAOS"):
        try:
            from ..testing.chaos import ChaosInjector
            chaos = ChaosInjector.from_env(
                rank, freeze_heartbeat=beat.freeze if beat else None)
        except BaseException as e:
            chaos_error = e
    n_dispatch = 0
    while True:
        try:
            blob = conn.recv_bytes()
        except EOFError:
            return
        if blob == _SENTINEL:
            conn.close()
            return
        n_dispatch += 1
        if telemetry is not None:
            # emitted BEFORE chaos/user code runs, and the recorder's
            # first emit spills eagerly: a rank that hangs or dies inside
            # this dispatch leaves "it entered dispatch N" on disk — the
            # tail the watchdog embeds into WorkerWedged.diagnosis
            telemetry.emit("dispatch_begin", n=n_dispatch)
        try:
            if chaos_error is not None:
                raise chaos_error
            fn, args, kwargs = cloudpickle.loads(blob)
            # Ray-style call-site deref: top-level ObjectRef args resolve
            # from the shared-memory store (reference: ray.put'd trainer_ref
            # arriving deserialized at ray_ddp.py:179,201)
            from .object_store import resolve
            args = tuple(resolve(a) for a in args)
            kwargs = {k: resolve(v) for k, v in kwargs.items()}
            # busy marker brackets the USER work only: deserialization
            # above imports the fn's module graph, and counting that
            # cold-start cost against a dispatch deadline would wedge
            # every freshly restarted (healthy) worker on its first
            # dispatch -- retries could then never converge.  A hung
            # loads is still bounded by the driver-side deadline
            # backstops (queue.process_results / world.run).
            if beat is not None:
                beat.begin_dispatch()
            if notice is not None:
                # busy bracket: a SIGTERM landing mid-dispatch drains at
                # the body's next boundary instead of killing the process
                notice.busy = True
            if chaos is not None:
                chaos.on_dispatch()
            result = fn(*args, **kwargs)
            payload = ("ok", cloudpickle.dumps(result))
        except BaseException as e:  # ship the traceback home
            payload = ("err", cloudpickle.dumps(
                (type(e).__name__, str(e), traceback.format_exc())))
        if notice is not None:
            notice.busy = False
        if beat is not None:
            beat.end_dispatch()
        if telemetry is not None:
            telemetry.emit("dispatch_end", n=n_dispatch,
                           ok=payload[0] == "ok")
        conn.send_bytes(cloudpickle.dumps(payload))


class RemoteError(RuntimeError):
    """A worker-side exception, carrying the remote traceback."""

    def __init__(self, name: str, message: str, remote_traceback: str):
        super().__init__(f"{name}: {message}\n--- remote traceback ---\n"
                         f"{remote_traceback}")
        self.remote_traceback = remote_traceback


class Worker:
    """One persistent subprocess executing shipped callables in order."""

    def __init__(self, rank: int, env: Optional[Dict[str, str]] = None,
                 ctx: Optional[Any] = None,
                 heartbeat_s: Optional[float] = None):
        self.rank = rank
        self._env = dict(env or {})  # kept for restart()
        self._ctx = ctx or mp.get_context("spawn")
        # with a preemption grace budget configured the worker installs a
        # SIGTERM *notice* handler (runtime/preemption.py) -- SIGTERM no
        # longer means "die", it means "drain".  Driver-initiated
        # kill/restart must therefore go straight to SIGKILL: a swallowed
        # terminate() would cost the full join timeout per worker AND
        # write a bogus preemption flag into the shared run dir
        from .preemption import PREEMPT_GRACE_ENV
        self._sigterm_is_notice = bool(
            knobs.get_raw(PREEMPT_GRACE_ENV, env=self._env))
        # liveness channel interval: explicit arg > per-worker env >
        # process env > default; <= 0 disables the channel entirely
        self._heartbeat_s = (heartbeat_s if heartbeat_s is not None
                             else heartbeat_interval_s(self._env))
        # Two locks: _state_lock guards _pending (held only for list ops, so
        # the collector can always drain the pipe even while a sender is
        # blocked on a full pipe buffer -- holding one lock across a blocking
        # send_bytes can three-way-deadlock driver sender / collector /
        # worker); _send_lock serializes senders so _pending order matches
        # wire order.
        self._state_lock = threading.Lock()
        self._send_lock = threading.Lock()
        self._spawn()

    def _spawn(self) -> None:
        self._conn, child_conn = self._ctx.Pipe()
        # fresh heartbeat channel per generation: a restarted worker starts
        # with a clean beat (watchdog state resets with the process)
        self.heartbeat = (HeartbeatChannel(self._ctx)
                          if self._heartbeat_s > 0 else None)
        self._proc = self._ctx.Process(
            target=_worker_main,
            args=(child_conn, self._env, self.rank, self.heartbeat,
                  self._heartbeat_s),
            daemon=True, name=f"rla-tpu-worker-{self.rank}")
        self._proc.start()
        child_conn.close()
        self._pending: List[Future] = []
        # per-generation metadata shared with THIS generation's collector:
        # a watchdog reap marks the wedge diagnosis here so the collector
        # fails the generation's futures with WorkerWedged, not 'died'
        self._meta: Dict[str, Any] = {"wedge": None}
        # the collector binds ITS generation's pipe/pending/process: after a
        # restart() swaps them on self, the old thread must keep draining the
        # old pipe (to fail the old futures), not the new one
        self._collector = threading.Thread(
            target=self._collect,
            args=(self._conn, self._proc, self._pending, self._meta),
            daemon=True)
        self._collector.start()

    @property
    def is_alive(self) -> bool:
        return self._proc.is_alive()

    @property
    def exitcode(self) -> Optional[int]:
        return self._proc.exitcode

    def restart(self) -> None:
        """Respawn a dead (or wedged) worker process with the same rank/env.

        The reference is fail-fast by explicit design (no_restart actors,
        SURVEY.md §5.3 / reference: ray_ddp.py:119); this is the recovery
        primitive it deliberately lacks.  Pending futures on the old process
        fail with 'worker died'; the new process starts with a clean slate —
        callers re-dispatch work (resuming from checkpoints, see
        runtime/elastic.py)."""
        with self._send_lock:
            if self._proc.is_alive():
                if self._sigterm_is_notice:
                    # SIGTERM is a drain request in this worker, not a
                    # kill -- a busy rank would swallow it, cost the full
                    # join timeout, and stamp a bogus preemption flag
                    self._proc.kill()
                else:
                    self._proc.terminate()
            self._proc.join(timeout=10)
            if self._proc.is_alive():
                # SIGTERM blocked/ignored (wedged in uninterruptible work):
                # escalate, or we'd leak a duplicate-rank process whose open
                # pipe end keeps the old collector (and its futures) hanging
                self._proc.kill()
                self._proc.join(timeout=10)
            self._conn.close()  # unblocks the old collector via EOF/OSError
            self._spawn()

    # ------------------------------------------------------------------ #
    def execute(self, fn: Callable, *args, **kwargs) -> Future:
        """Ship fn to the worker; returns a Future (ObjectRef analog)."""
        return self.execute_blob(cloudpickle.dumps((fn, args, kwargs)))

    def execute_blob(self, blob: bytes, raw: bool = False) -> Future:
        """Ship an already-cloudpickled (fn, args, kwargs) blob.

        ``raw=True`` resolves the Future with the wire tuple
        ``(status, payload_bytes)`` without deserializing -- the host
        agent relays results to a remote driver this way, so classes only
        importable driver-side never unpickle on the agent."""
        fut: Future = Future()
        with self._send_lock:
            if not self._proc.is_alive():
                fut.set_exception(RuntimeError(
                    f"worker {self.rank} is dead"))
                return fut
            with self._state_lock:
                self._pending.append((fut, raw))
            try:
                self._conn.send_bytes(blob)  # may block; collector still runs
            except (BrokenPipeError, OSError) as e:
                # worker died between the liveness check and the send
                with self._state_lock:
                    if (fut, raw) in self._pending:
                        self._pending.remove((fut, raw))
                fut.set_exception(RuntimeError(
                    f"worker {self.rank} died before accepting work: {e}"))
        return fut

    def _collect(self, conn, proc, pending_list, meta=None) -> None:
        from .wire import rebuild_remote

        while True:
            try:
                blob = conn.recv_bytes()
            except (EOFError, OSError):
                with self._state_lock:
                    pending = list(pending_list)
                    pending_list.clear()
                    wedge = (meta or {}).get("wedge")
                for fut, _raw in pending:
                    if fut.done():
                        continue
                    if wedge is not None:
                        # deliberate watchdog kill of an alive-but-stuck
                        # process: callers must see a wedge, not a death
                        fut.set_exception(
                            WorkerWedged.for_rank(self.rank, wedge))
                    else:
                        fut.set_exception(RuntimeError(
                            f"worker {self.rank} died "
                            f"(exitcode={proc.exitcode})"))
                return
            with self._state_lock:
                fut, raw = pending_list.pop(0)
            try:
                status, payload = cloudpickle.loads(blob)
                if raw:
                    fut.set_result((status, payload))
                elif status == "ok":
                    fut.set_result(cloudpickle.loads(payload))
                else:
                    # same typed-rebuild registry as the agent relay
                    # (runtime/wire.py): a Preempted/WorkerWedged raised
                    # INSIDE dispatched work crosses the local pipe as
                    # typed as it crosses the relay
                    name, msg, tb = cloudpickle.loads(payload)
                    fut.set_exception(rebuild_remote(name, msg, tb))
            except BaseException as e:
                # a result that can't unpickle driver-side (e.g. a class only
                # importable in the worker) must fail ITS future, not kill
                # this collector thread and strand every later future
                if not fut.done():
                    fut.set_exception(RuntimeError(
                        f"failed to deserialize result from worker "
                        f"{self.rank}: {type(e).__name__}: {e}"))

    def telemetry_tail(self) -> Optional[Dict[str, Any]]:
        """This rank's spilled flight-recorder snapshot (telemetry/
        recorder.py), read from the shared ``RLA_TPU_TELEMETRY_DIR``
        spill file — works even when the worker is wedged or dead,
        which is exactly when the watchdog asks.  None when no
        telemetry dir is configured or the rank never spilled."""
        from ..telemetry.recorder import read_spill, spill_path_for
        path = spill_path_for(self.rank, env=self._env)
        return read_spill(path) if path else None

    def live_snapshot(self) -> Optional[Dict[str, Any]]:
        """This rank's LIVE telemetry snapshot (telemetry/live.py),
        scraped from its portfile-published loopback endpoint — the
        ClusterView seam.  None when the live plane is disabled, the
        rank never bound, or it stopped answering (a wedged rank's
        last snapshot survives in the ClusterView's view, not here)."""
        from ..telemetry.live import scrape_rank
        try:
            return scrape_rank(self.rank, env=self._env)
        except Exception:
            return None

    # parity surface (reference: ray_ddp.py:21-27)
    def set_env_var(self, key: str, value: str) -> Future:
        return self.execute(_set_env, key, value)

    def get_node_ip(self) -> str:
        return self.execute(_node_ip).result()

    def reap(self, diagnosis: Optional[Dict[str, Any]] = None) -> None:
        """Deliberate SIGTERM-then-SIGKILL of an alive-but-stuck worker
        (the watchdog's kill path).  Unlike a spontaneous death, pending
        futures fail with ``WorkerWedged`` carrying the diagnosis, so
        retry layers can tell a wedge from a crash.  The worker stays
        restartable (``restart()`` respawns with rank/env intact)."""
        with self._state_lock:
            self._meta["wedge"] = dict(diagnosis or {})
        self.kill()

    def kill(self) -> None:
        if self._proc.is_alive():
            if self._sigterm_is_notice:
                # SIGTERM means "drain" in this worker (see __init__);
                # a deliberate kill goes straight to SIGKILL
                self._proc.kill()
            else:
                self._proc.terminate()
            self._proc.join(timeout=5)
        if self._proc.is_alive():
            # SIGTERM isn't fatal to every worker: jax.distributed installs
            # a preemption notifier that CATCHES it (and gloo-wedged ranks
            # sit in C++), so escalate -- a surviving child would hang the
            # interpreter's exit join forever (mp joins daemons at exit)
            self._proc.kill()
            self._proc.join(timeout=5)

    def shutdown(self, timeout: float = 10.0) -> None:
        try:
            with self._send_lock:
                self._conn.send_bytes(_SENTINEL)
            self._proc.join(timeout=timeout)
        except (BrokenPipeError, OSError):
            pass
        if self._proc.is_alive():
            self.kill()


def _set_env(key: str, value: str) -> None:
    os.environ[key] = value


def _probe_ok() -> bool:
    return True


def _node_ip() -> str:
    from .net import node_ip
    return node_ip()


class ActorPool:
    """N workers + fan-out helpers (the reference's actor list + fan-out loop,
    ray_ddp.py:105,178-182).

    ``agents``: HostAgent addresses ("host:port") for multi-machine pools --
    workers become `agent.RemoteWorker`s spread in contiguous blocks over
    the agents (the reference's multi-node actor placement,
    ray_ddp.py:92-97).  None = local subprocesses."""

    def __init__(self, num_workers: int,
                 env_per_worker: Optional[Sequence[Dict[str, str]]] = None,
                 init_hook: Optional[Callable[[], None]] = None,
                 agents: Optional[Sequence[str]] = None):
        envs = env_per_worker or [{} for _ in range(num_workers)]
        assert len(envs) == num_workers
        self.workers: List[Any] = []
        # env overlays of ranks removed by drop(), kept so revive() can
        # re-place a host that came back (the elastic grow path)
        self._dropped_envs: Dict[int, Dict[str, str]] = {}
        try:
            if agents:
                from .agent import RemoteWorker, assign_agents
                assignment = assign_agents(list(agents), num_workers)
                for i in range(num_workers):
                    self.workers.append(
                        RemoteWorker(assignment[i], i, envs[i]))
            else:
                ctx = mp.get_context("spawn")
                for i in range(num_workers):
                    self.workers.append(Worker(i, envs[i], ctx))
        except BaseException:
            # one unreachable agent must not orphan the workers already
            # spawned on the healthy ones
            self.kill()
            raise
        if init_hook is not None:
            for f in self.execute_all(init_hook):
                f.result()

    def __len__(self) -> int:
        return len(self.workers)

    def execute_all(self, fn: Callable, *args, **kwargs) -> List[Future]:
        return [w.execute(fn, *args, **kwargs) for w in self.workers]

    def execute_per_worker(self, fn: Callable,
                           args_per_worker: Sequence[tuple]) -> List[Future]:
        return [w.execute(fn, *args)
                for w, args in zip(self.workers, args_per_worker)]

    def set_env_vars(self, env: Dict[str, str]) -> None:
        futs = []
        for k, v in env.items():
            futs += [w.set_env_var(k, str(v)) for w in self.workers]
        for f in futs:
            f.result()

    def node_ips(self) -> List[str]:
        return [w.get_node_ip() for w in self.workers]

    def local_ranks(self) -> List[int]:
        """Global->local rank map from the node-IP census
        (reference: ray_ddp.py:132-143)."""
        counts: Dict[str, int] = {}
        ranks = []
        for ip in self.node_ips():
            ranks.append(counts.get(ip, 0))
            counts[ip] = counts.get(ip, 0) + 1
        return ranks

    # ------------------------------------------------------------------ #
    # failure detection / recovery (absent-by-design in the reference,
    # SURVEY.md §5.3; first-class here)                                  #
    # ------------------------------------------------------------------ #
    def health_check(self) -> List[bool]:
        """Liveness per rank, detected from the OS process state.  Note
        this only sees DEAD workers; a wedged (alive-but-stuck) rank needs
        progress-based supervision -- see ``watch()``."""
        return [w.is_alive for w in self.workers]

    def watch(self, **kwargs) -> "Any":
        """A started ``runtime.watchdog.Watchdog`` over this pool: per-rank
        ``ok | slow | wedged | dead`` classification from heartbeats, with
        wedged ranks reaped so their futures fail ``WorkerWedged``."""
        from .watchdog import Watchdog
        return Watchdog(self, **kwargs).start()

    def add_worker(self, env: Optional[Dict[str, str]] = None,
                   rank: Optional[int] = None) -> Worker:
        """Grow the pool by one LOCAL worker (the serve tier's scale-up
        primitive, serve/controller.py).  The new worker gets the next
        free rank (max existing + 1 — ranks are identity, so a rank
        freed by ``drop`` is never reused within one pool lifetime) and
        its own env overlay.  Agent-backed pools are not supported: a
        remote scale-up needs placement the agent protocol doesn't
        express yet."""
        if self.workers and not isinstance(self.workers[0], Worker):
            raise RuntimeError(
                "add_worker supports local subprocess pools only "
                "(agent-backed pools cannot place new workers)")
        if rank is None:
            rank = max((w.rank for w in self.workers), default=-1) + 1
        w = Worker(rank, dict(env or {}), mp.get_context("spawn"))
        self.workers.append(w)
        log.warning("added worker rank %d; pool now %d rank(s) %s",
                    rank, len(self.workers),
                    [x.rank for x in self.workers])
        return w

    def restart_dead(self, init_hook: Optional[Callable[[], None]] = None) \
            -> List[int]:
        """Respawn every dead worker; returns the restarted ranks."""
        restarted = []
        for w in self.workers:
            if not w.is_alive:
                w.restart()
                restarted.append(w.rank)
        if restarted and init_hook is not None:
            for rank in restarted:
                self.workers[rank].execute(init_hook).result()
        if restarted:
            log.warning("restarted dead workers: %s", restarted)
        return restarted

    def _probe_sweep(self, workers, timeout_s: float) -> List[int]:
        """Parallel round-trip probes; returns the ranks that failed.
        The timeout is shared across the whole sweep (the dispatches run
        in parallel)."""
        import time as _time
        futs = [(w.rank, w.execute(_probe_ok)) for w in workers]
        deadline = _time.monotonic() + timeout_s
        lost = []
        for rank, f in futs:
            try:
                f.result(timeout=max(0.1, deadline - _time.monotonic()))
            except BaseException as e:
                log.warning("probe of worker %d failed: %s", rank, e)
                lost.append(rank)
        return lost

    def find_lost(self, timeout_s: float = 120.0, classify: bool = False):
        """Ranks that fail a trivial round-trip dispatch within
        ``timeout_s`` — the "is this host actually back?" probe run after
        a restart.  A permanently lost rank (host gone; chaos
        ``lost@rankN``) respawns and immediately dies, failing its probe
        future fast via the collector's EOF path; healthy ranks answer as
        soon as their interpreter finishes booting.

        ``classify=True`` distinguishes a REVIVABLE rank from a gone one
        (the elastic grow path): each failed rank gets one restart + one
        re-probe — a host that came back mid-sweep (chaos ``rejoin``
        clearing its ``lost`` marker) lands in ``"revived"`` and stays
        in the pool; the rest are ``"gone"``.  Returns
        ``{"gone": [...], "revived": [...]}`` instead of the flat
        list."""
        lost = self._probe_sweep(self.workers, timeout_s)
        if not classify:
            return lost
        if not lost:
            return {"gone": [], "revived": []}
        retry = [w for w in self.workers if w.rank in set(lost)]
        for w in retry:
            try:
                w.restart()
            except BaseException as e:
                log.warning("classify restart of worker %d failed: %s",
                            w.rank, e)
        still_lost = set(self._probe_sweep(retry, timeout_s))
        revived = sorted(set(lost) - still_lost)
        if revived:
            log.warning("lost rank(s) %s answered their re-probe; "
                        "keeping them in the pool", revived)
        return {"gone": sorted(still_lost), "revived": revived}

    def drop(self, ranks: Sequence[int]) -> List[int]:
        """Remove ``ranks`` from the pool (the elastic scale-down
        primitive): the named workers are killed and forgotten; survivors
        KEEP their original rank identity — rank is placement (which
        host/slot a worker is), not position, so a surviving rank 2 stays
        rank 2 while callers dispatch with logical ranks derived from
        list position (``ElasticRunner`` passes the new world size to
        ``args_per_worker``)."""
        gone = set(ranks)
        dropping = [w for w in self.workers if w.rank in gone]
        for w in dropping:
            # remember the env overlay: a dropped host that comes back
            # can be re-placed at its old rank via revive()
            self._dropped_envs[w.rank] = dict(getattr(w, "_env", {}) or {})
            try:
                w.kill()
            except BaseException:
                pass
        self.workers = [w for w in self.workers if w.rank not in gone]
        dropped = [w.rank for w in dropping]
        if dropped:
            log.warning("dropped lost workers %s; pool now %d rank(s) %s",
                        dropped, len(self.workers),
                        [w.rank for w in self.workers])
        return dropped

    def dropped_ranks(self) -> List[int]:
        """Ranks removed by ``drop`` whose env overlay is remembered —
        the revival candidates the elastic grow path retries."""
        return sorted(self._dropped_envs)

    def revive(self, rank: int,
               probe_timeout_s: float = 30.0) -> Optional[Worker]:
        """Re-place a previously dropped rank (the elastic grow
        primitive): spawn a fresh Worker at the SAME rank with its
        remembered env overlay and probe it.  Returns the worker (now
        back in the pool, inserted in rank order so logical-rank
        dispatch stays deterministic) on success; None when the rank was
        never dropped, the pool is agent-backed, or the host is still
        gone (the probe failed — the spawn is killed and the rank stays
        dropped for a later retry)."""
        env = self._dropped_envs.get(rank)
        if env is None:
            return None
        if self.workers and not isinstance(self.workers[0], Worker):
            log.warning("revive(%d): agent-backed pools cannot re-place "
                        "workers", rank)
            return None
        w = Worker(rank, dict(env), mp.get_context("spawn"))
        if self._probe_sweep([w], probe_timeout_s):
            try:
                w.kill()
            except BaseException:
                pass
            log.warning("revive(%d): host still gone (probe failed)",
                        rank)
            return None
        del self._dropped_envs[rank]
        self.workers.append(w)
        self.workers.sort(key=lambda x: x.rank)
        log.warning("revived worker rank %d; pool now %d rank(s) %s",
                    rank, len(self.workers),
                    [x.rank for x in self.workers])
        return w

    def restart_all(self, init_hook: Optional[Callable[[], None]] = None) \
            -> List[int]:
        """Respawn EVERY worker, alive or not.

        The recovery primitive for collective (SPMD) work: when one rank
        dies mid-collective its peers stay alive-but-wedged in the broken
        collective, so restarting only the dead rank would re-dispatch into
        workers that never dequeue again.  All ranks restart together."""
        for w in self.workers:
            w.restart()
        ranks = [w.rank for w in self.workers]
        if init_hook is not None:
            for f in self.execute_all(init_hook):
                f.result()
        log.warning("restarted all workers: %s", ranks)
        return ranks

    def shutdown(self) -> None:
        # reverse rank order: rank 0 hosts the jax.distributed
        # coordination service, and a peer outliving it by milliseconds
        # logs a FATAL "leader died" before being reaped
        for w in reversed(self.workers):
            w.shutdown()

    def kill(self) -> None:
        for w in reversed(self.workers):
            w.kill()

    def __enter__(self) -> "ActorPool":
        return self

    def __exit__(self, *exc) -> None:
        self.shutdown()
