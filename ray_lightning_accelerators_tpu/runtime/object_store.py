"""Shared-memory object store: put/get large pytrees across host processes.

Capability analog of the reference's use of Ray's plasma object store —
``trainer_ref = ray.put(trainer)`` then every worker dereferences it
(reference: ray_lightning/ray_ddp.py:169-182, ray_horovod.py:124,148).
There, big payloads move through Ray's C++ store instead of being pickled
per-actor; here, numpy leaves above a size threshold go into POSIX shm
segments (native/shm_store.cc) that spawn workers on the same host map by
name, so N workers share one copy instead of N pickled copies through actor
pipes.

Driver-side lifecycle: the creating store owns its segments and unlinks them
on ``delete``/``shutdown``/exit.  ``ObjectRef`` itself is a small picklable
handle (segment names + pytree structure) that ships through the normal
actor channel; workers resolve it with ``get`` (``runtime.actors`` does this
automatically for top-level arguments, mirroring Ray's call-site deref).
"""

from __future__ import annotations

import atexit
import ctypes
import errno
import os
import secrets
import threading
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import cloudpickle
import numpy as np

from .. import native

DEFAULT_THRESHOLD = 64 * 1024  # leaves smaller than this stay inline


class _Placeholder:
    """Stand-in for a shm-backed leaf inside the pickled pytree."""

    __slots__ = ("index",)

    def __init__(self, index: int):
        self.index = index


@dataclass(frozen=True)
class ObjectRef:
    """Picklable handle on a stored object (the ray.ObjectRef analog)."""

    object_id: str
    # per shm leaf: (segment name, dtype string, shape)
    segments: Tuple[Tuple[str, str, Tuple[int, ...]], ...]
    payload: bytes = field(repr=False)  # cloudpickled tree w/ placeholders

    def total_shm_bytes(self) -> int:
        return sum(int(np.dtype(d).itemsize) * int(np.prod(s, dtype=np.int64))
                   for _, d, s in self.segments)


class ObjectStoreError(RuntimeError):
    pass


def _check_errno(action: str, name: str) -> "ObjectStoreError":
    err = native.lib().rla_shm_errno()
    if err == errno.ENOENT:
        return ObjectStoreError(
            f"{action} {name!r}: segment does not exist (already deleted, "
            f"or put on a different host — shm is per-host like plasma)")
    return ObjectStoreError(f"{action} {name!r}: {os.strerror(err)}")


class ObjectStore:
    """Put/get pytrees; large numpy leaves ride shared memory."""

    def __init__(self, threshold: int = DEFAULT_THRESHOLD):
        self.threshold = threshold
        self._lock = threading.Lock()
        self._owned: Dict[str, List[str]] = {}  # object_id -> segment names
        self._owned_bytes: Dict[str, int] = {}  # object_id -> shm bytes
        # zero-copy mappings, keyed by object_id so release(ref) can drop
        # exactly one object's views (pipeline handoff: a stage unmaps the
        # previous step's received activations at the next step boundary)
        self._mappings: Dict[str, List[Tuple[int, int]]] = {}
        self._prefix = f"/rla-{os.getpid()}-{secrets.token_hex(4)}"
        self._counter = 0
        atexit.register(self.shutdown)

    def total_shm_bytes(self) -> int:
        """Live shm bytes this store OWNS (placed and not yet deleted) —
        the ``object_store_shm`` gauge the perf HBM/host ledger samples."""
        with self._lock:
            return sum(self._owned_bytes.values())

    # ------------------------------------------------------------------ #
    def put(self, obj: Any) -> ObjectRef:
        """Store a pytree; large array leaves ride shared memory.

        Copy discipline (the pipeline-handoff fast path): exactly ONE
        copy per large leaf — ``np.copyto`` into the mapped segment.
        ``np.asarray`` on a CPU-backend ``jax.Array`` and
        ``np.ascontiguousarray`` on an already-contiguous array are both
        zero-copy views, so a stage publishing activations pays one
        host-side memcpy, and the receiver's ``get(copy=False)`` pays
        none (it feeds the read-only mapping straight to its programs).
        """
        import jax

        lib = native.lib()
        leaves, treedef = jax.tree_util.tree_flatten(obj)
        with self._lock:
            self._counter += 1
            object_id = f"{self._prefix}-{self._counter}"
        segments: List[Tuple[str, str, Tuple[int, ...]]] = []
        names: List[str] = []
        out_leaves: List[Any] = []
        try:
            for leaf in leaves:
                arr = None
                if isinstance(leaf, np.ndarray):
                    arr = leaf
                elif isinstance(leaf, jax.Array):
                    arr = np.asarray(leaf)  # view on CPU; one copy off-host
                if (arr is None or arr.dtype.hasobject
                        or arr.nbytes < self.threshold):
                    out_leaves.append(arr if arr is not None else leaf)
                    continue
                arr = np.ascontiguousarray(arr)  # no-op when contiguous
                name = f"{object_id}-{len(segments)}"
                ptr = lib.rla_shm_create(name.encode(), arr.nbytes)
                if not ptr:
                    raise _check_errno("create", name)
                dst = np.frombuffer(
                    (ctypes.c_char * arr.nbytes).from_address(ptr),
                    dtype=arr.dtype).reshape(arr.shape)
                np.copyto(dst, arr)
                del dst
                lib.rla_shm_unmap(ptr, arr.nbytes)
                out_leaves.append(_Placeholder(len(segments)))
                segments.append((name, arr.dtype.str, tuple(arr.shape)))
                names.append(name)
        except BaseException:
            for n in names:
                lib.rla_shm_unlink(n.encode())
            raise
        payload = cloudpickle.dumps(
            jax.tree_util.tree_unflatten(treedef, out_leaves))
        ref = ObjectRef(object_id, tuple(segments), payload)
        with self._lock:
            self._owned[object_id] = names
            self._owned_bytes[object_id] = ref.total_shm_bytes()
        return ref

    # ------------------------------------------------------------------ #
    def get(self, ref: ObjectRef, copy: bool = True) -> Any:
        """Materialize a stored object.

        ``copy=True`` (default) returns independent arrays.  ``copy=False``
        returns read-only views into the mapped segments — zero-copy, valid
        until this store is shut down (mappings are retained by the store).
        """
        import jax

        lib = native.lib()
        arrays: List[np.ndarray] = []
        for name, dtype_str, shape in ref.segments:
            size_out = ctypes.c_long()
            ptr = lib.rla_shm_open_ro(name.encode(), ctypes.byref(size_out))
            if not ptr:
                raise _check_errno("open", name)
            nbytes = size_out.value
            view = np.frombuffer(
                (ctypes.c_char * nbytes).from_address(ptr),
                dtype=np.dtype(dtype_str)).reshape(shape)
            view.flags.writeable = False
            if copy:
                arrays.append(view.copy())
                del view
                lib.rla_shm_unmap(ptr, nbytes)
            else:
                with self._lock:
                    self._mappings.setdefault(
                        ref.object_id, []).append((ptr, nbytes))
                arrays.append(view)
        tree = cloudpickle.loads(ref.payload)
        return jax.tree_util.tree_map(
            lambda l: arrays[l.index] if isinstance(l, _Placeholder) else l,
            tree, is_leaf=lambda l: isinstance(l, _Placeholder))

    # ------------------------------------------------------------------ #
    def release(self, ref: ObjectRef) -> None:
        """Unmap the zero-copy views a ``get(copy=False)`` of this ref
        retained.  Caller contract: every array that aliased the mapping
        is dead by now (the pipeline tick loop releases a step's refs at
        the NEXT step boundary, after its programs consumed them)."""
        lib = native.lib()
        with self._lock:
            mappings = self._mappings.pop(ref.object_id, [])
        for ptr, nbytes in mappings:
            lib.rla_shm_unmap(ptr, nbytes)

    def delete(self, ref: ObjectRef) -> None:
        lib = native.lib()
        with self._lock:
            names = self._owned.pop(ref.object_id, None)
            self._owned_bytes.pop(ref.object_id, None)
        for name in (names if names is not None
                     else [s[0] for s in ref.segments]):
            lib.rla_shm_unlink(name.encode())

    def shutdown(self) -> None:
        try:
            lib = native.lib()
        except RuntimeError:
            return
        with self._lock:
            owned = list(self._owned.values())
            self._owned.clear()
            self._owned_bytes.clear()
            mappings, self._mappings = self._mappings, {}
        for per_obj in mappings.values():
            for ptr, nbytes in per_obj:
                lib.rla_shm_unmap(ptr, nbytes)
        for names in owned:
            for name in names:
                lib.rla_shm_unlink(name.encode())

    def __enter__(self) -> "ObjectStore":
        return self

    def __exit__(self, *exc) -> None:
        self.shutdown()


# process-global store: workers resolve inbound ObjectRefs through this
_GLOBAL: Optional[ObjectStore] = None
_GLOBAL_LOCK = threading.Lock()


def global_store() -> ObjectStore:
    global _GLOBAL
    with _GLOBAL_LOCK:
        if _GLOBAL is None:
            _GLOBAL = ObjectStore()
        return _GLOBAL


def resolve(value: Any) -> Any:
    """Dereference if value is an ObjectRef (Ray-style call-site deref)."""
    if isinstance(value, ObjectRef):
        return global_store().get(value)
    return value


def put(obj: Any) -> ObjectRef:
    """``ray.put`` analog on the process-global store
    (reference: ray_lightning/ray_ddp.py:169)."""
    return global_store().put(obj)


def get(ref: ObjectRef, copy: bool = True) -> Any:
    """``ray.get`` analog on the process-global store."""
    return global_store().get(ref, copy=copy)


def global_shm_bytes() -> int:
    """Gauge for the perf HBM/host ledger: live shm bytes owned by this
    process's global store (0 when no store was ever built — sampling
    must not instantiate one)."""
    with _GLOBAL_LOCK:
        store = _GLOBAL
    return store.total_shm_bytes() if store is not None else 0
