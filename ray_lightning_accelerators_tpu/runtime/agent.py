"""Per-host worker agents: the multi-machine launch path.

Capability analog of the reference's multi-NODE story -- Ray actors placed
on remote cluster nodes with zero per-node setup (reference:
README.md:57-62 ``ray up`` / ``ray submit``; ray_lightning/ray_ddp.py:92-97
actor placement, :162-163 rank-0 rendezvous address).  Without Ray in the
image this is a from-scratch control plane:

- a **HostAgent** runs on every machine (``rla-tpu agent --port 7777``):
  a TCP server that spawns one `runtime.actors.Worker` subprocess per
  driver connection and relays cloudpickled work/results;
- a driver-side **RemoteWorker** speaks that protocol behind the exact
  interface of the local ``Worker`` (execute -> Future, restart, kill,
  node_ip), so ``ActorPool`` mixes local and remote workers freely;
- ``free_port``/``node_ip`` agent RPCs let the driver pick a
  ``jax.distributed`` coordinator address on the rank-0 HOST (the
  reference computed its tcp:// init string on the rank-0 actor,
  ray_ddp.py:162-163).

Wire protocol: 4-byte big-endian length prefix + cloudpickle payload.
Driver -> agent: ``(req_id, op, payload)``; agent -> driver:
``(req_id, status, payload)``.  ``execute`` replies when the worker
finishes (the agent relays the worker's raw result bytes without
deserializing them -- driver-only classes never unpickle on the agent).

Security note: agents execute arbitrary pickled callables, exactly like a
Ray worker does.  The default bind is loopback; binding a wider interface
should be paired with the shared-secret handshake (``RLA_TPU_AGENT_TOKEN``
on both ends -- the analog of Ray's redis password): connections must send
an ``auth`` frame with the token before any other op or they are refused.
"""

from __future__ import annotations

import os
import socket
import struct
import threading
from concurrent.futures import Future
from typing import Dict, List, Optional, Sequence, Tuple

import cloudpickle

from ..analysis import knobs
from ..utils.logging import log

_LEN = struct.Struct(">I")
DEFAULT_PORT = 7777
TOKEN_ENV = "RLA_TPU_AGENT_TOKEN"
# the auth frame is RAW BYTES with this prefix, compared before ANY
# cloudpickle.loads runs -- unpickling an unauthenticated frame would
# itself be the RCE the token exists to prevent
AUTH_MAGIC = b"RLA-TPU-AUTH1:"


def _token_from_env() -> Optional[str]:
    return knobs.get_str(TOKEN_ENV, None)


def check_auth_frame(raw: bytes, token: Optional[str]) -> Optional[bool]:
    """Classify a connection's FIRST raw frame.

    Returns True (valid auth frame / none required and frame is auth --
    skip it), False (refuse: bad token, or token required and the frame
    is not an auth frame), or None (no token required and this is a
    normal data frame -- process it)."""
    import hmac
    if raw.startswith(AUTH_MAGIC):
        if token is None:
            return True  # open endpoint: accept and ignore the frame
        return hmac.compare_digest(raw[len(AUTH_MAGIC):], token.encode())
    return False if token is not None else None


def auth_frame(token: str) -> bytes:
    return AUTH_MAGIC + token.encode()


# --------------------------------------------------------------------- #
# Framing                                                                #
# --------------------------------------------------------------------- #
def send_msg(sock: socket.socket, obj) -> None:
    blob = cloudpickle.dumps(obj)
    sock.sendall(_LEN.pack(len(blob)) + blob)


def send_raw(sock: socket.socket, blob: bytes) -> None:
    sock.sendall(_LEN.pack(len(blob)) + blob)


def recv_raw(sock: socket.socket) -> bytes:
    """Read one frame's raw bytes; raises ConnectionError on EOF."""
    header = _recv_exact(sock, _LEN.size)
    (n,) = _LEN.unpack(header)
    return _recv_exact(sock, n)


def recv_msg(sock: socket.socket):
    """Read one frame; raises ConnectionError on EOF mid-frame."""
    return cloudpickle.loads(recv_raw(sock))


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("connection closed")
        buf += chunk
    return bytes(buf)


def _node_ip() -> str:
    from .net import node_ip
    return node_ip()


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


# --------------------------------------------------------------------- #
# Agent (server) side                                                    #
# --------------------------------------------------------------------- #
class HostAgent:
    """One per machine.  Each accepted connection owns at most one worker
    subprocess (the driver opens one connection per remote worker)."""

    def __init__(self, port: int = DEFAULT_PORT, bind: str = "127.0.0.1",
                 token: Optional[str] = None):
        # token: shared secret required from every connection before any
        # other op; defaults to $RLA_TPU_AGENT_TOKEN so `rla-tpu agent` and
        # driver pick it up symmetrically.  None + loopback bind = open.
        self._token = token if token is not None else _token_from_env()
        check_tokenless_wide_bind("HostAgent", bind, self._token)
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((bind, port))
        self._srv.listen(128)
        self.port = self._srv.getsockname()[1]
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        # observability: total worker subprocesses ever spawned -- a driver
        # whose world persists across entry points spawns each rank ONCE
        self.spawn_count = 0

    def serve_forever(self) -> None:
        log.warning("rla-tpu agent listening on %s:%d", _node_ip(),
                    self.port)
        while not self._stop.is_set():
            try:
                conn, addr = self._srv.accept()
            except OSError:
                return  # socket closed by shutdown()
            t = threading.Thread(target=self._serve_conn,
                                 args=(conn, addr), daemon=True)
            t.start()
            self._threads.append(t)

    def serve_in_background(self) -> threading.Thread:
        t = threading.Thread(target=self.serve_forever, daemon=True)
        t.start()
        return t

    def shutdown(self) -> None:
        self._stop.set()
        try:
            self._srv.close()
        except OSError:
            pass

    # ------------------------------------------------------------------ #
    def _serve_conn(self, conn: socket.socket, addr) -> None:
        from .actors import Worker

        conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        worker: Optional[Worker] = None
        first_frame = True
        send_lock = threading.Lock()  # execute replies come from callbacks

        def reply(req_id, status, payload) -> None:
            try:
                with send_lock:
                    send_msg(conn, (req_id, status, payload))
            except OSError:
                pass  # driver went away; nothing to tell it

        try:
            while True:
                try:
                    raw = recv_raw(conn)
                except (ConnectionError, OSError):
                    return
                if first_frame:
                    # auth happens on RAW bytes, before any unpickling --
                    # cloudpickle.loads of an untrusted frame IS code
                    # execution, so a tokened agent never deserializes an
                    # unauthenticated connection's data.  Refusals close
                    # silently (a reply protocol would need the frame's
                    # req_id, which only unpickling could produce).
                    first_frame = False
                    verdict = check_auth_frame(raw, self._token)
                    if verdict is True:
                        continue  # auth frame consumed
                    if verdict is False:
                        log.warning(
                            "refused unauthenticated connection from %s "
                            "(%s required)", addr, TOKEN_ENV)
                        return
                    # None: open agent, normal data frame -- fall through
                try:
                    req_id, op, payload = cloudpickle.loads(raw)
                except BaseException:
                    return  # malformed frame: drop the connection
                try:
                    if op == "spawn":
                        rank, env = payload
                        worker = Worker(rank, env)
                        self.spawn_count += 1
                        reply(req_id, "ok", None)
                    elif op == "execute":
                        fut = worker.execute_blob(payload, raw=True)

                        def _done(f, req_id=req_id):
                            e = f.exception()
                            if e is not None:
                                # worker died (never produced wire bytes)
                                reply(req_id, "err", cloudpickle.dumps(
                                    (type(e).__name__, str(e), "")))
                            else:
                                status, result_payload = f.result()
                                # worker payloads are already pickled --
                                # tag so the driver knows to loads() them
                                reply(req_id,
                                      "raw-ok" if status == "ok" else "err",
                                      result_payload)

                        fut.add_done_callback(_done)
                    elif op == "alive":
                        reply(req_id, "ok", worker is not None
                              and worker.is_alive)
                    elif op == "heartbeat":
                        # snapshot taken HERE so only clock-free ages cross
                        # the wire (driver and agent clocks need not agree)
                        hb = getattr(worker, "heartbeat", None)
                        reply(req_id, "ok",
                              None if hb is None else hb.snapshot())
                    elif op == "telemetry":
                        # the worker's spilled flight-recorder tail, read
                        # agent-side (the spill file lives on THIS host).
                        # Works on a wedged/dead worker — the file is the
                        # part of the rank that survives it.
                        reply(req_id, "ok",
                              None if worker is None
                              else worker.telemetry_tail())
                    elif op == "live":
                        # the worker's live telemetry /snapshot, scraped
                        # agent-side (the loopback endpoint + portfile
                        # live on THIS host) — the ClusterView's remote
                        # seam, mirroring the `telemetry` spill op
                        reply(req_id, "ok",
                              None if worker is None
                              else worker.live_snapshot())
                    elif op == "reap":
                        if worker is not None:
                            worker.reap(payload)
                        reply(req_id, "ok", None)
                    elif op == "restart":
                        worker.restart()
                        reply(req_id, "ok", None)
                    elif op == "kill":
                        if worker is not None:
                            worker.kill()
                        reply(req_id, "ok", None)
                    elif op == "worker_shutdown":
                        if worker is not None:
                            worker.shutdown()
                            worker = None
                        reply(req_id, "ok", None)
                    elif op == "node_ip":
                        reply(req_id, "ok", _node_ip())
                    elif op == "free_port":
                        reply(req_id, "ok", free_port())
                    elif op == "ping":
                        reply(req_id, "ok", "pong")
                    else:
                        reply(req_id, "err", cloudpickle.dumps(
                            ("ValueError", f"unknown op {op!r}", "")))
                except BaseException as e:  # never kill the conn loop
                    reply(req_id, "err", cloudpickle.dumps(
                        (type(e).__name__, str(e), "")))
        finally:
            if worker is not None:
                worker.kill()
            try:
                conn.close()
            except OSError:
                pass


# --------------------------------------------------------------------- #
# Driver side                                                            #
# --------------------------------------------------------------------- #
def parse_address(address: str) -> Tuple[str, int]:
    host, _, port = address.partition(":")
    return host, int(port) if port else DEFAULT_PORT


class AgentConnection:
    """A single multiplexed request/response connection to a HostAgent."""

    def __init__(self, address: str, timeout: Optional[float] = None,
                 token: Optional[str] = None):
        self.address = address
        if timeout is None:
            # how long to keep retrying an unreachable agent (boot grace);
            # tests / fail-fast deployments shrink it via env
            timeout = knobs.get_float("RLA_TPU_AGENT_CONNECT_TIMEOUT", 30.0)
        token = token if token is not None else _token_from_env()
        host, port = parse_address(address)
        # retry while the agent boots: "start agents, then the driver" is
        # the documented flow, and an agent importing jax takes seconds
        import time as time_mod
        deadline = time_mod.monotonic() + timeout
        while True:
            try:
                self._sock = socket.create_connection(
                    (host, port), timeout=timeout)
                break
            except ConnectionRefusedError:
                if time_mod.monotonic() >= deadline:
                    raise
                time_mod.sleep(0.25)
        self._sock.settimeout(None)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._token_sent = token is not None
        if token is not None:
            # raw-bytes handshake, fire-and-forget: the agent validates it
            # before unpickling anything; a mismatch closes the connection
            # (surfaced by the first op's ConnectionError)
            send_raw(self._sock, auth_frame(token))
        self._send_lock = threading.Lock()
        self._state_lock = threading.Lock()
        self._pending: Dict[int, Future] = {}
        self._next_id = 0
        self._closed = False
        self._recv_thread = threading.Thread(target=self._recv_loop,
                                             daemon=True)
        self._recv_thread.start()

    def request(self, op: str, payload=None) -> Future:
        fut: Future = Future()
        with self._state_lock:
            if self._closed:
                fut.set_exception(ConnectionError(
                    f"agent {self.address} connection closed"))
                return fut
            req_id = self._next_id
            self._next_id += 1
            self._pending[req_id] = fut
        try:
            with self._send_lock:
                send_msg(self._sock, (req_id, op, payload))
        except OSError as e:
            with self._state_lock:
                self._pending.pop(req_id, None)
            if not fut.done():  # _recv_loop may have failed it concurrently
                fut.set_exception(ConnectionError(
                    f"agent {self.address} unreachable: {e}"))
        return fut

    def call(self, op: str, payload=None, timeout: float = 60.0):
        return self.request(op, payload).result(timeout=timeout)

    def _recv_loop(self) -> None:
        from .wire import rebuild_remote

        while True:
            try:
                req_id, status, payload = recv_msg(self._sock)
            except (ConnectionError, OSError):
                with self._state_lock:
                    self._closed = True
                    pending = list(self._pending.values())
                    self._pending.clear()
                hint = ("" if self._token_sent else
                        f" (if the agent requires {TOKEN_ENV}, export it "
                        f"on the driver too)")
                for fut in pending:
                    if not fut.done():
                        fut.set_exception(ConnectionError(
                            f"lost connection to agent "
                            f"{self.address}{hint}"))
                return
            with self._state_lock:
                fut = self._pending.pop(req_id, None)
            if fut is None or fut.done():
                continue
            try:
                if status == "ok":
                    fut.set_result(payload)
                elif status == "raw-ok":
                    fut.set_result(cloudpickle.loads(payload))
                else:
                    # rebuild typed outcomes (WorkerWedged diagnosis,
                    # Preempted step/checkpoint info, resize refusals)
                    # from the wire registry so driver-side retry layers
                    # classify them; everything else stays RemoteError
                    name, msg, tb = cloudpickle.loads(payload)
                    fut.set_exception(rebuild_remote(name, msg, tb))
            except BaseException as e:
                fut.set_exception(RuntimeError(
                    f"failed to deserialize result from agent "
                    f"{self.address}: {type(e).__name__}: {e}"))

    def close(self) -> None:
        with self._state_lock:
            self._closed = True
        try:
            self._sock.close()
        except OSError:
            pass


class RemoteWorker:
    """Driver-side handle to a worker subprocess on a remote HostAgent.

    Interface-compatible with ``runtime.actors.Worker`` so ``ActorPool``
    treats both uniformly."""

    def __init__(self, address: str, rank: int,
                 env: Optional[Dict[str, str]] = None):
        self.rank = rank
        self.address = address
        self._env = dict(env or {})
        self._conn = AgentConnection(address)
        self._conn.call("spawn", (rank, self._env))
        # Watchdog parity: snapshots are taken agent-side (ages only);
        # an unreachable agent degrades to liveness-only supervision
        self.heartbeat = _RemoteHeartbeat(self._conn)

    # -- Worker parity surface ---------------------------------------- #
    def execute(self, fn, *args, **kwargs) -> Future:
        # materialize driver-host object-store refs before shipping: the
        # remote host cannot see this host's shared memory
        from .object_store import ObjectRef, resolve
        if any(isinstance(a, ObjectRef) for a in args) or \
                any(isinstance(v, ObjectRef) for v in kwargs.values()):
            args = tuple(resolve(a) for a in args)
            kwargs = {k: resolve(v) for k, v in kwargs.items()}
        blob = cloudpickle.dumps((fn, args, kwargs))
        return self._conn.request("execute", blob)

    @property
    def is_alive(self) -> bool:
        try:
            return bool(self._conn.call("alive", timeout=10))
        except BaseException:
            return False

    @property
    def exitcode(self) -> Optional[int]:
        return None if self.is_alive else -1

    def restart(self) -> None:
        self._conn.call("restart", timeout=60)

    def reap(self, diagnosis: Optional[Dict] = None) -> None:
        """Watchdog kill of a wedged remote worker.  The agent connection
        stays open (unlike ``kill``): the worker slot remains restartable
        through the same agent, mirroring the local ``Worker.reap``."""
        try:
            self._conn.call("reap", diagnosis, timeout=30)
        except BaseException:
            pass  # agent gone: the lost connection already failed futures

    def telemetry_tail(self) -> Optional[Dict]:
        """This rank's spilled flight-recorder snapshot, fetched through
        the agent (the spill file lives on the remote host).  None on
        any failure — telemetry degrades, never blocks supervision."""
        try:
            return self._conn.call("telemetry", timeout=10)
        except BaseException:
            return None

    def live_snapshot(self) -> Optional[Dict]:
        """This rank's live telemetry /snapshot, scraped on the remote
        host through the agent (``live`` wire op — the portfile and
        loopback endpoint live there).  None on any failure: the
        ClusterView keeps the last successful view instead."""
        try:
            return self._conn.call("live", timeout=10)
        except BaseException:
            return None

    def set_env_var(self, key: str, value: str) -> Future:
        return self.execute(_set_env_remote, key, value)

    def get_node_ip(self) -> str:
        return self._conn.call("node_ip")

    def kill(self) -> None:
        try:
            self._conn.call("kill", timeout=10)
        except BaseException:
            pass
        self._conn.close()

    def shutdown(self, timeout: float = 10.0) -> None:
        try:
            self._conn.call("worker_shutdown", timeout=timeout)
        except BaseException:
            pass
        self._conn.close()


class _RemoteHeartbeat:
    """Driver-side heartbeat proxy for a worker on a HostAgent: snapshots
    are computed agent-side (only ages cross the wire).  Failures return
    None -- the watchdog then falls back to liveness-only supervision for
    this rank rather than false-positive killing on a slow network."""

    def __init__(self, conn: AgentConnection):
        self._conn = conn

    def snapshot(self) -> Optional[Dict]:
        try:
            # short timeout: the watchdog polls every rank sequentially,
            # so one partitioned agent must not stall wedge detection for
            # the healthy ranks by 10s-per-poll
            return self._conn.call("heartbeat", timeout=2)
        except BaseException:
            return None


def _set_env_remote(key: str, value: str) -> None:
    os.environ[key] = value


# --------------------------------------------------------------------- #
# Topology helpers                                                       #
# --------------------------------------------------------------------- #
def agents_from_env() -> Optional[List[str]]:
    """Agent addresses from ``RLA_TPU_AGENTS`` (comma-separated), set by
    ``rla-tpu launch`` or the user."""
    raw = (knobs.get_str("RLA_TPU_AGENTS", "") or "").strip()
    return [a.strip() for a in raw.split(",") if a.strip()] or None


def is_loopback(host: str) -> bool:
    """True only when ``host`` genuinely names the loopback interface.

    This feeds the tokenless-bind RCE gate, so it must not be foolable by
    prefix tricks: a hostname like ``127.evil.example`` can resolve to a
    public IP, and ``::1`` IS loopback.  Literal addresses are classified
    with ``ipaddress``; hostnames are resolved and count as loopback only
    when EVERY resolved address is (fail closed: unresolvable = not
    loopback, which at worst demands a token for a bind that didn't need
    one)."""
    import ipaddress
    host = host.strip().strip("[]")  # bracketed IPv6 literals
    if host == "localhost":
        return True
    try:
        return ipaddress.ip_address(host).is_loopback
    except ValueError:
        pass  # not a literal: resolve it
    try:
        infos = socket.getaddrinfo(host, None)
    except socket.gaierror:
        return False
    addrs = {info[4][0] for info in infos}
    try:
        return bool(addrs) and all(
            ipaddress.ip_address(a.split("%")[0]).is_loopback
            for a in addrs)
    except ValueError:
        return False


def check_tokenless_wide_bind(what: str, bind: str,
                              token: Optional[str]) -> None:
    """Shared RCE gate for every endpoint that executes received thunks
    (HostAgent runs them as this user; QueueServer unpickles and runs
    them driver-side): a tokenless network-reachable bind is refused
    unless RLA_TPU_ALLOW_TOKENLESS_BIND=1 explicitly accepts the risk --
    and even then the exposure is logged on every start."""
    if token is not None or is_loopback(bind):
        return
    if not knobs.get_bool("RLA_TPU_ALLOW_TOKENLESS_BIND"):
        raise RuntimeError(
            f"{what} refuses to bind {bind} without {TOKEN_ENV}: any "
            "host that can reach this port can execute code as this "
            "user.  Set the token on every machine (recommended), or "
            "set RLA_TPU_ALLOW_TOKENLESS_BIND=1 to accept the risk on "
            "a trusted network.")
    log.warning(
        "%s binding %s without %s (RLA_TPU_ALLOW_TOKENLESS_BIND=1): any "
        "host that can reach this port can execute code as this user",
        what, bind, TOKEN_ENV)


def parse_agent_spec(spec: str) -> Tuple[str, Optional[int]]:
    """``"host:port*3"`` -> ``("host:port", 3)``; bare address -> count None
    (count decided by the balanced split)."""
    addr, star, count = spec.partition("*")
    return addr.strip(), int(count) if star else None


def queue_bind_for_agents(agents) -> Optional[str]:
    """Bind address a driver-side QueueServer needs so these agents'
    workers can reach it: ``None`` (loopback) when every agent is on
    this host's loopback, else ``"0.0.0.0"``.  Keeping single-machine
    agent setups on loopback means the tokenless-wide-bind refusal in
    QueueServer only ever triggers for genuinely remote workers."""
    if not agents:
        return None
    for spec in agents:
        if not is_loopback(parse_agent_spec(spec)[0].rsplit(":", 1)[0]):
            return "0.0.0.0"
    return None


def assign_agents(agents: Sequence[str], num_workers: int) -> List[str]:
    """Contiguous block assignment: worker i's agent.  Blocks keep each
    host's workers adjacent so global rank order groups by host (the
    local-rank census stays meaningful, reference: ray_ddp.py:132-143).

    Layouts need not be even (the reference places actors wherever
    resources exist, ray_ddp.py:92-97): a balanced split gives the first
    ``num_workers % n_agents`` hosts one extra worker (3 over 2 -> 2+1),
    and explicit per-host capacities can be pinned with ``host:port*N``
    specs (then the counts must sum to ``num_workers``)."""
    n_agents = len(agents)
    if n_agents == 0 or num_workers < 1:
        raise ValueError("need at least one agent and one worker")
    parsed = [parse_agent_spec(a) for a in agents]
    addrs = [a for a, _ in parsed]
    counts = [c for _, c in parsed]
    if any(c is not None for c in counts):
        if any(c is None for c in counts):
            raise ValueError(
                "mix of explicit (host:port*N) and bare agent specs; "
                "give every agent a count or none")
        if any(c < 0 for c in counts):
            raise ValueError(f"negative worker count in agent specs: "
                             f"{list(agents)}")
        if sum(counts) != num_workers:
            raise ValueError(
                f"explicit agent worker counts {counts} sum to "
                f"{sum(counts)}, but num_workers={num_workers}")
    else:
        base, extra = divmod(num_workers, n_agents)
        counts = [base + (1 if i < extra else 0) for i in range(n_agents)]
    assignment: List[str] = []
    for addr, count in zip(addrs, counts):
        assignment.extend([addr] * count)
    return assignment


def coordinator_address_on(agent_address: str) -> str:
    """Pick a jax.distributed coordinator address on the given agent's
    host (rank-0 placement, reference setup_address analog,
    ray_ddp.py:162-163)."""
    conn = AgentConnection(agent_address)
    try:
        ip = conn.call("node_ip")
        port = conn.call("free_port")
        return f"{ip}:{port}"
    finally:
        conn.close()
