"""Preemption drain: turn a termination notice into a clean save-and-exit.

Spot/preemptible TPU reservations end with a SIGTERM and a short grace
window, not a crash — yet the reference (and PR 1's watchdog/elastic
layer) only knows crashes and hangs, so a preempted host counts as a
failure, burns a retry, and loses every step since the last periodic
checkpoint.  veScale (PAPERS.md) treats preemption as a first-class,
*graceful* outcome; this module is that path for this runtime:

- **PreemptionNotice**: a per-process singleton flag.  ``install()``
  hooks SIGTERM (workers install automatically when
  ``RLA_TPU_PREEMPT_GRACE_S`` is set in their env — see
  ``runtime/actors._worker_main``); a notice can also be raised
  programmatically (``request_local``) or cross-rank through a flag
  file on the shared run dir (every rank's handler writes it; every
  rank's fit loop polls it), so one rank's SIGTERM drains the whole
  SPMD job, not just the signaled process.
- **Drain contract**: the training loop polls ``requested()`` at step
  boundaries, forces an emergency checkpoint (fencing any in-flight
  async commit inside the grace budget), and raises **Preempted** — a
  typed outcome distinct from a crash (``RemoteError``/'worker died')
  and a hang (``WorkerWedged``).  ``ElasticRunner`` resumes preempted
  attempts without charging the failure budget;
  ``Trainer.fit(ckpt_path="last")`` resumes at the exact saved step.
- **Grace budget**: ``RLA_TPU_PREEMPT_GRACE_S`` seconds from notice to
  forced exit.  Worker-side, a hard-exit timer enforces it (the cloud
  yanks the host at the deadline whether or not the drain finished);
  an idle worker exits immediately on SIGTERM (nothing to drain), so
  pool shutdown/restart stays fast.

The wire shape matches ``WorkerWedged``: a ``Preempted`` raised inside a
worker crosses the pipe/agent relay as ``(name, message, traceback)`` and
is rebuilt driver-side from the marker embedded in its message.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, Optional

from ..analysis import knobs
from ..utils.logging import log

PREEMPT_GRACE_ENV = "RLA_TPU_PREEMPT_GRACE_S"
# multi-process fits run the cross-host drain consensus every N steps
# (a deterministic schedule, so the collective always has full
# participation); single-process runs check every step for free
PREEMPT_CONSENSUS_EVERY_ENV = "RLA_TPU_PREEMPT_CONSENSUS_EVERY"
DEFAULT_GRACE_S = 30.0
# exit code of a worker's hard-exit timer (grace expired mid-drain) and
# of an idle worker exiting on SIGTERM with a notice handler installed
PREEMPT_EXIT_CODE = 45
FLAG_FILENAME = ".rla_preempt_notice"


def grace_from_env() -> Optional[float]:
    """The configured grace budget, or None when preemption handling is
    not enabled (the handler stays uninstalled; SIGTERM keeps its default
    kill semantics so pool teardown is never slowed down).  A malformed
    value still ENABLES handling (the operator clearly asked for it) at
    the default budget."""
    return knobs.get_float(PREEMPT_GRACE_ENV, None,
                           malformed=DEFAULT_GRACE_S)


class Preempted(RuntimeError):
    """The run was preempted and drained cleanly: state is checkpointed
    and the job should be resumed (``fit(ckpt_path="last")``), not
    retried as a failure.  Distinct from ``RemoteError`` (worker crash)
    and ``WorkerWedged`` (hang): retry layers treat it as a
    resume-without-penalty outcome."""

    _MARKER = "| preempted="

    def __init__(self, message: str, step: Optional[int] = None,
                 ckpt_path: Optional[str] = None,
                 info: Optional[Dict[str, Any]] = None):
        super().__init__(message)
        self.step = step
        self.ckpt_path = ckpt_path
        self.info = dict(info or {})

    @classmethod
    def at_step(cls, step: int, ckpt_path: Optional[str] = None,
                source: str = "notice") -> "Preempted":
        info = {"step": int(step), "ckpt_path": ckpt_path,
                "source": source}
        msg = (f"preemption notice ({source}): drained at step {step}"
               + (f", emergency checkpoint at {ckpt_path}" if ckpt_path
                  else ", no emergency checkpoint written")
               + f" {cls._MARKER}{json.dumps(info, sort_keys=True)}")
        return cls(msg, step=step, ckpt_path=ckpt_path, info=info)

    @classmethod
    def from_message(cls, message: str) -> "Preempted":
        """Rebuild from a message that crossed a wire as (name, str, tb),
        recovering the embedded step/checkpoint info."""
        info: Dict[str, Any] = {}
        i = message.find(cls._MARKER)
        if i >= 0:
            tail = message[i + len(cls._MARKER):].splitlines()[0]
            try:
                info = json.loads(tail)
            except ValueError:
                pass
        return cls(message, step=info.get("step"),
                   ckpt_path=info.get("ckpt_path"), info=info)


def is_preemption(exc: BaseException) -> bool:
    """Typed check that survives the worker pipe / agent relay: a
    ``Preempted`` instance, or any exception whose message carries the
    preemption marker (``RemoteError`` wraps the original as
    ``'Preempted: <message>'``)."""
    if isinstance(exc, Preempted):
        return True
    return Preempted._MARKER in str(exc)


def as_preempted(exc: BaseException) -> Preempted:
    """The typed form of any preemption-classified exception."""
    if isinstance(exc, Preempted):
        return exc
    return Preempted.from_message(str(exc))


class PreemptionNotice:
    """Per-process preemption flag + SIGTERM plumbing.

    One singleton per process (``get_notice``).  ``requested()`` is true
    once a notice arrived by signal, by ``request_local()``, or through
    the attached flag file (cross-rank propagation over the shared run
    dir).  The flag is sticky until ``clear()``.
    """

    def __init__(self):
        self._event = threading.Event()
        self._lock = threading.Lock()
        self._installed = False
        self._prev_handler = None
        self._worker_mode = False
        self._flag_dir: Optional[str] = None
        self._deadline: Optional[float] = None
        self._timer: Optional[threading.Timer] = None
        self.source: Optional[str] = None
        # dispatch-in-progress marker (worker side): an idle worker dies
        # on SIGTERM like it always did; only mid-work notices drain
        self.busy = False

    # -- state ---------------------------------------------------------- #
    def enabled(self) -> bool:
        """Preemption handling is active: a handler is installed, a grace
        budget is configured, or a notice was already raised."""
        return (self._installed or grace_from_env() is not None
                or self._event.is_set())

    def requested(self) -> bool:
        if self._event.is_set():
            return True
        path = self._flag_path()
        if path is not None and os.path.exists(path):
            # another rank's handler raised the notice on the shared dir
            self._event.set()
            if self.source is None:
                self.source = "flag-file"
            self._arm_deadline()
            return True
        return False

    def grace_s(self) -> float:
        g = grace_from_env()
        return DEFAULT_GRACE_S if g is None else g

    def remaining_s(self) -> Optional[float]:
        """Seconds left in the grace budget, or None before any notice."""
        if self._deadline is None:
            return None
        return max(0.0, self._deadline - time.monotonic())

    def _flag_path(self) -> Optional[str]:
        if self._flag_dir is None:
            return None
        return os.path.join(self._flag_dir, FLAG_FILENAME)

    def attach_flag_dir(self, directory: str) -> None:
        """Propagate notices through ``directory`` (the shared run dir):
        this process's handler writes the flag file there, and
        ``requested()`` polls it — one rank's SIGTERM reaches every rank
        without any collective."""
        self._flag_dir = directory

    def clear_stale_flag(self) -> None:
        """Remove a flag file left by a PREVIOUS drain.  A notice applies
        to the allocation that received it; resumed/fresh runs over the
        same run dir must not re-drain off the old file (one stale flag
        would otherwise preempt every later fit at its first step).
        Never clears while THIS process holds a live notice.  If another
        rank's fresh signal races this unlink, that rank still drains
        from its sticky local event and re-propagates."""
        if self._event.is_set():
            return
        path = self._flag_path()
        if path is None:
            return
        try:
            os.unlink(path)
            log.warning("cleared stale preemption flag file %s (left by "
                        "a previous drain)", path)
        except OSError:
            pass

    # -- raising a notice ----------------------------------------------- #
    def request_local(self, source: str = "manual") -> None:
        """Raise the notice in this process only (tests, schedulers that
        know the reservation is ending)."""
        first = not self._event.is_set()
        self._event.set()
        if first:
            self.source = source
            self._arm_deadline()

    def request(self, source: str = "manual") -> None:
        """Raise the notice AND write the cross-rank flag file (when a
        flag dir is attached), so every rank of the job drains."""
        self.request_local(source)
        path = self._flag_path()
        if path is None:
            return
        try:
            os.makedirs(self._flag_dir, exist_ok=True)
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump({"source": source, "pid": os.getpid(),
                           "grace_s": self.grace_s()}, f)
            os.replace(tmp, path)
        except OSError as e:
            log.warning("could not write preemption flag file %s: %s",
                        path, e)

    def _arm_deadline(self) -> None:
        if self._deadline is None:
            self._deadline = time.monotonic() + self.grace_s()
        if self._worker_mode and self._timer is None:
            # the cloud yanks the host at the deadline whether or not the
            # drain finished; mirroring that worker-side keeps a stuck
            # drain from wedging the pool (daemon: dies with the process)
            t = threading.Timer(self.grace_s(), os._exit,
                                args=(PREEMPT_EXIT_CODE,))
            t.daemon = True
            t.start()
            self._timer = t

    # -- signal plumbing ------------------------------------------------- #
    def _handle_sigterm(self, signum, frame) -> None:
        if not self.busy:
            # idle worker: nothing to drain — die like default SIGTERM so
            # shutdown/restart paths stay fast.  (Driver installs with
            # worker_mode=False and never hard-exits here.)
            if self._worker_mode:
                os._exit(PREEMPT_EXIT_CODE)
        if self._event.is_set():
            # second SIGTERM: the notice is already raised, so the sender
            # wants termination, not another drain — restore the default
            # disposition and terminate (the graceful-then-force
            # convention; keeps a drained driver killable by `kill`)
            import signal
            try:
                signal.signal(signal.SIGTERM,
                              self._prev_handler or signal.SIG_DFL)
                self._installed = False
            except ValueError:
                pass
            os.kill(os.getpid(), signum)
            return
        self.request(source=f"signal-{signum}")

    def install(self, worker_mode: bool = False,
                flag_dir: Optional[str] = None) -> bool:
        """Hook SIGTERM as a preemption notice.  Returns False (and stays
        uninstalled) outside the main thread — ``request_local`` and the
        flag file still work there."""
        import signal
        if flag_dir is not None:
            self.attach_flag_dir(flag_dir)
        if self._installed:
            self._worker_mode = self._worker_mode or worker_mode
            return True
        try:
            self._prev_handler = signal.signal(signal.SIGTERM,
                                               self._handle_sigterm)
        except ValueError:
            log.warning("preemption notice handler not installed "
                        "(not in the main thread); SIGTERM keeps default "
                        "semantics, flag-file/manual notices still work")
            return False
        self._installed = True
        self._worker_mode = worker_mode
        return True

    def uninstall(self) -> None:
        """Restore the previous SIGTERM handler (test hygiene)."""
        if not self._installed:
            return
        import signal
        try:
            signal.signal(signal.SIGTERM,
                          self._prev_handler or signal.SIG_DFL)
        except ValueError:
            pass
        self._installed = False
        self._prev_handler = None

    def clear(self) -> None:
        """Drop a raised notice (test hygiene; a real drain ends the
        process or the attempt, never reuses the notice)."""
        self._event.clear()
        self.source = None
        self._deadline = None
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None
        path = self._flag_path()
        if path is not None:
            try:
                os.unlink(path)
            except OSError:
                pass


_notice: Optional[PreemptionNotice] = None


def get_notice() -> PreemptionNotice:
    global _notice
    if _notice is None:
        _notice = PreemptionNotice()
    return _notice


def install_from_env(worker_mode: bool = False,
                     flag_dir: Optional[str] = None
                     ) -> Optional[PreemptionNotice]:
    """Install the SIGTERM notice handler iff ``RLA_TPU_PREEMPT_GRACE_S``
    is configured; returns the notice (or None when disabled).  Workers
    call this at process start (``runtime/actors._worker_main``); the
    driver's fit loop calls it with the run dir as ``flag_dir``."""
    if grace_from_env() is None:
        return None
    notice = get_notice()
    notice.install(worker_mode=worker_mode, flag_dir=flag_dir)
    return notice


def consensus_requested(local: bool) -> bool:
    """SPMD-consistent drain decision: every process must stop at the
    same step boundary, so in a multi-process world the local flag is
    max-reduced across processes (a tiny scalar all-gather, paid only
    when preemption handling is enabled).  Single process: the local
    flag IS the decision."""
    import jax
    if jax.process_count() == 1:
        return local
    import numpy as np
    from jax.experimental import multihost_utils
    flags = multihost_utils.process_allgather(
        np.asarray([1 if local else 0], np.int32))
    return bool(np.max(flags))
