"""Multi-host bootstrap: coordinator discovery + jax.distributed init.

Capability analog of the reference's process-group rendezvous
(reference: ray_lightning/ray_ddp.py:162-163 -- rank-0 actor computes a
``tcp://ip:port`` init string; :222-237 -- every worker joins the NCCL/Gloo
group).  TPU-native redesign: there is no per-gradient process group to
manage -- workers call ``jax.distributed.initialize(coordinator, N, i)``
once, PjRt forms the global device view, and XLA emits collectives from
shardings.  The ``launch_distributed`` helper reproduces the full driver
flow: pick a coordinator address, fan a trainable out over actor workers
with the right env, pump the trampoline queue, and return every rank's
result (rank-0 first -- normalizing the result-tuple inconsistency SURVEY.md
§3.2 flags between the reference's two accelerators).

Multi-MACHINE launches pass ``agents`` -- per-host `runtime.agent.HostAgent`
addresses (the reference's multi-node Ray cluster analog,
reference: README.md:57-62).  The coordinator is then picked on agent[0]'s
host (rank-0 placement, reference: ray_ddp.py:162-163), and the trampoline
queue crosses the network through a `runtime.queue.QueueServer`.
"""

from __future__ import annotations

import socket
from typing import Any, Callable, Dict, List, Optional, Sequence

from .actors import ActorPool, RemoteError
from .queue import QueueServer, TrampolineQueue, process_results


def pick_coordinator_address(port: Optional[int] = None) -> str:
    """ip:port rendezvous string (reference setup_address analog,
    ray_ddp.py:10,162-163)."""
    from .net import node_ip
    ip = node_ip()
    if port is None:
        with socket.socket() as s:
            s.bind(("", 0))
            port = s.getsockname()[1]
    return f"{ip}:{port}"


def initialize_worker(coordinator_address: str, num_processes: int,
                      process_id: int,
                      platform: Optional[str] = None,
                      cpu_devices_per_process: Optional[int] = None) -> None:
    """Run INSIDE each worker before any jax use."""
    import jax

    if platform is not None:
        jax.config.update("jax_platforms", platform)
        if platform == "cpu":
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
            if cpu_devices_per_process:
                jax.config.update("jax_num_cpu_devices",
                                  cpu_devices_per_process)
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id)


def launch_distributed(trainable: Callable[[int], Any], num_processes: int,
                       platform: Optional[str] = None,
                       cpu_devices_per_process: Optional[int] = None,
                       env: Optional[Dict[str, str]] = None,
                       init_hook: Optional[Callable[[], None]] = None,
                       queue: Optional[TrampolineQueue] = None,
                       agents: Optional[Sequence[str]] = None) -> List[Any]:
    """Fan `trainable(process_id)` over num_processes fresh processes, each
    with a jax.distributed world formed first.  Returns per-rank results,
    rank 0 first.

    ``agents``: HostAgent addresses for a multi-machine launch (one worker
    process per address slot, contiguous blocks).  With a ``queue``, every
    worker gets a session whose trampoline reaches the driver over TCP, so
    tune callbacks work unchanged through remote workers.

    The probe-then-close port pick in ``pick_coordinator_address`` has an
    inherent reuse window (another process can claim the freed port before
    rank 0's coordinator binds it); a bind failure is retried with a fresh
    port rather than surfacing as an unattributable rendezvous hang.
    """
    for attempt in range(3):
        if agents:
            from .agent import coordinator_address_on, parse_agent_spec
            coord = coordinator_address_on(parse_agent_spec(agents[0])[0])
        else:
            coord = pick_coordinator_address()

        qserver: Optional[QueueServer] = None
        queue_address: Optional[str] = None
        if queue is not None:
            qserver = QueueServer(queue)
            queue_address = qserver.address

        def worker_body(process_id: int, coord=coord,
                        queue_address=queue_address) -> Any:
            initialize_worker(coord, num_processes, process_id, platform,
                              cpu_devices_per_process)
            client = None
            if queue_address is not None:
                from . import session as session_lib
                from .queue import QueueClient
                client = QueueClient(queue_address)
                session_lib.init_session(process_id, client)
            try:
                if init_hook is not None:
                    init_hook()
                return trainable(process_id)
            finally:
                # the result travels the worker pipe while queued thunks
                # travel a separate TCP connection: without this barrier the
                # driver's final drain can run before the server enqueues
                # the last thunks, dropping tune reports (mirrors
                # _process_trial_main in tune/run.py)
                if client is not None:
                    client.flush()

        pool: Optional[ActorPool] = None
        try:
            # inside try: a partially-constructed multi-machine pool (one
            # agent down) must still tear down the workers it DID spawn
            pool = ActorPool(num_processes,
                             env_per_worker=[dict(env or {})
                                             for _ in range(num_processes)],
                             agents=agents)
            futures = pool.execute_per_worker(
                worker_body, [(i,) for i in range(num_processes)])
            return process_results(futures, queue)
        except RemoteError as e:
            pool.kill()
            bindy = any(tok in str(e).lower()
                        for tok in ("bind", "address already in use"))
            if not (bindy and attempt < 2):
                raise
        except BaseException:
            # a crashed rank leaves its peers blocked in the distributed
            # barrier; they will never drain a shutdown sentinel -- kill
            if pool is not None:
                pool.kill()
            raise
        finally:
            if qserver is not None:
                qserver.close()
            if pool is not None:
                pool.shutdown()
