"""Multi-host bootstrap: coordinator discovery + jax.distributed init.

Capability analog of the reference's process-group rendezvous
(reference: ray_lightning/ray_ddp.py:162-163 -- rank-0 actor computes a
``tcp://ip:port`` init string; :222-237 -- every worker joins the NCCL/Gloo
group).  TPU-native redesign: there is no per-gradient process group to
manage -- workers call ``jax.distributed.initialize(coordinator, N, i)``
once, PjRt forms the global device view, and XLA emits collectives from
shardings.  The ``launch_distributed`` helper reproduces the full driver
flow: pick a coordinator address, fan a trainable out over actor workers
with the right env, pump the trampoline queue, and return every rank's
result (rank-0 first -- normalizing the result-tuple inconsistency SURVEY.md
§3.2 flags between the reference's two accelerators).

Multi-MACHINE launches pass ``agents`` -- per-host `runtime.agent.HostAgent`
addresses (the reference's multi-node Ray cluster analog,
reference: README.md:57-62).  The coordinator is then picked on agent[0]'s
host (rank-0 placement, reference: ray_ddp.py:162-163), and the trampoline
queue crosses the network through a `runtime.queue.QueueServer`.
"""

from __future__ import annotations

import os
import socket
from typing import Any, Callable, Dict, List, Optional, Sequence

from .actors import ActorPool, RemoteError
from .queue import QueueServer, TrampolineQueue, process_results


def pick_coordinator_address(port: Optional[int] = None) -> str:
    """ip:port rendezvous string (reference setup_address analog,
    ray_ddp.py:10,162-163)."""
    from .net import node_ip
    ip = node_ip()
    if port is None:
        with socket.socket() as s:
            s.bind(("", 0))
            port = s.getsockname()[1]
    return f"{ip}:{port}"


def initialize_worker(coordinator_address: str, num_processes: int,
                      process_id: int,
                      platform: Optional[str] = None,
                      cpu_devices_per_process: Optional[int] = None) -> None:
    """Run INSIDE each worker before any jax use."""
    import jax

    if platform is not None:
        jax.config.update("jax_platforms", platform)
        if platform == "cpu":
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
            if cpu_devices_per_process:
                try:
                    jax.config.update("jax_num_cpu_devices",
                                      cpu_devices_per_process)
                except AttributeError:
                    # pre-0.5 jax: the XLA flag is the only spelling; this
                    # runs before the worker's backend initializes, so the
                    # env route still takes effect
                    flags = os.environ.get("XLA_FLAGS", "")
                    if "xla_force_host_platform_device_count" not in flags:
                        os.environ["XLA_FLAGS"] = (
                            flags + " --xla_force_host_platform_device_"
                            f"count={cpu_devices_per_process}").strip()
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id)


def _nested_query_handler() -> Optional[Callable[[str, Any], Any]]:
    """Query handler for a fit-level QueueServer: workers inside THIS fit
    may poll tune state ("should_stop", synchronous "report"/"checkpoint")
    that lives one level up -- with the fit nested in a tune process
    trial, the decision is on the TUNE driver, reachable through this
    process's own session QueueClient.  Forwards those queries upward,
    re-stamping the inner worker's fit rank with this process's trial
    rank; answers directly when a tune trial session lives right here
    (sequential thread-executor trials).  Returns None (no handler) when
    there is nothing to answer from this process."""
    def handler(name: str, payload: Any) -> Any:
        try:
            from ..tune import run as tune_run
            s = tune_run._current_session()
        except Exception:
            s = None
        if s is not None:
            # one dispatch shared with the tune driver's own QueueServer;
            # inner fit ranks all resolve to THIS process's trial session
            return tune_run.dispatch_trial_query(name, payload,
                                                 lambda _rank: s)
        from . import session as session_lib
        if not session_lib.session_exists():
            return None
        sess = session_lib.get_session()
        q = getattr(sess, "_queue", None)
        if not hasattr(q, "query"):
            return None
        if name in ("report", "checkpoint"):
            return q.query(name, (sess.rank,) + tuple(payload[1:]))
        return q.query(name, sess.rank)
    return handler


# Ship-once store: content-keyed pickled blobs written to the worker
# HOST's tmpdir (one copy per machine, shared by every worker process on
# it), namespaced per world.  Resolution unpickles a FRESH object per use
# -- runs mutate loaders (sampler injection etc.), so caching live
# objects would leak one run's mutations into the next.


def _ship_dir(ns: str) -> str:
    import tempfile
    return os.path.join(tempfile.gettempdir(), f"rla_ship_{ns}")


class ShippedRef:
    """Handle to a payload cached on every host of a DistributedWorld
    (see ``DistributedWorld.ship_value``)."""

    __slots__ = ("ns", "key")

    def __init__(self, ns: str, key: str):
        self.ns = ns
        self.key = key


def _store_shipped(ns: str, key: str, blob: bytes) -> None:
    d = _ship_dir(ns)
    os.makedirs(d, exist_ok=True)
    tmp = os.path.join(d, f".{key}.tmp.{os.getpid()}")
    with open(tmp, "wb") as f:
        f.write(blob)
    os.replace(tmp, os.path.join(d, key))  # atomic: readers see all or none


def _cleanup_shipped(ns: str) -> None:
    import shutil
    shutil.rmtree(_ship_dir(ns), ignore_errors=True)


def resolve_shipped(obj):
    """Materialize a ShippedRef from this host's store (fresh copy);
    pass anything else through."""
    if isinstance(obj, ShippedRef):
        import cloudpickle
        path = os.path.join(_ship_dir(obj.ns), obj.key)
        try:
            with open(path, "rb") as f:
                return cloudpickle.loads(f.read())
        except FileNotFoundError:
            raise KeyError(
                f"shipped payload {obj.key[:12]} not cached on this host "
                "(world respawned without re-shipping?)") from None
    return obj


def _run_world_body(process_id: int, trainable, queue_address, init_hook):
    """One entry-point run inside a (persistent) worker: fresh session
    bound to this run's queue, trainable, flush barrier."""
    from . import session as session_lib

    # persistent workers run many bodies; each run binds a fresh session
    # to ITS driver queue (and a queue-less run must not inherit a stale
    # client from the previous one)
    session_lib.shutdown_session()
    client = None
    if queue_address is not None:
        from .queue import QueueClient
        client = QueueClient(queue_address)
        session_lib.init_session(process_id, client)
    try:
        if init_hook is not None:
            init_hook()
        return trainable(process_id)
    finally:
        # the result travels the worker pipe while queued thunks travel a
        # separate TCP connection: without this barrier the driver's final
        # drain can run before the server enqueues the last thunks,
        # dropping tune reports (mirrors _process_trial_main in
        # tune/run.py).  A dead driver/queue here must not mask the body's
        # real exception (e.g. a crashed peer already tore the server
        # down).
        if client is not None:
            try:
                client.flush()
            except (ConnectionError, OSError):
                pass
            client.shutdown()


class DistributedWorld:
    """Persistent fan-out world: spawned worker processes with a formed
    ``jax.distributed`` world, reusable across entry points
    (fit -> validate -> test -> predict) without respawning workers,
    re-forming the world, or recompiling from a cold runtime.

    The reference keeps its Ray actors alive for the accelerator's whole
    ``setup()`` -> ``teardown()`` span and routes every stage through them
    (reference: ray_lightning/ray_ddp.py:99-121); this is that lifecycle
    for agent workers.  Construction spawns the pool and forms the world
    (so an unreachable agent fails HERE, before any driver state is
    mutated); ``run`` executes one trainable over the live world; a failed
    run poisons the collectives, so the world kills itself and ``alive``
    turns False.
    """

    def __init__(self, num_processes: int,
                 platform: Optional[str] = None,
                 cpu_devices_per_process: Optional[int] = None,
                 env: Optional[Dict[str, str]] = None,
                 agents: Optional[Sequence[str]] = None):
        self.num_processes = num_processes
        self.agents = list(agents) if agents else None
        self.spec = (num_processes, platform, cpu_devices_per_process,
                     tuple(sorted((env or {}).items())),
                     tuple(self.agents or ()))
        self.pool: Optional[ActorPool] = None
        # ship-once bookkeeping: content digests already cached on every
        # HOST of this world (per-world tmpdir namespace), plus counters
        # tests/users can read
        import secrets
        self._ship_ns = secrets.token_hex(8)
        self._shipped: set = set()
        self.ship_stats = {"sent": 0, "reused": 0}
        # the probe-then-close port pick has an inherent reuse window
        # (another process can claim the freed port before rank 0's
        # coordinator binds it); bind failures retry with a fresh port
        # rather than surfacing as an unattributable rendezvous hang
        for attempt in range(3):
            if self.agents:
                from .agent import coordinator_address_on, parse_agent_spec
                coord = coordinator_address_on(
                    parse_agent_spec(self.agents[0])[0])
            else:
                coord = pick_coordinator_address()
            pool: Optional[ActorPool] = None
            try:
                # inside try: a partially-constructed multi-machine pool
                # (one agent down) must still tear down the workers it DID
                # spawn
                pool = ActorPool(num_processes,
                                 env_per_worker=[dict(env or {})
                                                 for _ in
                                                 range(num_processes)],
                                 agents=self.agents)
                futures = pool.execute_per_worker(
                    initialize_worker,
                    [(coord, num_processes, i, platform,
                      cpu_devices_per_process)
                     for i in range(num_processes)])
                for f in futures:
                    f.result()
                self.pool = pool
                # a world left open at interpreter exit must die BEFORE
                # multiprocessing's exit handler joins children:
                # jax.distributed workers catch SIGTERM (preemption
                # notifier), so the default terminate-and-join hangs.
                # The closure holds the POOL strongly -- a world dropped
                # without shutdown() (e.g. a GC'd trainer) still gets its
                # worker processes killed at exit
                import atexit

                def _reap(p=pool):
                    try:
                        p.kill()
                    except Exception:
                        pass  # agents already gone; processes die with us

                self._atexit_cb = _reap
                atexit.register(_reap)
                return
            except RemoteError as e:
                if pool is None:
                    raise  # pool construction itself failed: no retry
                pool.kill()
                pool.shutdown()
                bindy = any(tok in str(e).lower()
                            for tok in ("bind", "address already in use"))
                if not (bindy and attempt < 2):
                    raise
            except BaseException:
                if pool is not None:
                    pool.kill()
                    pool.shutdown()
                raise

    def alive(self) -> bool:
        return (self.pool is not None
                and all(w.is_alive for w in self.pool.workers))

    def _one_worker_per_host(self) -> List[Any]:
        """One representative worker per distinct placement: the store is
        host-shared (tmpdir), so the blob crosses the wire once per
        machine, not once per worker slot."""
        seen = set()
        reps = []
        for w in self.pool.workers:
            addr = getattr(w, "address", None)  # None = local subprocess
            host = None if addr is None else addr.split(":")[0]
            if host not in seen:
                seen.add(host)
                reps.append(w)
        return reps

    def ship_value(self, obj):
        """Cache ``obj`` on every HOST of this world ONCE,
        content-addressed; returns a ShippedRef later runs reference
        instead of re-shipping the bytes (on real TPU hosts a dataset
        crossing the wire per entry point is the dominant fit->test cost;
        the reference ships its trainer to the object store once,
        ray_ddp.py:169).  Workers unpickle a FRESH copy per resolve, so
        one run's mutations never leak into the next.  ``None`` passes
        through un-shipped."""
        if obj is None:
            return None
        import hashlib

        import cloudpickle
        blob = cloudpickle.dumps(obj)
        key = hashlib.sha256(blob).hexdigest()
        if key in self._shipped:
            self.ship_stats["reused"] += 1
            return ShippedRef(self._ship_ns, key)
        for f in [w.execute(_store_shipped, self._ship_ns, key, blob)
                  for w in self._one_worker_per_host()]:
            f.result()
        self._shipped.add(key)
        self.ship_stats["sent"] += 1
        return ShippedRef(self._ship_ns, key)

    def run(self, trainable: Callable[[int], Any],
            queue: Optional[TrampolineQueue] = None,
            init_hook: Optional[Callable[[], None]] = None,
            deadline_s: Optional[float] = None,
            wedge_timeout_s: Optional[float] = None) -> List[Any]:
        """Fan ``trainable(process_id)`` over the live world.  Returns
        per-rank results, rank 0 first.  With a ``queue``, every worker
        gets a session whose trampoline reaches this driver over TCP, so
        tune callbacks work unchanged through remote workers.

        Hang-aware supervision (`runtime.watchdog`) runs when
        ``deadline_s`` (per-attempt budget for this run's dispatch),
        ``wedge_timeout_s`` (stale-heartbeat threshold), or the
        ``RLA_TPU_WEDGE_TIMEOUT_S`` env is set: a rank that stops making
        progress is reaped and fails the run with ``WorkerWedged``
        (retryable) instead of hanging the driver forever.  A padded
        driver-side ``process_results`` deadline backstops the case where
        the supervision channel itself is broken."""
        # liveness was checked by the caller (_acquire_world) moments ago;
        # re-probing here would cost another N agent round-trips per entry
        # point, and a racing death still surfaces as a dispatch failure
        if self.pool is None:
            raise RuntimeError("DistributedWorld is not alive (a prior run "
                               "failed or it was shut down)")
        qserver: Optional[QueueServer] = None
        queue_address: Optional[str] = None
        if queue is not None:
            # loopback unless workers live on other machines; the query
            # handler lets worker-side stop-polls/reports cross THIS fit
            # and reach an enclosing tune driver (nested process trials)
            from .agent import queue_bind_for_agents
            qserver = QueueServer(queue,
                                  bind=queue_bind_for_agents(self.agents),
                                  query_handler=_nested_query_handler())
            queue_address = qserver.address
        from .watchdog import Watchdog, wedge_timeout_from_env
        if wedge_timeout_s is None:
            wedge_timeout_s = wedge_timeout_from_env()
        watchdog: Optional[Watchdog] = None
        self.last_stall: List[Dict[str, Any]] = []
        try:
            futures = self.pool.execute_per_worker(
                _run_world_body,
                [(i, trainable, queue_address, init_hook)
                 for i in range(self.num_processes)])
            if deadline_s is not None or wedge_timeout_s is not None:
                watchdog = Watchdog(
                    self.pool, wedge_timeout_s=wedge_timeout_s,
                    dispatch_deadline_s=deadline_s).start()
            # backstop deadline, padded past the watchdog's trigger so
            # the typed WorkerWedged (with diagnosis) wins when possible
            hard_deadline = (deadline_s + max(30.0, wedge_timeout_s or 0.0)
                             if deadline_s is not None else None)
            return process_results(futures, queue,
                                   deadline_s=hard_deadline)
        except BaseException as e:
            # a crashed rank leaves its peers blocked in the distributed
            # barrier; they will never drain a shutdown sentinel -- kill
            # the whole world (callers respawn a fresh one)
            self.kill()
            from .preemption import as_preempted, is_preemption
            if is_preemption(e):
                # a graceful drain crossed the worker pipe as a generic
                # RemoteError; hand the caller the TYPED outcome (step +
                # emergency checkpoint path) so fit(ckpt_path="last")
                # resumes instead of counting a failure
                raise as_preempted(e) from e
            raise
        finally:
            if watchdog is not None:
                watchdog.stop()
                self.last_stall = list(watchdog.reaped)
            if qserver is not None:
                qserver.close()

    def _drop_atexit(self) -> None:
        cb = getattr(self, "_atexit_cb", None)
        if cb is not None:
            import atexit
            atexit.unregister(cb)
            self._atexit_cb = None

    def kill(self) -> None:
        self._drop_atexit()
        if self.pool is not None:
            self.pool.kill()
            self.pool = None

    def shutdown(self) -> None:
        self._drop_atexit()
        if self.pool is not None:
            if self._shipped:
                # best-effort: clear the per-world host caches while the
                # workers are still alive (kill() paths leave the files
                # to the OS tmp reaper)
                try:
                    for f in [w.execute(_cleanup_shipped, self._ship_ns)
                              for w in self._one_worker_per_host()]:
                        f.result(timeout=10)
                except Exception:
                    pass
            self.pool.shutdown()
            self.pool = None

    def __enter__(self) -> "DistributedWorld":
        return self

    def __exit__(self, *exc) -> None:
        self.shutdown()


def launch_distributed(trainable: Callable[[int], Any], num_processes: int,
                       platform: Optional[str] = None,
                       cpu_devices_per_process: Optional[int] = None,
                       env: Optional[Dict[str, str]] = None,
                       init_hook: Optional[Callable[[], None]] = None,
                       queue: Optional[TrampolineQueue] = None,
                       agents: Optional[Sequence[str]] = None) -> List[Any]:
    """Fan `trainable(process_id)` over num_processes fresh processes, each
    with a jax.distributed world formed first.  Returns per-rank results,
    rank 0 first.  One-shot wrapper over ``DistributedWorld`` (the
    persistent form the Trainer uses across entry points).

    ``agents``: HostAgent addresses for a multi-machine launch (one worker
    process per address slot, contiguous blocks).  With a ``queue``, every
    worker gets a session whose trampoline reaches the driver over TCP, so
    tune callbacks work unchanged through remote workers.
    """
    world = DistributedWorld(num_processes, platform,
                             cpu_devices_per_process, env, agents)
    try:
        return world.run(trainable, queue=queue, init_hook=init_hook)
    finally:
        world.shutdown()
