"""Elastic execution: retry fan-out work across worker failures.

The reference explicitly punts on fault tolerance — actors are created with
no restart policy, a crash surfaces as a raised exception from the driver
poll loop, and the README defers elasticity to RaySGD (SURVEY.md §5.3;
reference: ray_lightning/ray_ddp.py:119, util.py:103, README.md:111).
This module is the recovery layer that design left out, built on the two
primitives the runtime provides:

- failure *detection*: a dead worker fails its futures with 'worker died'
  (runtime/actors.py collector) and shows dead in ``pool.health_check()``;
- worker *restart*: ``pool.restart_dead()`` respawns crashed ranks with
  their rank/env intact.

Recovery is checkpoint-based, matching the framework's training semantics:
a collective (SPMD) step cannot survive losing a participant mid-step, so
on failure the runner restarts dead ranks and re-dispatches the whole
attempt; the dispatched function is expected to resume from the latest
checkpoint (see utils/checkpoint.latest_checkpoint and
Trainer.fit(ckpt_path="last")).
"""

from __future__ import annotations

import time
from typing import Any, Callable, List, Optional, Sequence

from ..utils.logging import log
from .actors import ActorPool
from .queue import TrampolineQueue, process_results


class ElasticRunner:
    """Run per-worker callables with restart-and-resume on failure."""

    def __init__(self, pool: ActorPool, max_failures: int = 3,
                 backoff_s: float = 0.0,
                 on_failure: Optional[Callable[[int, BaseException], None]]
                 = None,
                 init_hook: Optional[Callable[[], None]] = None):
        """``max_failures``: attempts beyond the first before giving up.
        ``on_failure(attempt, exc)``: observer hook per failed attempt.
        ``init_hook``: re-run on restarted workers before re-dispatch
        (parity with the accelerator's per-worker init_hook,
        reference: ray_lightning/ray_ddp.py:106-107)."""
        self.pool = pool
        self.max_failures = max_failures
        self.backoff_s = backoff_s
        self.on_failure = on_failure
        self.init_hook = init_hook
        self.attempts_used = 0

    def run(self, fn: Callable,
            args_per_worker: Optional[Callable[[int], Sequence[tuple]]]
            = None,
            queue: Optional[TrampolineQueue] = None) -> List[Any]:
        """Dispatch ``fn`` to every worker until one attempt fully succeeds.

        ``args_per_worker(attempt)`` builds the per-rank argument tuples for
        a given attempt — resume state (e.g. the latest checkpoint path)
        belongs there.  ``fn`` must be re-runnable: each retry re-executes
        the whole attempt on all ranks (collective steps cannot continue
        with a hole in the mesh)."""
        last_exc: Optional[BaseException] = None
        for attempt in range(self.max_failures + 1):
            self.attempts_used = attempt + 1
            if attempt > 0:
                if self.backoff_s:
                    time.sleep(self.backoff_s * attempt)
                # restart every rank, not just dead ones: survivors of a
                # broken collective are alive-but-wedged and would never
                # dequeue the retry
                restarted = self.pool.restart_all(init_hook=self.init_hook)
                log.warning("elastic attempt %d/%d (restarted ranks %s)",
                            attempt + 1, self.max_failures + 1, restarted)
            try:
                if args_per_worker is not None:
                    futures = self.pool.execute_per_worker(
                        fn, args_per_worker(attempt))
                else:
                    futures = self.pool.execute_all(fn)
                return process_results(futures, queue)
            except BaseException as e:  # noqa: BLE001 — resurfaced below
                last_exc = e
                if self.on_failure is not None:
                    self.on_failure(attempt, e)
                if attempt == self.max_failures:
                    break
        raise RuntimeError(
            f"elastic run failed after {self.max_failures + 1} attempts"
        ) from last_exc
