"""Elastic execution: retry fan-out work across worker failures.

The reference explicitly punts on fault tolerance — actors are created with
no restart policy, a crash surfaces as a raised exception from the driver
poll loop, and the README defers elasticity to RaySGD (SURVEY.md §5.3;
reference: ray_lightning/ray_ddp.py:119, util.py:103, README.md:111).
This module is the recovery layer that design left out, built on the
primitives the runtime provides:

- failure *detection*: a dead worker fails its futures with 'worker died'
  (runtime/actors.py collector) and shows dead in ``pool.health_check()``;
  a HUNG worker -- alive but stopped making progress -- is detected by a
  per-attempt `runtime.watchdog.Watchdog` (stale heartbeat or overrun
  dispatch deadline), reaped, and fails its futures with ``WorkerWedged``,
  so wedges retry exactly like crashes instead of hanging the driver;
- worker *restart*: ``pool.restart_dead()`` respawns crashed ranks with
  their rank/env intact; retries use ``pool.restart_all()`` because the
  wedge/crash survivors of a broken collective are alive-but-stuck and
  must be cleared deliberately, not left to hang the re-dispatch;
- graceful *preemption* (`runtime.preemption`): a spot/termination notice
  drains into an emergency checkpoint and a typed ``Preempted`` — the
  runner resumes it WITHOUT charging the failure budget (a clean drain is
  not a failure), bounded separately by ``max_preemptions``;
- elastic *scale-down*: when a restarted rank never comes back (host
  gone; ``pool.find_lost`` probe fails — chaos kind ``lost@rankN``), a
  runner with ``allow_shrink=True`` drops the rank and re-dispatches at
  the surviving world size (``args_per_worker`` receives it), the
  veScale-style alternative to burning every retry on an unrecoverable
  host;
- numeric *rewind* (`runtime.guardian`): a typed ``NumericAnomaly`` from
  a tripped in-step guard resumes WITHOUT charging the failure budget
  (the fit body already rewound to a verified checkpoint and quarantined
  the blamed data window), bounded separately by ``max_rewinds``; SDC
  blame with a named suspect rank demotes that rank via elastic shrink.

Recovery is checkpoint-based, matching the framework's training semantics:
a collective (SPMD) step cannot survive losing a participant mid-step, so
on failure the runner restarts dead ranks and re-dispatches the whole
attempt; the dispatched function is expected to resume from the latest
*verified* checkpoint (see utils/checkpoint.latest_checkpoint and
Trainer.fit(ckpt_path="last")).
"""

from __future__ import annotations

import inspect
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

from ..analysis import knobs
from ..telemetry import perf as perf_lib
from ..telemetry import recorder as telemetry
# the backoff schedule lives in utils/backoff.py (shared with the serve
# tier's retry/revival loops); re-exported here so existing importers
# (tests, downstream orchestration) keep working
from ..utils.backoff import DEFAULT_BACKOFF_CAP_S, backoff_delay_s
from ..utils.logging import log
from . import preemption as preempt_lib
from .actors import ActorPool
from .queue import TrampolineQueue, process_results
from .watchdog import Watchdog, WorkerWedged, wedge_timeout_from_env

BACKOFF_BASE_ENV = "RLA_TPU_ELASTIC_BACKOFF_S"
BACKOFF_CAP_ENV = "RLA_TPU_ELASTIC_BACKOFF_CAP_S"

__all__ = ["ElasticResizeError", "ElasticRunner", "backoff_delay_s",
           "DEFAULT_BACKOFF_CAP_S"]


class ElasticResizeError(ValueError):
    """Resuming at a different world size is genuinely impossible: some
    divisibility contract (per-process batch over the new data-parallel
    size) breaks.  Typed so orchestration can tell "re-shard and go" from
    "this run cannot continue at this size"."""


class ElasticRunner:
    """Run per-worker callables with restart-and-resume on failure."""

    def __init__(self, pool: ActorPool, max_failures: int = 3,
                 backoff_s: float = 0.0,
                 on_failure: Optional[Callable[[int, BaseException], None]]
                 = None,
                 init_hook: Optional[Callable[[], None]] = None,
                 wedge_timeout_s: Optional[float] = None,
                 dispatch_deadline_s: Optional[float] = None,
                 watchdog_poll_s: Optional[float] = None,
                 allow_shrink: bool = False,
                 resize_in_memory: bool = False,
                 min_workers: int = 1,
                 probe_timeout_s: float = 120.0,
                 max_preemptions: int = 3,
                 max_rewinds: int = 2,
                 backoff_cap_s: float = DEFAULT_BACKOFF_CAP_S,
                 report_dir: Optional[str] = None):
        """``max_failures``: attempts beyond the first before giving up.
        ``on_failure(attempt, exc)``: observer hook per failed attempt.
        ``init_hook``: re-run on restarted workers before re-dispatch
        (parity with the accelerator's per-worker init_hook,
        reference: ray_lightning/ray_ddp.py:106-107).

        ``backoff_s`` is the BASE of an exponential schedule with
        half-jitter, capped at ``backoff_cap_s`` (envs
        ``RLA_TPU_ELASTIC_BACKOFF_S`` / ``RLA_TPU_ELASTIC_BACKOFF_CAP_S``
        override both); 0 disables sleeping between retries.

        ``allow_shrink``: when a restarted rank fails its liveness probe
        (host permanently gone), drop it and continue at the surviving
        world size instead of failing every retry — requires
        ``args_per_worker`` to accept ``(attempt, world_size)`` so the
        dispatched work re-partitions.  ``min_workers`` floors the
        shrink.  ``max_preemptions`` bounds graceful-preemption resumes
        (which do NOT consume the failure budget).  ``max_rewinds``
        separately bounds numeric-guard rewinds (``NumericAnomaly`` from
        ``runtime/guardian.py``): a tripped guard has already rewound
        state to a verified checkpoint and quarantined the blamed data
        window, so the resume is cheap and does not consume the failure
        budget either — but a guard that keeps tripping is a diverged
        run, and the separate budget makes it terminal instead of an
        infinite rewind loop.  A ``data``-blamed trip that recurs at the
        SAME step after its window was quarantined is terminal
        immediately (the quarantine demonstrably did not clear it), and
        an ``sdc``-blamed trip with a named suspect rank demotes that
        rank via elastic shrink when ``allow_shrink`` permits.

        ``resize_in_memory``: survivors of a failed attempt KEEP their
        process (and its live in-memory state — the dispatched body is
        expected to retain state across dispatches and redistribute it,
        e.g. via ``Trainer.resize_in_memory`` + ``fit(ckpt_path=
        'live')``) instead of the blanket ``restart_all``; only dead
        ranks respawn, ``find_lost(classify=True)`` distinguishes a
        revivable host from a gone one, and previously dropped ranks
        are re-placed via ``pool.revive`` when their host answers again
        (elastic GROW).  The between-attempt downtime is accounted as
        the goodput ledger's ``resize`` phase (priced against
        ``restart``/``ckpt`` in ``goodput_fraction``) and bracketed by
        ``resize_begin``/``resize_end`` telemetry.  Bodies keep the
        checkpoint chain as their fallback — when no surviving rank
        retains usable state, an attempt resumes from disk exactly as
        without this flag, charging the failure budget once.

        Hang-aware supervision runs when any of ``wedge_timeout_s``
        (stale-heartbeat threshold), ``dispatch_deadline_s`` (per-attempt
        budget for the dispatched fn), or the ``RLA_TPU_WEDGE_TIMEOUT_S``
        env is set: each attempt is watched by a `runtime.watchdog
        .Watchdog`, wedged ranks are reaped, and the attempt fails
        retryably with ``WorkerWedged`` instead of hanging forever.

        ``report_dir``: when set, every failed attempt (and a terminal
        preemption — driver hand-up or exhausted ``max_preemptions``
        budget) writes a ``run_report.json`` postmortem
        there — per-rank flight-recorder timelines, the failure, the
        wedge diagnosis (telemetry/registry.py); the newest failure
        wins the file."""
        self.pool = pool
        self.report_dir = report_dir
        self.max_failures = max_failures
        self.backoff_s = knobs.get_float(BACKOFF_BASE_ENV, backoff_s)
        self.backoff_cap_s = knobs.get_float(BACKOFF_CAP_ENV,
                                             backoff_cap_s)
        self.on_failure = on_failure
        self.init_hook = init_hook
        self.wedge_timeout_s = wedge_timeout_s
        self.dispatch_deadline_s = dispatch_deadline_s
        self.watchdog_poll_s = watchdog_poll_s
        self.allow_shrink = allow_shrink
        self.min_workers = max(1, min_workers)
        self.probe_timeout_s = probe_timeout_s
        self.max_preemptions = max_preemptions
        self.max_rewinds = max_rewinds
        self.attempts_used = 0
        # wedge diagnosis records accumulated across attempts (one dict
        # per reaped rank, runtime/watchdog.py death-record shape)
        self.wedge_events: List[Dict[str, Any]] = []
        # graceful preemption drains resumed (typed Preempted, one per
        # resumed attempt) and scale-down records ({"dropped": ranks,
        # "world_size": new size})
        self.preempt_events: List[preempt_lib.Preempted] = []
        self.shrink_events: List[Dict[str, Any]] = []
        # numeric-guard rewinds resumed (the tripped NumericAnomaly's
        # structured diagnosis, one dict per rewound attempt)
        self.anomaly_events: List[Dict[str, Any]] = []
        self.resize_in_memory = resize_in_memory
        # elastic GROW records under resize_in_memory ({"revived": ranks,
        # "world_size": new size, "attempt": n}): a previously dropped
        # rank whose host answers probes again is re-placed in the pool
        self.grow_events: List[Dict[str, Any]] = []
        # driver-side notice: installed when RLA_TPU_PREEMPT_GRACE_S is
        # configured, so a driver SIGTERM ends the retry loop instead of
        # respawning workers on a host that is going away
        self._notice = preempt_lib.install_from_env()
        # goodput ledger (telemetry/perf.py): the runner accounts the
        # overheads only the driver can see (restart/boot + backoff,
        # wedge-detection wait); feed the attempts' interior split via
        # goodput.absorb_timeline / absorb_profiler and read one
        # goodput fraction per run from goodput.snapshot()
        self.goodput = perf_lib.GoodputLedger()

    def _write_report(self, exc: BaseException) -> None:
        """Postmortem artifact for a failed/preempted attempt (no-op
        without ``report_dir``): driver timeline + every rank's spill
        tail + the typed failure, via telemetry.write_run_report.
        Best-effort by contract — it must never mask ``exc``."""
        if not self.report_dir:
            return
        try:
            from ..telemetry import registry as treg
            stall = getattr(exc, "diagnosis", None) or (
                self.wedge_events[-1] if self.wedge_events else None)
            treg.write_run_report(
                self.report_dir, error=exc,
                rank_events=treg.gather_worker_tails(self.pool.workers),
                stall_diagnosis=stall,
                extra={"attempts_used": self.attempts_used,
                       "world_size": len(self.pool)})
        except BaseException as e:
            log.warning("elastic run-report write failed: %s", e)

    def _supervised(self) -> bool:
        return (self.wedge_timeout_s is not None
                or self.dispatch_deadline_s is not None
                or wedge_timeout_from_env() is not None)

    def _collective_mismatch(self, exc: BaseException):
        """The SPMD sanitizer's verdict on a failed attempt (no-op
        unless RLA_TPU_SPMD_SANITIZER + a telemetry dir are configured):
        a typed CollectiveMismatch when the rank spills diverge, else
        None.  Only HANG-shaped failures (WorkerWedged / TimeoutError)
        are decoded — a crashed rank's spill is legitimately truncated
        mid-trace, and reading that as divergence would turn every
        retryable crash into a terminal mismatch.  Best-effort —
        diagnosing must never mask the failure."""
        if not isinstance(exc, (WorkerWedged, TimeoutError)):
            return None
        try:
            from ..testing import spmd_sanitizer
            return spmd_sanitizer.check_world_collectives(
                raise_on_mismatch=False)
        except Exception:
            return None

    def _numeric_anomaly(self, exc: BaseException):
        """The typed numeric-guard verdict on a failed attempt, or None.
        Wire-registered (``runtime/wire.py``), so an anomaly raised
        inside a worker arrives here as a real ``NumericAnomaly`` with
        its blame/suspect/step postmortem intact."""
        try:
            from .guardian import NumericAnomaly
        except Exception:
            return None
        if isinstance(exc, NumericAnomaly):
            return exc
        # process_results can wrap the first failed future's exception;
        # a one-level cause walk keeps the typed verdict reachable
        cause = getattr(exc, "__cause__", None)
        if isinstance(cause, NumericAnomaly):
            return cause
        return None

    def _demote_suspect(self, anomaly: Any, attempt: int) -> None:
        """SDC blame names a rank producing divergent numerics on
        identical inputs — a hardware suspect.  Under ``allow_shrink``
        the named rank is demoted via the same elastic-shrink path as a
        lost host (floored by ``min_workers``); without shrink the rank
        stays and the rewind alone is the recovery."""
        suspect = anomaly.suspect_rank
        if (not self.allow_shrink or suspect is None
                or int(suspect) < 0):
            return
        suspect = int(suspect)
        if not any(w.rank == suspect for w in self.pool.workers):
            return
        if len(self.pool) - 1 < self.min_workers:
            log.warning(
                "elastic SDC demotion skipped: dropping rank %d would "
                "leave %d < min_workers=%d", suspect,
                len(self.pool) - 1, self.min_workers)
            return
        dropped = self.pool.drop([suspect])
        event = {"dropped": dropped, "world_size": len(self.pool),
                 "attempt": attempt + 1, "blame": anomaly.blame}
        self.shrink_events.append(event)
        telemetry.emit("elastic_shrink", **event)
        log.warning("elastic SDC demotion: %s", event)

    def _reset_collectives(self) -> None:
        """Attempt-entry spill reset (same knob gating): an attempt is
        never diffed against a previous attempt's (or run's) sequences.
        Restarted workers rewrite their spill at boot install."""
        try:
            from ..testing import spmd_sanitizer
            spmd_sanitizer.reset_world_collectives()
        except Exception:
            pass

    def _build_args(self, args_per_worker, attempt: int) -> Sequence[tuple]:
        """Per-rank argument tuples; callables accepting a second
        parameter receive the CURRENT world size (required under
        ``allow_shrink`` — re-dispatch after a scale-down must
        re-partition the work)."""
        try:
            params = [
                p for p in
                inspect.signature(args_per_worker).parameters.values()
                if p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD)]
            # world-size-aware means an explicit, REQUIRED second
            # positional slot: a defaulted second param (attempt,
            # ckpt_dir=...), **opts, *args, or keyword-only extras keep
            # the legacy 1-arg call — silently overwriting a default
            # with the pool size would corrupt existing builders
            takes_world = (len(params) >= 2
                           and params[1].default is inspect.Parameter.empty)
        except (TypeError, ValueError):
            takes_world = False
        if takes_world:
            args = args_per_worker(attempt, len(self.pool))
        else:
            args = args_per_worker(attempt)
        if len(args) != len(self.pool):
            raise ValueError(
                f"args_per_worker built {len(args)} argument tuples for a "
                f"pool of {len(self.pool)} workers; under allow_shrink it "
                "must accept (attempt, world_size) and size its output to "
                "the current world")
        return args

    def _prepare_retry(self, attempt: int, failures: int) -> None:
        """Between-attempt recovery: backoff, restart every rank (clearing
        alive-but-stuck survivors of the broken collective), drop ranks
        whose host never came back (scale-down), re-run the init hook."""
        delay = backoff_delay_s(failures, self.backoff_s,
                                self.backoff_cap_s)
        if delay > 0:
            log.warning("elastic backoff %.2fs before attempt %d",
                        delay, attempt + 1)
            time.sleep(delay)
        # cleared BEFORE the restart: every respawned rank rewrites its
        # spill at boot install, so the retry diffs only its own traces
        self._reset_collectives()
        if self.resize_in_memory:
            self._prepare_retry_in_memory(attempt)
            return
        restarted = self.pool.restart_all(
            init_hook=None if self.allow_shrink else self.init_hook)
        log.warning("elastic attempt %d (restarted ranks %s)",
                    attempt + 1, restarted)
        if not self.allow_shrink:
            return
        lost = self.pool.find_lost(timeout_s=self.probe_timeout_s)
        if lost:
            survivors = len(self.pool) - len(lost)
            if survivors < self.min_workers:
                raise RuntimeError(
                    f"elastic scale-down impossible: ranks {lost} are "
                    f"gone, leaving {survivors} < min_workers="
                    f"{self.min_workers}")
            dropped = self.pool.drop(lost)
            event = {"dropped": dropped, "world_size": len(self.pool),
                     "attempt": attempt + 1}
            self.shrink_events.append(event)
            telemetry.emit("elastic_shrink", **event)
            log.warning("elastic scale-down: %s", event)
        if self.init_hook is not None:
            for f in self.pool.execute_all(self.init_hook):
                f.result()

    def _prepare_retry_in_memory(self, attempt: int) -> None:
        """The ``resize_in_memory`` between-attempt path: survivors KEEP
        their process (and whatever live state the body retained — the
        in-memory alternative to the checkpoint round-trip), so there is
        no ``restart_all``.  Order matters:

        1. GROW — previously dropped ranks whose host answers again are
           re-placed via ``pool.revive`` (elastic grow without touching
           any survivor).
        2. Dead-but-present ranks respawn in place (``restart_dead``).
        3. SHRINK — ``find_lost(classify=True)`` separates a revivable
           host (restart succeeded mid-probe) from a gone one; only the
           gone ranks are dropped, floored by ``min_workers``.
        4. ``init_hook`` runs ONLY on fresh processes (revived +
           respawned): re-running it on a survivor would wipe the live
           state this mode exists to preserve.
        """
        fresh: List[int] = []
        for rank in self.pool.dropped_ranks():
            w = self.pool.revive(rank, probe_timeout_s=self.probe_timeout_s)
            if w is not None:
                fresh.append(rank)
        if fresh:
            event = {"revived": sorted(fresh),
                     "world_size": len(self.pool),
                     "attempt": attempt + 1}
            self.grow_events.append(event)
            telemetry.emit("elastic_grow", **event)
            log.warning("elastic grow: %s", event)
        restarted = self.pool.restart_dead()
        fresh.extend(restarted)
        log.warning("elastic attempt %d (in-memory resize; respawned "
                    "ranks %s)", attempt + 1, sorted(fresh))
        if self.allow_shrink:
            verdict = self.pool.find_lost(timeout_s=self.probe_timeout_s,
                                          classify=True)
            fresh.extend(verdict["revived"])
            gone = verdict["gone"]
            if gone:
                survivors = len(self.pool) - len(gone)
                if survivors < self.min_workers:
                    raise RuntimeError(
                        f"elastic scale-down impossible: ranks {gone} "
                        f"are gone, leaving {survivors} < min_workers="
                        f"{self.min_workers}")
                dropped = self.pool.drop(gone)
                event = {"dropped": dropped,
                         "world_size": len(self.pool),
                         "attempt": attempt + 1}
                self.shrink_events.append(event)
                telemetry.emit("elastic_shrink", **event)
                log.warning("elastic scale-down: %s", event)
        if self.init_hook is not None and fresh:
            fresh_set = set(fresh)
            targets = [w for w in self.pool.workers
                       if w.rank in fresh_set]
            for f in [w.execute(self.init_hook) for w in targets]:
                f.result()

    def run(self, fn: Callable,
            args_per_worker: Optional[Callable[..., Sequence[tuple]]]
            = None,
            queue: Optional[TrampolineQueue] = None) -> List[Any]:
        """Dispatch ``fn`` to every worker until one attempt fully succeeds.

        ``args_per_worker(attempt)`` — or ``(attempt, world_size)`` when
        the work must re-partition after a scale-down — builds the
        per-rank argument tuples for a given attempt; resume state (e.g.
        the latest checkpoint path) belongs there.  ``fn`` must be
        re-runnable: each retry re-executes the whole attempt on all
        ranks (collective steps cannot continue with a hole in the
        mesh)."""
        last_exc: Optional[BaseException] = None
        attempt = 0
        failures = 0
        preemptions = 0
        rewinds = 0
        # data-blamed trip steps already quarantined once: a SECOND trip
        # at the same step means the quarantine did not clear it
        quarantined_steps: set = set()
        self.goodput.run_begin()
        # a fresh run must not inherit a previous run's (or a smaller
        # world's leftover) collective sequences
        self._reset_collectives()
        while True:
            self.attempts_used = attempt + 1
            self.goodput.note_attempt()
            telemetry.emit("elastic_attempt", attempt=attempt + 1,
                           world_size=len(self.pool))
            if attempt > 0:
                # restart every rank, not just dead ones: survivors of a
                # broken collective (and watchdog-reaped wedges' peers)
                # are alive-but-stuck and would never dequeue the retry.
                # Under resize_in_memory survivors keep their process and
                # the pause is an in-memory RESIZE, accounted and
                # bracketed as such.
                old_world = len(self.pool)
                if self.resize_in_memory:
                    telemetry.emit("resize_begin", old_world=old_world,
                                   attempt=attempt + 1)
                t_prep = time.monotonic()
                phase = "resize" if self.resize_in_memory else "restart"
                with self.goodput.measure(phase):
                    self._prepare_retry(attempt, failures)
                if self.resize_in_memory:
                    telemetry.emit(
                        "resize_end", old_world=old_world,
                        new_world=len(self.pool), attempt=attempt + 1,
                        seconds=time.monotonic() - t_prep)
            watchdog: Optional[Watchdog] = None
            # built OUTSIDE the try: a mis-sized args_per_worker is a
            # configuration error, not a retryable attempt failure
            args = (self._build_args(args_per_worker, attempt)
                    if args_per_worker is not None else None)
            try:
                if args is not None:
                    futures = self.pool.execute_per_worker(fn, args)
                else:
                    futures = self.pool.execute_all(fn)
                hard_deadline = None
                if self._supervised():
                    # per-attempt watchdog: started after dispatch,
                    # stopped before any restart touches the pool
                    watchdog = Watchdog(
                        self.pool,
                        wedge_timeout_s=self.wedge_timeout_s,
                        dispatch_deadline_s=self.dispatch_deadline_s,
                        poll_s=self.watchdog_poll_s).start()
                    if self.dispatch_deadline_s is not None:
                        # driver-side backstop, padded past the reap
                        # trigger so the typed WorkerWedged wins when the
                        # channel works -- but a heartbeat-less hang
                        # still fails the attempt (retryably) instead of
                        # blocking the driver forever
                        hard_deadline = self.dispatch_deadline_s + max(
                            30.0, watchdog.wedge_timeout_s)
                results = process_results(futures, queue,
                                          deadline_s=hard_deadline)
                self.goodput.run_end()
                return results
            except BaseException as e:  # noqa: BLE001 — resurfaced below
                last_exc = e
                if preempt_lib.is_preemption(e):
                    # a drained preemption is a RESUME, not a failure:
                    # state is checkpointed, the budget stays intact
                    preempted = preempt_lib.as_preempted(e)
                    self.goodput.note_preemption()
                    self.preempt_events.append(preempted)
                    telemetry.emit("elastic_preempt_resume",
                                   attempt=attempt + 1,
                                   step=getattr(preempted, "step", None))
                    if (self._notice is not None
                            and self._notice.requested()):
                        # the DRIVER is being preempted too: hand the
                        # typed outcome up instead of respawning workers
                        # on a host that is going away
                        self._write_report(preempted)
                        raise preempted from e
                    preemptions += 1
                    if preemptions > self.max_preemptions:
                        # terminal exit: like the failure-budget path, it
                        # must leave a postmortem when report_dir is set
                        self._write_report(preempted)
                        raise RuntimeError(
                            f"elastic run preempted {preemptions} times "
                            f"(max_preemptions={self.max_preemptions})"
                        ) from e
                    log.warning("attempt %d preempted (%s); resuming "
                                "from emergency checkpoint",
                                attempt + 1, preempted)
                elif self._numeric_anomaly(e) is not None:
                    # a tripped numeric guard is a REWIND, not a failure:
                    # the fit body already rewound to a verified
                    # checkpoint and (on data blame) quarantined the
                    # blamed window, so the resume is cheap and the
                    # failure budget stays intact — bounded separately
                    # by max_rewinds
                    anomaly = self._numeric_anomaly(e)
                    self.anomaly_events.append(dict(anomaly.diagnosis))
                    telemetry.emit("rewind", attempt=attempt + 1,
                                   step=anomaly.step, blame=anomaly.blame,
                                   suspect_rank=anomaly.suspect_rank)
                    from .guardian import BLAME_DATA, BLAME_SDC
                    if anomaly.blame == BLAME_DATA \
                            and anomaly.step is not None:
                        if anomaly.step in quarantined_steps:
                            # deterministic: the quarantined window was
                            # skipped and the SAME step still trips —
                            # retrying cannot converge
                            self._write_report(anomaly)
                            raise RuntimeError(
                                f"numeric anomaly at step {anomaly.step} "
                                "recurred after its data window was "
                                "quarantined — not a data fault; "
                                "refusing to rewind again") from e
                        quarantined_steps.add(anomaly.step)
                    rewinds += 1
                    if rewinds > self.max_rewinds:
                        self._write_report(anomaly)
                        raise RuntimeError(
                            f"elastic run tripped the numeric guard "
                            f"{rewinds} times (max_rewinds="
                            f"{self.max_rewinds})") from e
                    if anomaly.blame == BLAME_SDC:
                        self._demote_suspect(anomaly, attempt)
                    log.warning("attempt %d tripped the numeric guard "
                                "(%s); rewinding to the last verified "
                                "checkpoint", attempt + 1, anomaly)
                else:
                    mismatch = self._collective_mismatch(e)
                    if mismatch is not None:
                        # a rank-divergent collective (opt-in sanitizer,
                        # RLA_TPU_SPMD_SANITIZER) is DETERMINISTIC: every
                        # retry would trace the same divergent programs
                        # and hang again — surface the typed postmortem
                        # terminally instead of burning the budget
                        self._write_report(mismatch)
                        raise mismatch from e
                    failures += 1
                    telemetry.emit("elastic_failure",
                                   attempt=attempt + 1,
                                   error=type(e).__name__)
                    if self.on_failure is not None:
                        self.on_failure(attempt, e)
                    self._write_report(e)
                    if failures > self.max_failures:
                        break
            finally:
                if watchdog is not None:
                    watchdog.stop()
                    self.wedge_events.extend(watchdog.reaped)
                    for rec in watchdog.reaped:
                        # wedge-detection wait: the run sat behind a
                        # frozen rank from its last observed progress
                        # to the reap — the stale-beat age when the
                        # channel measured it, else the configured
                        # detection budget
                        self.goodput.account(
                            "wedge_wait",
                            rec.get("beat_age_s")
                            or watchdog.wedge_timeout_s or 0.0)
            attempt += 1
        self.goodput.run_end()
        raise RuntimeError(
            f"elastic run failed after {self.max_failures + 1} attempts"
        ) from last_exc
